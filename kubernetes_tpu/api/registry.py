"""Per-resource REST strategies over the store — the registry layer.

Reference: pkg/registry/* (19,217 LoC of per-resource strategies over one
generic etcd store, pkg/registry/generic/etcd/etcd.go:152-527). Here each
resource is described by a ResourceInfo (kind, scope, field extractor, TTL,
validation/defaulting hooks) and one Registry executes the generic verbs:
create (name generation, uid, timestamps, validation), get, list (label +
field selectors), update, update-status, delete, watch, plus the pod
`binding` subresource with its bind-only-if-unbound CAS
(ref: pkg/registry/pod/etcd/etcd.go:121-189 BindingREST/assignPod).
"""

from __future__ import annotations

import random
import re
import threading
import time
import uuid
from dataclasses import dataclass, replace, field as dc_field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import obs
from ..core import fields as fieldspkg
from ..core import intstr
from ..core import labels as labelspkg
from ..core import types as api
from ..core.errors import (BadRequest, Conflict, Invalid,
                           MethodNotSupported, NotFound)
from ..core.scheme import Scheme, default_scheme
from ..core.store import Store
from ..core.watch import Watcher

DEFAULT_EVENT_TTL = 60 * 60.0  # ref: --event-ttl default 1h (cmd/kube-apiserver)


_DNS1123_LABEL_RE = re.compile(r"[a-z0-9]([-a-z0-9]*[a-z0-9])?")
_DNS1123_SUBDOMAIN_RE = re.compile(
    r"[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*")


# uid generation: uuid4() reads os.urandom per call, which serializes hard
# under concurrent creators (30 writer threads is the reference benchmark
# shape). A per-thread urandom-seeded PRNG keeps uids unique with NO
# shared lock — the r3 profile showed 30 writers spending ~19% of the
# create storm's runnable samples contending one RNG lock
# (PROFILE_e2e.md registry.py:_new_uid).
_uid_local = threading.local()


# Resources whose creation has side effects or verb rewrites beyond the
# plain store write (services allocate IPs/ports, bindings are a verb,
# TPRs mount storage, componentstatuses are computed): batch paths must
# take the per-object create() road for these.
CREATE_SIDE_EFFECT_RESOURCES = ("componentstatuses", "bindings",
                                "services", "thirdpartyresources")
# ...and the template fast path must ALSO route kinds with per-kind
# create defaulting through _prepare_create (namespaces gain the
# kubernetes finalizer there).
TEMPLATE_FALLBACK_RESOURCES = CREATE_SIDE_EFFECT_RESOURCES + ("namespaces",)


def _uid_rng() -> random.Random:
    rng = getattr(_uid_local, "rng", None)
    if rng is None:
        rng = _uid_local.rng = random.Random()  # seeds from os.urandom
    return rng


def _new_uid() -> str:
    bits = _uid_rng().getrandbits(128)
    # format the RFC-4122 v4 shape directly: uuid.UUID's field validation
    # plus __str__ was ~7us per create under the 30-writer benchmark load
    bits = (bits & ~(0xF << 76)) | (0x4 << 76)   # version nibble
    bits = (bits & ~(0x3 << 62)) | (0x2 << 62)   # variant bits
    h = "%032x" % bits
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


def _name_suffix(n: int = 5) -> str:
    return "%0*x" % (n, _uid_rng().getrandbits(4 * n))


def _dns1123(name: str) -> bool:
    """DNS-1123 subdomain (ref: pkg/api/validation IsDNS1123Subdomain)."""
    return 0 < len(name) <= 253 and bool(_DNS1123_SUBDOMAIN_RE.fullmatch(name))


def _dns1123_label(name: str) -> bool:
    """DNS-1123 label: lowercase ASCII alnum + '-', alnum at both ends,
    <=63. Ref: pkg/api/validation ValidateDNS1123Label (volume names)."""
    return 0 < len(name) <= 63 and bool(_DNS1123_LABEL_RE.fullmatch(name))


def validate_object_meta(meta: api.ObjectMeta, namespaced: bool) -> None:
    if not meta.name and not meta.generate_name:
        raise Invalid("metadata.name: required value")
    if meta.name and not _dns1123(meta.name):
        raise Invalid(f"metadata.name: invalid value {meta.name!r}")
    if namespaced and meta.namespace and not _dns1123(meta.namespace):
        raise Invalid(f"metadata.namespace: invalid value {meta.namespace!r}")


def validate_pod(pod: api.Pod) -> None:
    validate_object_meta(pod.metadata, True)
    if not pod.spec.containers:
        raise Invalid("spec.containers: required value")
    names = set()
    for c in pod.spec.containers:
        if not c.name:
            raise Invalid("spec.containers[].name: required value")
        if c.name in names:
            raise Invalid(f"spec.containers[].name: duplicate {c.name!r}")
        names.add(c.name)
    vol_names = set()
    for v in pod.spec.volumes:
        # DNS-1123 label check also forecloses path traversal through the
        # kubelet volume dir layout (ref: validation.go validateVolumes).
        if not _dns1123_label(v.name):
            raise Invalid(f"spec.volumes[].name: invalid value {v.name!r}")
        if v.name in vol_names:
            raise Invalid("spec.volumes[].name: duplicate volume name")
        vol_names.add(v.name)
    # priority is a flat integer (DIVERGENCES #35); bound |p| <= 1e9 so
    # the device's composite victim score stays exact in int64
    prio = pod.spec.priority
    if type(prio) is not int:
        raise Invalid("spec.priority: must be an integer")
    if abs(prio) > 1_000_000_000:
        raise Invalid("spec.priority: must satisfy |priority| <= 1e9")


def validate_node(node: api.Node) -> None:
    validate_object_meta(node.metadata, False)


def validate_service(svc: api.Service) -> None:
    """ref: pkg/api/validation ValidateService — the address-bearing
    spec fields must parse as IPs before a controller hands them to a
    cloud API (an invalid string would otherwise surface as an opaque
    provider error instead of a 422 at admission time)."""
    import ipaddress
    validate_object_meta(svc.metadata, True)
    # explicit JSON nulls decode to None (serde): treat as defaults
    spec = svc.spec or api.ServiceSpec()
    for label, ip in ([("spec.loadBalancerIP",
                        spec.load_balancer_ip or "")]
                      + [("spec.externalIPs", x)
                         for x in (spec.external_ips or [])]):
        if not ip:
            continue
        try:
            # ip_address: strict v4 dotted-quad OR v6, like the
            # reference's net.ParseIP (inet_aton-style "127.1"
            # shorthand is rejected; an IPv6 externalIP is accepted)
            ipaddress.ip_address(ip)
        except (ValueError, TypeError):
            raise Invalid(f"{label}: {ip!r} is not a valid IP address")


def validate_deployment(d: api.Deployment) -> None:
    """ref: pkg/apis/extensions/validation/validation.go
    ValidateRollingUpdateDeployment:258-268 — both bounds must be
    positive ints or percent strings, and maxUnavailable cannot be 0
    when maxSurge is 0 (the rollout could never make progress)."""
    validate_object_meta(d.metadata, True)
    # explicit JSON nulls decode to None (serde); the reference treats
    # a nil strategy/rollingUpdate as defaults (extensions defaults.go)
    spec = d.spec or api.DeploymentSpec()
    strategy = spec.strategy or api.DeploymentStrategy()
    if strategy.type != "RollingUpdate":
        return
    ru = strategy.rolling_update or api.RollingUpdateDeployment()
    vals = {}
    for fld, v in (("maxUnavailable", ru.max_unavailable),
                   ("maxSurge", ru.max_surge)):
        try:
            vals[fld] = intstr.resolve_int_or_percent(v, 100)
        except (ValueError, TypeError):
            raise Invalid(
                f"spec.strategy.rollingUpdate.{fld}: not an int or percent")
        if vals[fld] < 0:
            raise Invalid(
                f"spec.strategy.rollingUpdate.{fld}: must be non-negative")
    if isinstance(ru.max_unavailable, str) \
            and vals["maxUnavailable"] > 100:
        raise Invalid("spec.strategy.rollingUpdate.maxUnavailable: "
                      "cannot be more than 100%")
    if vals["maxUnavailable"] == 0 and vals["maxSurge"] == 0:
        raise Invalid("spec.strategy.rollingUpdate.maxUnavailable: "
                      "cannot be 0 when maxSurge is 0 as well")


@dataclass
class ResourceInfo:
    name: str                      # plural resource name, e.g. "pods"
    kind: str
    cls: type
    namespaced: bool = True
    fields_fn: Callable[[Any], Dict[str, str]] = api.generic_resource_fields
    validate: Optional[Callable[[Any], None]] = None
    ttl: Optional[float] = None    # fixed TTL (events)
    has_status: bool = True


RESOURCES: Dict[str, ResourceInfo] = {}

_FIELD_GETTER_MAPS = {
    api.pod_resource_fields: api.POD_FIELD_GETTERS,
    api.node_resource_fields: api.NODE_FIELD_GETTERS,
    api.event_resource_fields: api.EVENT_FIELD_GETTERS,
    api.generic_resource_fields: api.GENERIC_FIELD_GETTERS,
}


def _compile_field_pred(info: "ResourceInfo", fsel):
    """Direct-attribute matcher for a parsed field selector, or None.

    The dict path (fsel.matches(info.fields_fn(o))) allocates one
    throwaway field map per object-version; the scheduler's watch pair
    (spec.nodeName= / !=) pays that on every event of a 30k-pod commit
    fan-out, and a node-scoped kubelet LIST pays it per stored pod.
    When every term's key has a registered getter the selector compiles
    to attribute reads — same semantics (missing keys read as "" via
    the dict path's .get default only for keys NO getter covers, which
    is exactly when this returns None and the dict path runs)."""
    getters = _FIELD_GETTER_MAPS.get(info.fields_fn)
    if getters is None:
        return None
    try:
        terms = [(getters[k], v, neg) for k, v, neg in fsel.terms]
    except KeyError:
        return None

    def matches(o) -> bool:
        for g, v, neg in terms:
            if (g(o) == v) == neg:
                return False
        return True
    return matches


def field_matcher(info: "ResourceInfo", fsel, fields_of_factory=None):
    """THE field-selector matcher: compiled attribute reads when every
    term has a getter, else the dict path. fields_of_factory (optional)
    supplies a memoized fields_of and is only invoked on the fallback,
    so compiled callers never build the memo. One helper so list(),
    watch(), and the reflector's client-side check cannot drift."""
    m = _compile_field_pred(info, fsel)
    if m is not None:
        return m
    fn = fields_of_factory() if fields_of_factory else info.fields_fn
    return lambda o: fsel.matches(fn(o))


# Per-kind field-label conversion (ref: pkg/api/v1/conversion.go:53-178
# AddFieldLabelConversionFunc — the apiserver rewrites legacy labels,
# e.g. the pre-v1 `spec.host` -> `spec.nodeName`, and rejects labels
# the kind does not support with "field label not supported" before
# the selector reaches storage). Each entry is (aliases, supported);
# kinds without an entry keep the permissive pass-through the generic
# metadata fields provide. The supported sets mirror the reference's
# switch arms exactly — including labels the conversion accepts but
# the selectable-fields set never populates (a pod selector on
# `metadata.labels` converts fine and then matches nothing, both
# there and here).
_FIELD_LABEL_CONVERSIONS: Dict[str, Tuple[Dict[str, str], frozenset]] = {
    "pods": ({"spec.host": "spec.nodeName"},
             frozenset({"metadata.name", "metadata.namespace",
                        "metadata.labels", "metadata.annotations",
                        "status.phase", "status.podIP", "spec.nodeName"})),
    "nodes": ({}, frozenset({"metadata.name", "spec.unschedulable"})),
    "replicationcontrollers": ({}, frozenset({"metadata.name",
                                              "status.replicas"})),
    # events: the reference's switch arm plus the ObjectMeta pair its
    # selectable set (event/strategy.go getAttrs ObjectMetaFieldsSet)
    # exposes — rejecting metadata.name would dead-end a selector the
    # storage layer can serve
    "events": ({}, frozenset({
        "metadata.name", "metadata.namespace",
        "involvedObject.kind", "involvedObject.namespace",
        "involvedObject.name", "involvedObject.uid",
        "involvedObject.apiVersion", "involvedObject.resourceVersion",
        "involvedObject.fieldPath", "reason", "source", "type"})),
    "namespaces": ({}, frozenset({"status.phase"})),
    "secrets": ({}, frozenset({"type"})),
    "serviceaccounts": ({}, frozenset({"metadata.name"})),
    "endpoints": ({}, frozenset({"metadata.name"})),
}


def convert_field_selector(resource: str,
                           fsel: fieldspkg.FieldSelector
                           ) -> fieldspkg.FieldSelector:
    """Apply the kind's field-label conversion to a parsed selector:
    legacy labels rewrite, unsupported labels raise BadRequest (the
    reference's conversion error surfaces as a 400 from the selector
    query parsing, pkg/apiserver/resthandler.go)."""
    conv = _FIELD_LABEL_CONVERSIONS.get(resource)
    if conv is None:
        return fsel
    aliases, supported = conv
    terms = []
    changed = False
    for k, v, neg in fsel.terms:
        nk = aliases.get(k, k)
        if nk not in supported:
            raise BadRequest(f"field label not supported: {k}")
        changed = changed or nk != k
        terms.append((nk, v, neg))
    return fieldspkg.FieldSelector(tuple(terms)) if changed else fsel


def _register(info: ResourceInfo) -> None:
    RESOURCES[info.name] = info


_register(ResourceInfo("pods", "Pod", api.Pod, True, api.pod_resource_fields,
                       validate_pod))
_register(ResourceInfo("nodes", "Node", api.Node, False,
                       api.node_resource_fields, validate_node))
_register(ResourceInfo("services", "Service", api.Service, True,
                       validate=validate_service))
_register(ResourceInfo("endpoints", "Endpoints", api.Endpoints, True,
                       has_status=False))
_register(ResourceInfo("replicationcontrollers", "ReplicationController",
                       api.ReplicationController, True))
_register(ResourceInfo("events", "Event", api.Event, True,
                       api.event_resource_fields,
                       ttl=DEFAULT_EVENT_TTL, has_status=False))
_register(ResourceInfo("namespaces", "Namespace", api.Namespace, False))
_register(ResourceInfo("secrets", "Secret", api.Secret, True, has_status=False))
_register(ResourceInfo("limitranges", "LimitRange", api.LimitRange, True,
                       has_status=False))
_register(ResourceInfo("resourcequotas", "ResourceQuota", api.ResourceQuota, True))
_register(ResourceInfo("serviceaccounts", "ServiceAccount", api.ServiceAccount,
                       True, has_status=False))
_register(ResourceInfo("persistentvolumes", "PersistentVolume",
                       api.PersistentVolume, False))
_register(ResourceInfo("persistentvolumeclaims", "PersistentVolumeClaim",
                       api.PersistentVolumeClaim, True))
_register(ResourceInfo("podtemplates", "PodTemplate", api.PodTemplate,
                       True, has_status=False))
# read-only, computed per request from component health probes
# (ref: pkg/registry/componentstatus — scheduler :10251, controller-
# manager :10252, etcd; master.go getServersToValidate)
_register(ResourceInfo("componentstatuses", "ComponentStatus",
                       api.ComponentStatus, False, has_status=False))
# extensions/v1beta1 group (ref: pkg/registry/{job,deployment,daemonset,
# horizontalpodautoscaler,ingress}; mounted master.go:1049-1091 — served
# under /apis/extensions/v1beta1 by the API server)
EXTENSIONS_RESOURCES = ("jobs", "deployments", "daemonsets",
                        "horizontalpodautoscalers", "ingresses",
                        "thirdpartyresources")


def validate_third_party_resource(tpr: api.ThirdPartyResource) -> None:
    """(ref: validation.ValidateThirdPartyResource + util.go
    ExtractApiGroupAndKind: name must be <kind>.<domain>.<tld>)"""
    validate_object_meta(tpr.metadata, True)
    if len(tpr.metadata.name.split(".")) < 3:
        raise Invalid(
            f"metadata.name: {tpr.metadata.name!r} must be "
            f"<kind>.<domain>.<tld>")
    if not tpr.versions:
        raise Invalid("versions: at least one version is required")
    seen = set()
    for v in tpr.versions:
        if not v.name:
            raise Invalid("versions[].name: required value")
        if v.name in seen:
            raise Invalid(f"versions[].name: duplicate {v.name!r}")
        seen.add(v.name)


def extract_group_and_kind(tpr: api.ThirdPartyResource):
    """-> (kind, group, plural) from `<kind-dashed>.<domain>...`
    (ref: thirdpartyresourcedata/util.go ExtractApiGroupAndKind)."""
    parts = tpr.metadata.name.split(".")
    kind = "".join(p[:1].upper() + p[1:] for p in parts[0].split("-"))
    group = ".".join(parts[1:])
    plural = parts[0].replace("-", "") + "s"
    return kind, group, plural


def encode_third_party(obj: api.ThirdPartyResourceData, kind: str,
                       group_version: str) -> dict:
    """The raw custom document back out (the reference stores the whole
    JSON and re-serves it)."""
    wire = dict(obj.data)
    wire["kind"] = kind
    wire["apiVersion"] = group_version
    meta = {"name": obj.metadata.name, "namespace": obj.metadata.namespace,
            "uid": obj.metadata.uid,
            "resourceVersion": obj.metadata.resource_version,
            "creationTimestamp": obj.metadata.creation_timestamp}
    if obj.metadata.labels:
        meta["labels"] = dict(obj.metadata.labels)
    if obj.metadata.annotations:
        meta["annotations"] = dict(obj.metadata.annotations)
    wire["metadata"] = meta
    return wire


def decode_third_party(data: dict) -> api.ThirdPartyResourceData:
    meta = data.get("metadata") or {}
    return api.ThirdPartyResourceData(
        metadata=api.ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            uid=meta.get("uid", ""),
            resource_version=meta.get("resourceVersion", ""),
            creation_timestamp=meta.get("creationTimestamp", ""),
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {})),
        data={k: v for k, v in data.items()
              if k not in ("kind", "apiVersion", "metadata")})
_register(ResourceInfo("jobs", "Job", api.Job, True))
_register(ResourceInfo("deployments", "Deployment", api.Deployment, True,
                       validate=validate_deployment))
_register(ResourceInfo("daemonsets", "DaemonSet", api.DaemonSet, True))
_register(ResourceInfo("horizontalpodautoscalers", "HorizontalPodAutoscaler",
                       api.HorizontalPodAutoscaler, True))
_register(ResourceInfo("ingresses", "Ingress", api.Ingress, True))
_register(ResourceInfo("thirdpartyresources", "ThirdPartyResource",
                       api.ThirdPartyResource, True,
                       validate=validate_third_party_resource,
                       has_status=False))
# Virtual resource: POST /bindings assigns a pod to a node (no storage of its
# own; ref: pkg/registry/pod/etcd BindingREST).
_register(ResourceInfo("bindings", "Binding", api.Binding, True,
                       has_status=False))
# coordination/leases: the CAS-renewed leader-election record
# (utils/leaderelection.py). Forward-ported from the reference's master
# election seam into the typed Lease the later reference grew; served
# flat under api/v1 rather than a coordination.k8s.io group (the server
# mounts one registry — DIVERGENCES.md #25). Every PUT carries the
# elector's observed resourceVersion, so acquisition races resolve to
# exactly one winner per fencing term at the store's CAS.
_register(ResourceInfo("leases", "Lease", api.Lease, True,
                       has_status=False))


class Registry:
    """Generic REST verbs for every registered resource over one Store."""

    def __init__(self, store: Optional[Store] = None,
                 scheme: Scheme = default_scheme,
                 admission: Optional[
                     Callable[[str, str, Any, str, str], Any]] = None,
                 service_cidr: str = "10.0.0.0/24",
                 txn_commit: bool = True):
        self.store = store or Store()
        self.scheme = scheme
        # multi-key ledger transactions for the batched bind/status
        # verbs: one revision window + one WAL frame + one publish
        # batch per call (store.commit_txn) instead of one store.batch
        # window per caller-side chunk. txn_commit=False keeps the
        # per-chunk batch() path as the A/B control arm
        # (bench.py --txn-ab); stores without the verb degrade to it.
        self._txn_commit = txn_commit and hasattr(self.store, "commit_txn")
        # per-resource field-map memo shared by this registry's filtered
        # watch predicates (see watch()); entries are transient and
        # bounded by periodic clear
        self._fields_memo: Dict[str, dict] = {}
        # admission(operation, resource, obj, namespace, name) -> obj;
        # raises to reject (ref: pkg/admission chain invoked from
        # resthandler createHandler). Set after construction when plugins
        # need the registry itself (admission.new_from_plugins).
        self.admission = admission
        # service cluster-IP + node-port allocators (ref:
        # pkg/registry/service ipallocator/portallocator); repaired from
        # the store so a registry over pre-existing state stays coherent
        # componentstatus probes (ref: master.go getServersToValidate —
        # the store plays etcd-0; Master adds scheduler/controller-
        # manager probes at their conventional ports)
        self.component_probes: Dict[str, Callable] = {
            "etcd-0": lambda: (
                True, f"revision {self.store.current_revision}")}
        from .allocators import IPAllocator, PortAllocator
        self.ip_allocator = IPAllocator(service_cidr)
        self.port_allocator = PortAllocator()
        for svc in self.store.list(self.prefix("services"))[0]:
            if svc.spec.cluster_ip and svc.spec.cluster_ip != "None":
                try:
                    self.ip_allocator.allocate_specific(svc.spec.cluster_ip)
                except Invalid:
                    pass
            for port in svc.spec.ports:
                if port.node_port:
                    try:
                        self.port_allocator.allocate_specific(port.node_port)
                    except Invalid:
                        pass

    def _store_batch(self, ops) -> List[Any]:
        """Route one batched multi-key write: the txn verb when enabled
        (whole op list in one revision window) or the classic batch()
        (same semantics, per-record WAL frames) as the control arm."""
        if self._txn_commit:
            return self.store.commit_txn(ops)
        return self.store.batch(ops)

    # ------------------------------------------------------------- keys

    @staticmethod
    def info(resource: str) -> ResourceInfo:
        try:
            return RESOURCES[resource]
        except KeyError:
            raise NotFound(f'the server could not find resource "{resource}"')

    @staticmethod
    def key(resource: str, namespace: str, name: str) -> str:
        return f"/registry/{resource}/{namespace}/{name}"

    @staticmethod
    def prefix(resource: str, namespace: str = "") -> str:
        if namespace:
            return f"/registry/{resource}/{namespace}/"
        return f"/registry/{resource}/"

    def _namespace_for(self, info: ResourceInfo, obj: Any,
                       namespace: str) -> str:
        if not info.namespaced:
            return ""
        ns = obj.metadata.namespace or namespace or "default"
        if namespace and obj.metadata.namespace and namespace != obj.metadata.namespace:
            raise BadRequest(
                f"namespace in URL ({namespace}) differs from object "
                f"({obj.metadata.namespace})")
        return ns

    # ------------------------------------------------------------ verbs

    def create(self, resource: str, obj: Any, namespace: str = "") -> Any:
        if resource == "componentstatuses":
            raise MethodNotSupported("componentstatuses is read-only")
        if resource == "bindings":
            return self.bind(obj, namespace)
        if resource == "thirdpartyresources":
            # two TPRs must never map to one (group, plural) — they'd
            # silently share a storage prefix and the first one's Kind
            _, new_group, new_plural = extract_group_and_kind(obj)
            existing = self.third_party_groups().get(new_group, {})
            if new_plural in existing:
                raise Conflict(
                    f"a ThirdPartyResource already serves "
                    f"{new_group}/{new_plural}")
        info = self.info(resource)
        ns, name, obj = self._prepare_create(info, resource, obj, namespace)
        if resource == "services":
            obj, allocated_ip, allocated_ports = self._service_allocate(obj)
            try:
                return self.store.create(self.key(resource, ns, name), obj,
                                         ttl=info.ttl)
            except Exception:
                # roll the allocations back (ref: service REST releases on
                # failed create)
                if allocated_ip:
                    self.ip_allocator.release(allocated_ip)
                for port in allocated_ports:
                    self.port_allocator.release(port)
                raise
        if resource == "pods":
            # the "create" stage of the pod lifecycle model: the
            # server-side store commit (utils/metrics.OBS_STAGES)
            with obs.tracer().span("registry.create", stage="create"):
                return self.store.create(self.key(resource, ns, name),
                                         obj, ttl=info.ttl)
        return self.store.create(self.key(resource, ns, name), obj, ttl=info.ttl)

    def _prepare_create(self, info: "ResourceInfo", resource: str, obj: Any,
                        namespace: str) -> Tuple[str, str, Any]:
        """Everything create() does to one object before the store write:
        type check, namespace resolution, name generation, uid/timestamp
        stamping, per-kind defaulting, validation, admission.
        -> (namespace, name, prepared object)."""
        if not isinstance(obj, info.cls):
            raise BadRequest(f"expected {info.kind}, got {type(obj).__name__}")
        ns = self._namespace_for(info, obj, namespace)
        meta = obj.metadata
        name = meta.name
        if not name and meta.generate_name:
            # ref: pkg/api/rest names.SimpleNameGenerator (5 random chars)
            name = meta.generate_name + _name_suffix(5)
        # create-time trace context rides the object as an annotation:
        # through the store, the WAL, every watch replay/live delivery
        # and every wire serialization — how the scheduler's informer
        # links a tile back to the create that fed it (obs layer). A
        # client-stamped annotation wins (cross-process creates where
        # the caller owns the root span).
        annotations = meta.annotations
        ctx = obs.current()
        if ctx is not None and obs.tracer().enabled \
                and obs.TRACEPARENT_ANNOTATION not in annotations:
            annotations = {**annotations,
                           obs.TRACEPARENT_ANNOTATION:
                           obs.format_traceparent(ctx)}
        meta = api.fast_replace(
            meta, name=name, namespace=ns,
            uid=meta.uid or _new_uid(),
            creation_timestamp=meta.creation_timestamp or api.now_rfc3339(),
            annotations=annotations,
            resource_version="")
        obj = api.fast_replace(obj, metadata=meta)
        if resource == "namespaces" and not obj.spec.finalizers:
            # every namespace carries the kubernetes finalizer so deletion
            # is two-phase (ref: pkg/registry/namespace/strategy.go
            # PrepareForCreate)
            obj = replace(obj, spec=replace(obj.spec,
                                            finalizers=["kubernetes"]))
        if info.validate:
            info.validate(obj)
        if self.admission:
            obj = self.admission("CREATE", resource, obj, ns, name)
        return ns, name, obj

    def create_batch(self, resource: str, objs: List[Any],
                     namespace: str = "") -> List[Any]:
        """Create many objects of one resource in a single store pass:
        one lock window, one watch fan-out flush (the write-side
        analogue of bind_batch — SURVEY.md section 7 hard part 2's
        create storm). Per-object preparation (validation, admission,
        name generation) is byte-identical to create(). Resources with
        create-time side effects outside the store (services' IP/port
        allocators, bindings, TPR mounting) fall back to the serial
        path object-by-object."""
        if resource in CREATE_SIDE_EFFECT_RESOURCES:
            return [self.create(resource, o, namespace) for o in objs]
        info = self.info(resource)
        entries = []
        for obj in objs:
            ns, name, prepared = self._prepare_create(
                info, resource, obj, namespace)
            entries.append((self.key(resource, ns, name), prepared, info.ttl))
        # _prepare_create fresh-builds both the object and its metadata
        # (fast_replace x2) and admission plugins only ever swap
        # spec/status around that fresh metadata, so the store may
        # stamp the revision in place instead of re-cloning both per
        # object (the clone pair was most of the create storm's work
        # under the store lock, PROFILE_e2e.md)
        if resource == "pods":
            with obs.tracer().span("registry.create_batch", stage="create",
                                   attrs={"pods": len(entries)}):
                return self.store.create_batch(entries, owned_meta=True)
        return self.store.create_batch(entries, owned_meta=True)

    def create_from_template(self, resource: str, template: Any,
                             names: List[str], namespace: str = ""
                             ) -> List[Any]:
        """Columnar bulk create — the host half of the array-first
        design (SURVEY.md section 7 hard part 3; PROFILE_e2e.md's
        ~80us/pod interpreter floor). One validation pass on the
        template, then per name only a fresh ObjectMeta (name, uid,
        shared timestamp) around the template's spec/status, which the
        created objects SHARE. Sharing is safe under the framework's
        replace-don't-mutate contract: every write path (store rv
        stamping, binding assignment, status updates) clones via
        fast_replace and the store's owned_meta stamping touches only
        the per-object fresh metadata.

        Falls back to the per-object create path when admission chains
        or create-time side effects (services' allocators, TPRs) need
        to see each object individually."""
        info = self.info(resource)
        if self.admission or resource in TEMPLATE_FALLBACK_RESOURCES:
            return self.create_batch(
                resource, api.expand_template_rows(template, names),
                namespace)
        if not names:
            return []
        if not isinstance(template, info.cls):
            raise BadRequest(
                f"expected {info.kind}, got {type(template).__name__}")
        ns = self._namespace_for(info, template, namespace)
        ts = api.now_rfc3339()
        tm = template.metadata
        # same traceparent stamping as _prepare_create, once for the
        # whole batch: the rows share the creating span's context (one
        # logical create storm, one trace exemplar per template)
        ctx = obs.current()
        if ctx is not None and obs.tracer().enabled \
                and obs.TRACEPARENT_ANNOTATION not in tm.annotations:
            tm = api.fast_replace(
                tm, annotations={**tm.annotations,
                                 obs.TRACEPARENT_ANNOTATION:
                                 obs.format_traceparent(ctx)})
        # template-wide validation once, against a representative row
        rep = api.fast_replace(
            template, metadata=api.fast_replace(
                tm, name=names[0], namespace=ns, uid="template",
                creation_timestamp=ts, resource_version=""))
        if info.validate:
            info.validate(rep)
        # one RFC-4122-shaped random base, consecutive uids off it: the
        # per-row cost is one hex format instead of a fresh getrandbits
        base = _uid_rng().getrandbits(128)
        key_prefix = self.key(resource, ns, "")
        entries = []
        fr = api.fast_replace
        for i, name in enumerate(names):
            if not _dns1123(name):
                raise Invalid(f"metadata.name: invalid value {name!r}")
            bits = base + i
            bits = (bits & ~(0xF << 76)) | (0x4 << 76)
            bits = (bits & ~(0x3 << 62)) | (0x2 << 62)
            h = "%032x" % bits
            meta = fr(tm, name=name, namespace=ns,
                      uid=f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}",
                      creation_timestamp=ts, resource_version="")
            entries.append((key_prefix + name, fr(template, metadata=meta),
                            info.ttl))
        if resource == "pods":
            with obs.tracer().span("registry.create_from_template",
                                   stage="create",
                                   attrs={"pods": len(entries)}):
                return self.store.create_batch(entries, owned_meta=True)
        return self.store.create_batch(entries, owned_meta=True)

    def _service_allocate(self, obj: api.Service):
        """Assign cluster IP + node ports (ref: pkg/registry/service
        rest.go Create: headless "None" skips IP; explicit requests are
        honored or rejected; NodePort/LoadBalancer types get node ports)."""
        # an explicit JSON-null spec decodes to None: normalize to
        # defaults so the allocator (and every later reader of the
        # STORED object) sees a real ServiceSpec
        spec = obj.spec or api.ServiceSpec()
        allocated_ip = ""
        if spec.cluster_ip != "None":
            if spec.cluster_ip:
                self.ip_allocator.allocate_specific(spec.cluster_ip)
                allocated_ip = spec.cluster_ip
            else:
                allocated_ip = self.ip_allocator.allocate()
                spec = replace(spec, cluster_ip=allocated_ip)
        allocated_ports = []
        if spec.type in ("NodePort", "LoadBalancer"):
            try:
                ports = []
                for port in spec.ports:
                    if port.node_port:
                        self.port_allocator.allocate_specific(port.node_port)
                        allocated_ports.append(port.node_port)
                        ports.append(port)
                    else:
                        node_port = self.port_allocator.allocate()
                        allocated_ports.append(node_port)
                        ports.append(replace(port, node_port=node_port))
                spec = replace(spec, ports=ports)
            except Exception:
                if allocated_ip:
                    self.ip_allocator.release(allocated_ip)
                for port in allocated_ports:
                    self.port_allocator.release(port)
                raise
        return replace(obj, spec=spec), allocated_ip, allocated_ports

    def _service_update_ports(self, current: api.Service, obj: api.Service):
        """Reconcile node-port allocations on update: newly requested
        ports are claimed (or assigned when 0 on a NodePort service);
        ports the update drops are returned for release AFTER the store
        write lands (a failed write must leave the allocator matching
        storage). -> (obj, claimed, to_release_on_success)."""
        old_ports = {p.node_port for p in current.spec.ports if p.node_port}
        spec = obj.spec
        wants_node_ports = spec.type in ("NodePort", "LoadBalancer")
        claimed = []
        try:
            ports = []
            for port in spec.ports:
                if port.node_port and not wants_node_ports:
                    # type changed to ClusterIP: strip the node port so the
                    # stale allocation is released below (ref: service REST
                    # releases node ports when the type drops them).
                    ports.append(replace(port, node_port=0))
                elif port.node_port:
                    if port.node_port not in old_ports:
                        self.port_allocator.allocate_specific(port.node_port)
                        claimed.append(port.node_port)
                    ports.append(port)
                elif wants_node_ports:
                    node_port = self.port_allocator.allocate()
                    claimed.append(node_port)
                    ports.append(replace(port, node_port=node_port))
                else:
                    ports.append(port)
        except Exception:
            for port in claimed:
                self.port_allocator.release(port)
            raise
        new_ports = {p.node_port for p in ports if p.node_port}
        return (replace(obj, spec=replace(spec, ports=ports)), claimed,
                sorted(old_ports - new_ports))

    def _service_release(self, obj: api.Service) -> None:
        if obj.spec.cluster_ip and obj.spec.cluster_ip != "None":
            self.ip_allocator.release(obj.spec.cluster_ip)
        for port in obj.spec.ports:
            if port.node_port:
                self.port_allocator.release(port.node_port)

    def get(self, resource: str, name: str, namespace: str = "") -> Any:
        if resource == "componentstatuses":
            if name not in self.component_probes:
                raise NotFound(kind=resource, name=name)
            # only the requested component is probed — a down scheduler
            # must not slow a GET of etcd-0
            return self._component_statuses([name])[0]
        info = self.info(resource)
        # cluster-scoped resources ignore a caller-supplied namespace
        # (the CLI defaults one for every request; HttpClient._url
        # drops it, the in-proc path must too)
        ns = (namespace or "default") if info.namespaced else ""
        try:
            return self.store.get(self.key(resource, ns, name))
        except NotFound:
            raise NotFound(kind=resource, name=name)

    def _component_statuses(self, names: Optional[List[str]] = None
                            ) -> List[api.ComponentStatus]:
        """Computed per request from the registered probes, fanned out
        in parallel — one slow/down component costs one timeout, not a
        sum (ref: pkg/registry/componentstatus REST.List ->
        validator.Server, probed concurrently)."""
        from concurrent.futures import ThreadPoolExecutor

        wanted = sorted(names if names is not None
                        else self.component_probes)

        def run_probe(name):
            try:
                return self.component_probes[name]()
            except Exception as e:
                return False, repr(e)

        with ThreadPoolExecutor(max_workers=max(1, len(wanted))) as pool:
            results = list(pool.map(run_probe, wanted))
        return [api.ComponentStatus(
            metadata=api.ObjectMeta(name=name),
            conditions=[api.ComponentCondition(
                type="Healthy",
                status="True" if ok else "False",
                message=message if ok else "",
                error="" if ok else message)])
            for name, (ok, message) in zip(wanted, results)]

    def add_component_probe(self, name: str, probe) -> None:
        """probe() -> (healthy: bool, message: str)."""
        self.component_probes[name] = probe

    def list(self, resource: str, namespace: str = "",
             label_selector: str = "", field_selector: str = ""
             ) -> Tuple[List[Any], int]:
        info = self.info(resource)
        if not info.namespaced:
            namespace = ""  # cluster-scoped: a defaulted ns must not filter
        lsel = labelspkg.parse(label_selector) if label_selector else None
        fsel = fieldspkg.parse(field_selector) if field_selector else None
        if fsel is not None:
            fsel = convert_field_selector(resource, fsel)

        fmatch = field_matcher(info, fsel) if fsel is not None else None

        def pred(o: Any) -> bool:
            if lsel is not None and not lsel.matches(o.metadata.labels):
                return False
            if fmatch is not None and not fmatch(o):
                return False
            return True

        use_pred = pred if (lsel is not None or fsel is not None) else None
        if resource == "componentstatuses":
            statuses = self._component_statuses()
            if use_pred is not None:
                statuses = [s for s in statuses if pred(s)]
            return statuses, self.store.current_revision
        return self.store.list(self.prefix(resource, namespace), use_pred)

    def update(self, resource: str, obj: Any, namespace: str = "") -> Any:
        if resource == "componentstatuses":
            raise MethodNotSupported("componentstatuses is read-only")
        info = self.info(resource)
        ns = self._namespace_for(info, obj, namespace)
        if not obj.metadata.name:
            raise Invalid("metadata.name: required value")
        if resource == "namespaces":
            # finalizers/deletionTimestamp only move via DELETE and the
            # finalize subresource (ref: pkg/registry/namespace/strategy.go
            # PrepareForUpdate pins them on regular updates)
            current = self.store.get(self.key(resource, "", obj.metadata.name))
            obj = replace(
                obj,
                metadata=replace(obj.metadata,
                                 deletion_timestamp=(
                                     current.metadata.deletion_timestamp)),
                spec=replace(obj.spec,
                             finalizers=list(current.spec.finalizers)))
        if resource == "services":
            # clusterIP is immutable once assigned (ref:
            # pkg/registry/service/rest.go Update + api validation)
            current = self.store.get(self.key(resource, ns,
                                              obj.metadata.name))
            if not obj.spec.cluster_ip:
                obj = replace(obj, spec=replace(
                    obj.spec, cluster_ip=current.spec.cluster_ip))
            elif obj.spec.cluster_ip != current.spec.cluster_ip:
                raise Invalid("spec.clusterIP: field is immutable")
            obj, svc_claimed, svc_to_release = \
                self._service_update_ports(current, obj)
        else:
            svc_claimed, svc_to_release = [], []
        if info.validate:
            info.validate(obj)
        try:
            if self.admission:
                obj = self.admission("UPDATE", resource, obj, ns,
                                     obj.metadata.name)
            key = self.key(resource, ns, obj.metadata.name)
            if not obj.metadata.resource_version:
                # Unconditional update requires the object to exist
                # (PUT never creates in the reference's generic store).
                # One atomic read-modify-write: a get-then-set pair
                # would let a concurrent DELETE land between the two
                # lock acquisitions and the set would RESURRECT the
                # deleted object as a fresh ADDED event
                result = self.store.guaranteed_update(
                    key, lambda cur: obj, ttl=info.ttl)
            else:
                result = self.store.update(key, obj)
        except Exception:
            # the write never landed: newly claimed ports go back, dropped
            # ones stay owned by the stored object
            for port in svc_claimed:
                self.port_allocator.release(port)
            raise
        for port in svc_to_release:
            self.port_allocator.release(port)
        return result

    # Resources serving the scale subresource and how a Scale projects
    # onto them (ref: registry/experimental/controller/etcd/etcd.go
    # ScaleREST for replicationcontrollers, registry/deployment/etcd
    # for deployments).
    SCALABLE = ("replicationcontrollers", "deployments")

    @staticmethod
    def _project_scale(obj: Any) -> api.Scale:
        """RC/Deployment -> its Scale projection (shared by GET and the
        post-update read-back so the two cannot drift)."""
        return api.Scale(
            metadata=api.ObjectMeta(
                name=obj.metadata.name, namespace=obj.metadata.namespace,
                resource_version=obj.metadata.resource_version,
                creation_timestamp=obj.metadata.creation_timestamp),
            spec=api.ScaleSpec(replicas=obj.spec.replicas),
            status=api.ScaleStatus(replicas=obj.status.replicas,
                                   selector=dict(obj.spec.selector)))

    def get_scale(self, resource: str, name: str,
                  namespace: str = "") -> api.Scale:
        if resource not in self.SCALABLE:
            raise NotFound(f"{resource} has no scale subresource")
        return self._project_scale(self.get(resource, name, namespace))

    def update_scale(self, resource: str, name: str, scale: api.Scale,
                     namespace: str = "") -> api.Scale:
        """PUT .../{name}/scale: move ONLY spec.replicas, optimistic on
        the Scale's resourceVersion when it carries one (the reference's
        ScaleREST.Update runs the generic GuaranteedUpdate)."""
        if resource not in self.SCALABLE:
            raise NotFound(f"{resource} has no scale subresource")
        ns = namespace or "default"
        key = self.key(resource, ns, name)
        want = scale.spec.replicas
        if want < 0:
            raise Invalid("spec.replicas: must be non-negative")
        expect_rv = scale.metadata.resource_version

        def apply(cur: Any) -> Any:
            if expect_rv and cur.metadata.resource_version != expect_rv:
                raise Conflict(
                    f"scale update on {key} failed: object was modified "
                    f"(have {expect_rv}, current "
                    f"{cur.metadata.resource_version})")
            return replace(cur, spec=replace(cur.spec, replicas=want))

        return self._project_scale(self.store.guaranteed_update(key, apply))

    # Content types the PATCH verb accepts (ref: pkg/api/types.go:2065
    # PatchType; resthandler.go patchResource dispatches on them)
    PATCH_STRATEGIC = "application/strategic-merge-patch+json"
    PATCH_MERGE = "application/merge-patch+json"
    PATCH_JSON = "application/json-patch+json"

    def patch(self, resource: str, name: str, patch_body: Any,
              namespace: str = "",
              patch_type: str = PATCH_STRATEGIC) -> Any:
        """Server-side PATCH (ref: pkg/apiserver/resthandler.go
        patchResource): read the live object, apply the patch in wire
        space per content type, decode, and PUT — retrying the
        read-apply-write loop on optimistic-concurrency conflicts the
        way the reference's patch handler re-applies against a fresh
        read. The merged document carries the read's resourceVersion,
        so a racing writer surfaces as Conflict, never a lost update."""
        from ..utils.strategicpatch import (apply_json_patch,
                                            json_merge_patch,
                                            strategic_patch)
        info = self.info(resource)
        ns = (namespace or "default") if info.namespaced else ""
        last: Optional[Conflict] = None
        for _ in range(5):
            current = self.get(resource, name, ns)
            wire = self.scheme.encode_dict(current)
            if patch_type == self.PATCH_JSON:
                if not isinstance(patch_body, list):
                    raise BadRequest("json-patch body must be a list "
                                     "of operations")
                try:
                    merged = apply_json_patch(wire, patch_body)
                except (ValueError, KeyError, IndexError,
                        TypeError) as e:
                    raise BadRequest(f"json patch failed: {e}")
            elif patch_type == self.PATCH_MERGE:
                if not isinstance(patch_body, dict):
                    raise BadRequest("merge-patch body must be an object")
                merged = json_merge_patch(wire, patch_body)
            elif patch_type == self.PATCH_STRATEGIC:
                if not isinstance(patch_body, dict):
                    raise BadRequest(
                        "strategic-merge-patch body must be an object "
                        "(json-patch op arrays need the "
                        "application/json-patch+json content type)")
                try:
                    merged = strategic_patch(wire, patch_body)
                except ValueError as e:
                    # unknown $patch directive (patch.go's "Unknown
                    # patch type" surfaces as a 400)
                    raise BadRequest(f"strategic merge patch failed: {e}")
            else:
                raise BadRequest(
                    f"unsupported patch content type {patch_type!r}")
            if not isinstance(merged, dict):
                raise BadRequest("patch must produce an object")
            # identity is immutable under PATCH; the read's rv rides
            # along for the CAS unless the patch pinned one itself
            merged.setdefault("kind", wire.get("kind"))
            merged.setdefault("apiVersion", wire.get("apiVersion"))
            meta = merged.setdefault("metadata", {})
            if not isinstance(meta, dict):
                raise BadRequest("patch produced a non-object metadata")
            meta["name"] = current.metadata.name
            meta.setdefault("resourceVersion",
                            current.metadata.resource_version)
            obj = self.scheme.decode_dict(merged)
            try:
                return self.update(resource, obj, ns)
            except Conflict as e:
                last = e
                continue
        raise last if last is not None else Conflict(
            f"patch on {resource}/{name} could not land")

    def update_status(self, resource: str, obj: Any, namespace: str = "") -> Any:
        """Status subresource: replace only .status, keep spec/meta
        (ref: pkg/registry/pod/etcd statusStrategy)."""
        info = self.info(resource)
        if not info.has_status:
            raise BadRequest(f"{resource} has no status subresource")
        ns = self._namespace_for(info, obj, namespace)
        key = self.key(resource, ns, obj.metadata.name)
        new_status = obj.status
        expect_rv = obj.metadata.resource_version

        def apply(cur: Any) -> Any:
            # optimistic concurrency like every reference status write
            # (statusStrategy through the generic etcd update,
            # etcd.go:270-316): a writer carrying a stale rv must 409
            # and re-read, not silently resurrect what it saw before —
            # e.g. a delayed kubelet heartbeat overwriting the node
            # controller's Ready=Unknown with pre-outage conditions.
            # rv-less writes stay unconditional (the in-proc callers'
            # documented contract).
            if expect_rv and cur.metadata.resource_version != expect_rv:
                raise Conflict(
                    f"status update on {key} failed: object was "
                    f"modified (have {expect_rv}, current "
                    f"{cur.metadata.resource_version})")
            return replace(cur, status=new_status)

        return self.store.guaranteed_update(key, apply)

    def update_status_batch(self, resource: str, objs: List[Any],
                            namespace: str = "") -> List[Any]:
        """Many status writes in ONE store pass (single lock, batched
        watch fan-out; one revision window when the store's txn verb is
        routed — see _store_batch). The hollow fleet confirms a whole
        tile of pods Running this way; semantics per object match
        update_status. The batch is all-or-nothing — callers that need
        per-object NotFound tolerance catch and degrade to singles."""
        info = self.info(resource)
        if not info.has_status:
            raise BadRequest(f"{resource} has no status subresource")
        ops = []
        for obj in objs:
            ns = self._namespace_for(info, obj, namespace)

            def set_status(cur, rv="", s=obj.status,
                           expect=obj.metadata.resource_version):
                if expect and cur.metadata.resource_version != expect:
                    # same optimistic-concurrency contract as the
                    # single update_status above
                    raise Conflict(
                        f"status update failed: object was modified "
                        f"(have {expect}, current "
                        f"{cur.metadata.resource_version})")
                if rv:
                    return api.fast_replace(
                        cur, status=s, metadata=api.fast_replace(
                            cur.metadata, resource_version=rv))
                return replace(cur, status=s)

            set_status.wants_rv = True
            ops.append((self.key(resource, ns, obj.metadata.name),
                        set_status))
        return self._store_batch(ops)

    def guaranteed_update(self, resource: str, name: str, namespace: str,
                          fn) -> Any:
        """Retry-on-conflict read-modify-write through the store
        (GuaranteedUpdate semantics, etcd_helper.go:449), for callers that
        must be atomic against concurrent writers (quota admission)."""
        info = self.info(resource)
        ns = (namespace or "default") if info.namespaced else ""
        return self.store.guaranteed_update(self.key(resource, ns, name), fn)

    def delete(self, resource: str, name: str, namespace: str = "",
               grace_period_seconds: Optional[int] = None,
               uid: Optional[str] = None) -> Any:
        if resource == "componentstatuses":
            raise MethodNotSupported("componentstatuses is read-only")
        info = self.info(resource)
        ns = (namespace or "default") if info.namespaced else ""
        if self.admission:
            self.admission("DELETE", resource, None, ns, name)
        if resource == "namespaces":
            return self._delete_namespace(name)
        if resource == "pods":
            graceful = self._pod_graceful_delete(ns, name,
                                                 grace_period_seconds, uid)
            if graceful is not None:
                return graceful
        key = self.key(resource, ns, name)
        try:
            if uid:
                # Preconditions.UID (ref: pkg/api/types.go, honored by
                # rest/delete.go BeforeDelete): CAS on the rv observed
                # WITH the matching uid, so a same-name replacement
                # created between the check and the delete survives
                while True:
                    cur = self.store.get(key)
                    if cur.metadata.uid != uid:
                        raise Conflict(
                            f"uid precondition failed: have "
                            f"{uid}, current {cur.metadata.uid}")
                    try:
                        deleted = self.store.delete(
                            key, expect_rv=cur.metadata.resource_version)
                        break
                    except Conflict:
                        continue  # rv moved: re-read and re-check uid
            else:
                deleted = self.store.delete(key)
        except NotFound:
            raise NotFound(kind=resource, name=name)
        if resource == "services":
            self._service_release(deleted)
        if resource == "thirdpartyresources":
            # unmounting a kind removes its instance data too (ref:
            # master.go removeThirdPartyStorage) — otherwise stale
            # objects silently resurrect under a re-created TPR
            _, group, plural = extract_group_and_kind(deleted)
            prefix = f"/registry/thirdparty/{group}/{plural}/"
            for obj in self.store.list(prefix)[0]:
                try:
                    self.store.delete(self.third_party_key(
                        group, plural, obj.metadata.namespace,
                        obj.metadata.name))
                except NotFound:
                    pass
        return deleted

    def _pod_graceful_delete(self, ns: str, name: str,
                             grace: Optional[int],
                             uid: Optional[str] = None
                             ) -> Optional[api.Pod]:
        """Two-phase pod deletion (ref: pkg/api/rest/delete.go
        BeforeDelete + pkg/registry/pod/strategy.go CheckGracefulDelete):
        a running, scheduled pod with a grace period is MARKED
        (deletionTimestamp = now+grace, deletionGracePeriodSeconds) and
        stays in the store for the kubelet to drain and confirm with a
        grace-0 delete; unscheduled or already-terminal pods — and
        grace 0 — fall through to the immediate path (returns None).
        Repeated deletes may only SHORTEN the grace period.

        Divergence from the reference: an absent grace defaults to the
        pod's own spec.terminationGracePeriodSeconds OR immediate —
        not the reference's unconditional 30s (DIVERGENCES #20)."""
        key = self.key("pods", ns, name)
        try:
            pod = self.store.get(key)
        except NotFound:
            raise NotFound(kind="pods", name=name)
        if grace is None:
            grace = pod.spec.termination_grace_period_seconds or 0
        if grace < 0:
            raise Invalid("gracePeriodSeconds: must be non-negative")
        if (grace == 0 or not pod.spec.node_name
                or pod.status.phase in (api.POD_SUCCEEDED, api.POD_FAILED)):
            return None

        expires = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                time.gmtime(int(time.time()) + grace))

        class _AlreadyTerminating(Exception):
            def __init__(self, current):
                self.current = current

        def apply(cur: Any) -> Any:
            # the only-shorten check runs on the CURRENT object inside
            # the CAS closure — a racing longer-grace delete must not
            # re-lengthen a period another caller already shortened
            # (the pre-read outside the closure can be stale)
            if uid and cur.metadata.uid != uid:
                raise Conflict(f"uid precondition failed: have {uid}, "
                               f"current {cur.metadata.uid}")
            existing = cur.metadata.deletion_grace_period_seconds
            if (cur.metadata.deletion_timestamp is not None
                    and existing is not None and grace >= existing):
                raise _AlreadyTerminating(cur)  # no-op: don't re-stamp
            return replace(cur, metadata=replace(
                cur.metadata, deletion_timestamp=expires,
                deletion_grace_period_seconds=grace))

        try:
            return self.store.guaranteed_update(key, apply)
        except _AlreadyTerminating as e:
            return e.current

    # --------------------------------------------- namespace lifecycle

    def _delete_namespace(self, name: str) -> Any:
        """Two-phase: with finalizers present, DELETE only marks the
        namespace Terminating; the namespace controller empties it and
        finalizes, and the store drop happens once finalizers are gone
        (ref: pkg/registry/namespace/etcd/etcd.go Delete +
        namespace strategy)."""
        key = self.key("namespaces", "", name)
        try:
            current = self.store.get(key)
        except NotFound:
            raise NotFound(kind="namespaces", name=name)
        if not current.spec.finalizers:
            return self.store.delete(key)

        def mark(ns_obj):
            return replace(
                ns_obj,
                metadata=replace(ns_obj.metadata,
                                 deletion_timestamp=(
                                     ns_obj.metadata.deletion_timestamp
                                     or api.now_rfc3339())),
                status=replace(ns_obj.status, phase="Terminating"))

        return self.store.guaranteed_update(key, mark)

    def finalize_namespace(self, obj: api.Namespace) -> Any:
        """Replace spec.finalizers; if the namespace is terminating and no
        finalizers remain, remove it from storage (ref:
        pkg/registry/namespace/etcd FinalizeREST + etcd.go Delete)."""
        key = self.key("namespaces", "", obj.metadata.name)

        def swap(ns_obj):
            return replace(ns_obj, spec=replace(
                ns_obj.spec, finalizers=list(obj.spec.finalizers)))

        updated = self.store.guaranteed_update(key, swap)
        if (updated.metadata.deletion_timestamp is not None
                and not updated.spec.finalizers):
            try:
                return self.store.delete(key)
            except NotFound:
                pass
        return updated

    def delete_collection(self, resource: str, namespace: str = "",
                          label_selector: str = "",
                          field_selector: str = "") -> List[Any]:
        items, _ = self.list(resource, namespace, label_selector, field_selector)
        out = []
        for o in items:
            try:
                out.append(self.delete(resource, o.metadata.name,
                                       o.metadata.namespace))
            except NotFound:
                pass
        return out

    def watch(self, resource: str, namespace: str = "",
              since_rev: Optional[int] = None, label_selector: str = "",
              field_selector: str = "", shard: Any = None) -> Watcher:
        if resource == "componentstatuses":
            # computed per request, not stored: a watch would hang
            # forever with zero events (the reference rejects it too)
            raise MethodNotSupported("componentstatuses is not watchable")
        pred = None
        if label_selector or field_selector:
            # server-side watch filtering (the apiserver filters before
            # the wire; transition semantics live in store._filtered_event)
            info = self.info(resource)
            lsel = labelspkg.parse(label_selector) if label_selector else None
            fsel = fieldspkg.parse(field_selector) if field_selector else None
            if fsel is not None:
                fsel = convert_field_selector(resource, fsel)
            # The store's publisher fans one event out to every
            # filtered watcher in a single serialized pass (under its
            # publish lock — off the ledger lock since the two-phase
            # commit split, so this memo stays single-threaded);
            # without sharing, N watchers rebuild the same field map N
            # times per event (2N for MODIFIED: new + prev). Memo key
            # (id, resourceVersion) is collision-safe within this
            # registry — its rv strings are unique per committed write,
            # so an id reused by a later object of the SAME store can't
            # alias (the memo is per-Registry precisely because two
            # stores can mint equal rvs for different objects).
            def _memoized_fields_of():
                # memo'd dict path, built only when the selector didn't
                # compile (the common selectors all compile)
                memo = self._fields_memo.setdefault(resource, {})

                def fields_of(o: Any) -> Dict[str, str]:
                    key = (id(o), o.metadata.resource_version)
                    f = memo.get(key)
                    if f is None:
                        if len(memo) > 16:
                            memo.clear()
                        f = info.fields_fn(o)
                        memo[key] = f
                    return f
                return fields_of

            fmatch = (field_matcher(info, fsel, _memoized_fields_of)
                      if fsel is not None else None)

            def pred(o: Any) -> bool:
                if lsel is not None and not lsel.matches(o.metadata.labels):
                    return False
                if fmatch is not None and not fmatch(o):
                    return False
                return True
        if not self.info(resource).namespaced:
            namespace = ""  # cluster-scoped (same rule as list)
        if shard is not None:
            # worker fan-out shard routing (Fleet serving): the watcher
            # joins the serving worker's partition and is delivered by
            # that worker's pump. Passed through only when set, so any
            # duck-typed store without shard support keeps working.
            return self.store.watch(self.prefix(resource, namespace),
                                    since_rev, predicate=pred, shard=shard)
        return self.store.watch(self.prefix(resource, namespace), since_rev,
                                predicate=pred)

    # ------------------------------------------------- binding subresource

    @staticmethod
    def _binding_op(binding: api.Binding, namespace: str):
        """(store key, CAS update fn) for one binding — shared by bind and
        bind_batch so validation + annotation-merge semantics can't drift
        (ref: pkg/registry/pod/etcd/etcd.go:121 BindingREST.Create ->
        assignPod -> setPodHostAndAnnotations)."""
        return Registry._assign_op(
            binding.metadata.namespace or namespace or "default",
            binding.metadata.name, binding.target.name,
            dict(binding.metadata.annotations))

    @staticmethod
    def _assign_op(ns: str, name: str, host: str,
                   annotations: Dict[str, str]):
        if not name:
            raise Invalid("binding.metadata.name: required value")
        if not host:
            raise Invalid("binding.target.name: required value")

        def assign(pod: api.Pod, rv: str = "") -> api.Pod:
            """wants_rv: with a pre-assigned resourceVersion the stamped
            pod is built in one pass (store.batch fuses the rv clone)."""
            if pod.spec.node_name:
                raise Conflict(
                    f"pod {pod.metadata.name} is already assigned to a node")
            meta_fields: Dict[str, Any] = {}
            if annotations:
                meta_fields["annotations"] = {**pod.metadata.annotations,
                                              **annotations}
            if rv:
                meta_fields["resource_version"] = rv
            meta = (api.fast_replace(pod.metadata, **meta_fields)
                    if meta_fields else pod.metadata)
            return api.fast_replace(
                pod, metadata=meta,
                spec=api.fast_replace(pod.spec, node_name=host))

        assign.wants_rv = True
        return ns, name, assign

    def bind(self, binding: api.Binding, namespace: str = "") -> api.Pod:
        """POST bindings: set pod.spec.nodeName iff currently unset, merging
        binding annotations."""
        ns, name, assign = self._binding_op(binding, namespace)
        key = self.key("pods", ns, name)
        try:
            return self.store.guaranteed_update(key, assign)
        except NotFound:
            raise NotFound(kind="pods", name=name)

    def bind_batch(self, bindings: List[api.Binding],
                   namespace: str = "") -> List[api.Pod]:
        """Commit a tile of bindings in one store pass (all-or-nothing) —
        the batched-commit path the <1s/30k-pod north star requires
        (SURVEY.md section 7 hard part 2). Per-binding validation and
        conflict semantics are identical to bind()."""
        ops = []
        for b in bindings:
            ns, name, assign = self._binding_op(b, namespace)
            ops.append((self.key("pods", ns, name), assign))
        return self._store_batch(ops)

    def bind_batch_hosts(self, assignments: List[Tuple[str, str, str]]
                         ) -> List[api.Pod]:
        """bind_batch without the Binding carrier objects: (namespace,
        name, host) rows straight from the batch scheduler's tile —
        the columnar commit half of the host hot path. CAS/assignment
        semantics are _assign_op's, identical to bind()."""
        ops = []
        for ns, name, host in assignments:
            ns2, name2, assign = self._assign_op(ns or "default", name,
                                                 host, {})
            ops.append((self.key("pods", ns2, name2), assign))
        return self._store_batch(ops)

    # ------------------------------------------- third-party resources

    def third_party_groups(self) -> Dict[str, Dict[str, Tuple[str, str]]]:
        """group -> {plural: (Kind, version)} derived live from the
        stored ThirdPartyResources (a restarted apiserver re-mounts
        everything from the store, like master.go:972 on re-list).
        create() rejects new collisions on (group, plural); should
        pre-existing store state still contain any, the first TPR in
        (namespace, name) order wins deterministically."""
        out: Dict[str, Dict[str, Tuple[str, str]]] = {}
        tprs, _ = self.list("thirdpartyresources", "")
        for tpr in sorted(tprs, key=lambda t: (t.metadata.namespace,
                                               t.metadata.name)):
            kind, group, plural = extract_group_and_kind(tpr)
            version = tpr.versions[0].name if tpr.versions else "v1"
            out.setdefault(group, {}).setdefault(plural, (kind, version))
        return out

    def third_party_kind(self, group: str, plural: str,
                         groups: Optional[Dict] = None
                         ) -> Tuple[str, str]:
        """-> (Kind, version); NotFound when no TPR declares the pair.
        `groups`: a precomputed third_party_groups() map (the server
        resolves once per request instead of re-scanning per verb)."""
        kinds = (groups if groups is not None
                 else self.third_party_groups()).get(group, {})
        if plural not in kinds:
            raise NotFound(
                f'the server could not find resource "{plural}" '
                f'in group "{group}"')
        return kinds[plural]

    @staticmethod
    def third_party_key(group: str, plural: str, namespace: str,
                        name: str = "") -> str:
        base = f"/registry/thirdparty/{group}/{plural}/{namespace}/"
        return base + name if name else base

    def third_party_create(self, group: str, plural: str,
                           obj: api.ThirdPartyResourceData,
                           namespace: str, checked: bool = False
                           ) -> api.ThirdPartyResourceData:
        if not checked:
            self.third_party_kind(group, plural)
        name = obj.metadata.name
        if not _dns1123(name):
            raise Invalid(f"metadata.name: invalid value {name!r}")
        if obj.metadata.namespace and namespace \
                and obj.metadata.namespace != namespace:
            # the URL names the namespace the authorizer approved; the
            # body must not redirect the write (typed _namespace_for
            # enforces the same)
            raise BadRequest(
                f"namespace mismatch: body {obj.metadata.namespace!r} "
                f"vs request {namespace!r}")
        ns = obj.metadata.namespace or namespace or "default"
        if not _dns1123(ns):
            raise Invalid(f"metadata.namespace: invalid value {ns!r}")
        obj = api.fast_replace(obj, metadata=api.fast_replace(
            obj.metadata, namespace=ns, uid=obj.metadata.uid or _new_uid(),
            creation_timestamp=(obj.metadata.creation_timestamp
                                or api.now_rfc3339()),
            resource_version=""))
        return self.store.create(
            self.third_party_key(group, plural, ns, name), obj)

    def third_party_get(self, group: str, plural: str, name: str,
                        namespace: str, checked: bool = False
                        ) -> api.ThirdPartyResourceData:
        if not checked:
            self.third_party_kind(group, plural)
        try:
            return self.store.get(
                self.third_party_key(group, plural, namespace, name))
        except NotFound:
            raise NotFound(kind=plural, name=name)

    def third_party_list(self, group: str, plural: str,
                         namespace: str = "", checked: bool = False):
        if not checked:
            self.third_party_kind(group, plural)
        if namespace:
            return self.store.list(
                self.third_party_key(group, plural, namespace))
        return self.store.list(f"/registry/thirdparty/{group}/{plural}/")

    def third_party_update(self, group: str, plural: str,
                           obj: api.ThirdPartyResourceData,
                           namespace: str, checked: bool = False
                           ) -> api.ThirdPartyResourceData:
        if not checked:
            self.third_party_kind(group, plural)
        if not obj.metadata.name:
            raise Invalid("metadata.name: required value")
        if obj.metadata.namespace and namespace \
                and obj.metadata.namespace != namespace:
            raise BadRequest(
                f"namespace mismatch: body {obj.metadata.namespace!r} "
                f"vs request {namespace!r}")
        ns = obj.metadata.namespace or namespace or "default"
        return self.store.update(
            self.third_party_key(group, plural, ns, obj.metadata.name),
            obj)

    def third_party_delete(self, group: str, plural: str, name: str,
                           namespace: str, checked: bool = False
                           ) -> api.ThirdPartyResourceData:
        if not checked:
            self.third_party_kind(group, plural)
        try:
            return self.store.delete(
                self.third_party_key(group, plural, namespace, name))
        except NotFound:
            raise NotFound(kind=plural, name=name)

    def third_party_watch(self, group: str, plural: str,
                          namespace: str = "",
                          since_rev: Optional[int] = None,
                          checked: bool = False):
        if not checked:
            self.third_party_kind(group, plural)
        prefix = (self.third_party_key(group, plural, namespace)
                  if namespace
                  else f"/registry/thirdparty/{group}/{plural}/")
        return self.store.watch(prefix, since_rev)
