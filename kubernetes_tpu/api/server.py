"""The API server: REST + watch streaming over the registry.

Reference: pkg/apiserver (route install api_installer.go:64, REST dispatch
resthandler.go, watch-over-HTTP watch.go:81, MaxInFlightLimit handlers.go:76)
composed by pkg/master/master.go:279. Routes:

    GET    /healthz | /metrics | /api | /api/v1
    GET    /api/v1/{resource}                      (cluster-scoped or all-ns)
    GET    /api/v1/namespaces/{ns}/{resource}      [?labelSelector=&fieldSelector=
                                                    &watch=true&resourceVersion=]
    POST   /api/v1[/namespaces/{ns}]/{resource}
    GET    /api/v1[/namespaces/{ns}]/{resource}/{name}
    PUT    /api/v1[/namespaces/{ns}]/{resource}/{name}[/status]
    DELETE /api/v1[/namespaces/{ns}]/{resource}/{name}
    POST   /api/v1/namespaces/{ns}/bindings        (pod binding subresource)
    POST   /api/v1/namespaces/{ns}/pods/{name}/binding

Watch responses stream one JSON object per line:
    {"type": "ADDED|MODIFIED|DELETED|ERROR", "object": {...}}
matching the reference's watch/json wire format (pkg/watch/json).
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional, Tuple

from .. import obs
from ..auth.authenticate import authenticate_request
from ..auth.authorize import AuthorizerAttributes
from ..core.errors import (ApiError, BadGateway, BadRequest, Forbidden,
                           MethodNotSupported, NotFound, ServiceUnavailable,
                           TooManyRequests, Unauthorized)
from ..core import types as api_types
from ..core.scheme import Scheme, default_scheme
from ..utils.metrics import (APISERVER_WORKER_REQUESTS, MetricsRegistry,
                             global_metrics)
from .registry import RESOURCES, Registry

WATCH_HEARTBEAT_SECONDS = 30.0

# /api/v1/proxy/nodes/{name}/exec/... — the relayed kubelet exec surface
_EXEC_PROXY_RE = re.compile(r"/proxy/nodes/[^/]+/exec(/|$)")
# pods/{name}/portforward and /attach — a GET in transport, a raw
# channel into the pod in effect (the reference requires the create
# verb on both subresources)
_PORTFORWARD_RE = re.compile(r"/pods/[^/]+/(portforward|attach|exec)$")


def _authz_target(path: str):
    """(resource, namespace) for authorization attributes; non-API paths
    authorize against resource ""."""
    parts = [p for p in path.split("/") if p]
    if len(parts) >= 3 and parts[0] == "apis":
        parts = parts[3:]
    elif len(parts) >= 3 and parts[0] == "api":
        parts = parts[2:]
    else:
        return "", ""
    if not parts:
        return "", ""  # bare group discovery (/apis/extensions/v1beta1)
    if parts[0] == "watch":
        parts = parts[1:]
    if (parts and parts[0] == "proxy" and len(parts) >= 4
            and parts[1] == "namespaces"):
        # a namespaced proxy request authorizes against the proxied
        # resource IN its namespace — an unscoped 'proxy' grant must
        # not reach every namespace, and a namespace-confined policy
        # must cover its own pods/services proxying
        return parts[3], parts[2]
    if parts and parts[0] == "namespaces" and len(parts) >= 3 \
            and parts[2] not in ("status", "finalize"):
        return parts[2], parts[1]
    if parts and parts[0] == "namespaces":
        # the namespaces resource itself, incl. its own subresources
        # (same carve-out the router applies)
        return "namespaces", ""
    if parts:
        return parts[0], ""
    return "", ""


class ApiServer:
    def __init__(self, registry: Registry, host: str = "127.0.0.1",
                 port: int = 0, max_in_flight: int = 400,
                 scheme: Scheme = default_scheme,
                 metrics: Optional[MetricsRegistry] = None,
                 authenticator=None, authorizer=None, request_log=None,
                 tls_cert_file: str = "", tls_key_file: str = "",
                 tls_client_ca_file: str = "",
                 runtime_config: Optional[dict] = None,
                 shed_retry_after: float = 1.0,
                 worker_index: int = 0, fanout_shard=None):
        """tls_cert_file/tls_key_file: serve HTTPS (the reference's
        --tls-cert-file/--tls-private-key-file secure port).
        tls_client_ca_file: additionally request client certificates
        verified against this CA (--client-ca-file); the verified peer
        subject reaches authenticators as the X-Peer-Certificate
        pseudo-header (auth.X509Authenticator consumes it).

        runtime_config: the reference's --runtime-config ConfigurationMap
        (cmd/kube-apiserver/app/server.go:244, parsed :427
        parseRuntimeConfig): `api/v1=false` and
        `apis/extensions/v1beta1=false` disable a whole group-version,
        `apis/extensions/v1beta1/<resource>=false` one extensions
        resource; `api/all=false` turns every version off except those
        explicitly re-enabled. Disabled surfaces 404 and vanish from
        discovery. `api/legacy` is accepted (no pre-v1 wire versions
        exist here to govern).

        worker_index/fanout_shard: Fleet serving (ApiServerPool). The
        shard is this worker's delivery partition over the shared
        store's publish ring — watches served by this worker register
        on it and are pumped by its drain thread, so delivery work is
        split across workers instead of queuing behind one publisher.
        fanout_shard=None keeps the classic single-plane behavior
        (watches ride the store's committer-drained default shard)."""
        self.registry = registry
        self.worker_index = worker_index
        self._shard = fanout_shard
        rc = dict(runtime_config or {})
        all_default = rc.get("api/all", True)
        self._v1_enabled = rc.get("api/v1", all_default)
        self._ext_enabled = rc.get("apis/extensions/v1beta1", all_default)
        prefix = "apis/extensions/v1beta1/"
        self._disabled_resources = {
            k[len(prefix):] for k, v in rc.items()
            if k.startswith(prefix) and not v}
        self._rc_gating = (not self._v1_enabled or not self._ext_enabled
                           or bool(self._disabled_resources))
        self.scheme = scheme
        self.metrics = metrics or global_metrics
        # ref: --max-requests-inflight (cmd/kube-apiserver/app/server.go),
        # MaxInFlightLimit pkg/apiserver/handlers.go:76
        self._inflight = threading.BoundedSemaphore(max_in_flight)
        # the backpressure hint shed 429s carry (Retry-After seconds);
        # the retrying client treats it as a backoff floor
        self.shed_retry_after = shed_retry_after
        # (resource, ns, selectors) -> (segment write version, response
        # bytes): whole-LIST responses reused verbatim between writes
        # to that resource (the watch cache's LIST half at the byte
        # tier; see the GET list handler)
        self._list_bytes_cache: dict = {}
        self.authenticator = authenticator
        self.authorizer = authorizer
        self.request_log = request_log
        self._tls = bool(tls_cert_file)

        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet; httplog is opt-in
                if server.request_log:
                    server.request_log(fmt % args)

            def do_GET(self):
                server.handle(self, "GET")

            def do_POST(self):
                server.handle(self, "POST")

            def do_PUT(self):
                server.handle(self, "PUT")

            def do_DELETE(self):
                server.handle(self, "DELETE")

            def do_PATCH(self):
                # resource PATCH (three patch content types,
                # resthandler.go patchResource) and the any-method
                # proxy relay (pkg/apiserver/proxy.go:52)
                server.handle(self, "PATCH")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        if self._tls:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert_file, tls_key_file or None)
            if tls_client_ca_file:
                ctx.load_verify_locations(tls_client_ca_file)
                # request-but-don't-require: unauthenticated clients may
                # still basic-auth/token-auth; presented certs must chain
                # to the CA (ref: --client-ca-file x509 request auth)
                ctx.verify_mode = ssl.CERT_OPTIONAL
            # Handshake in the per-connection thread, NOT on the listening
            # socket: wrapping the listener would run the (blocking,
            # unbounded) handshake inside the single accept loop, letting
            # one silent TCP client park the whole control plane.
            # ThreadingMixIn calls finish_request from the spawned thread.
            httpd = self.httpd
            orig_finish = httpd.finish_request

            def finish_request(request, client_address):
                request.settimeout(10)  # bound the handshake
                try:
                    tls_conn = ctx.wrap_socket(request, server_side=True)
                except (ssl.SSLError, OSError, TimeoutError):
                    try:
                        request.close()
                    except OSError:
                        pass
                    return
                tls_conn.settimeout(None)  # watches stream indefinitely
                orig_finish(tls_conn, client_address)

            httpd.finish_request = finish_request
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None
        # live watch streams, so stop() can end them: a stopped server
        # must behave like a killed one — shutting only the accept loop
        # would leave established watch handler threads streaming from
        # the in-process registry forever, and clients would never
        # notice the "crash" (the fault tier restarts servers in-proc)
        self._live_watchers: set = set()
        self._watchers_lock = threading.Lock()

    # ------------------------------------------------------------ lifecycle

    @property
    def url(self) -> str:
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{self.host}:{self.port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name=f"apiserver-{self.worker_index}")
        self._thread.start()
        if self._shard is not None:
            self._shard.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._shard is not None:
            # joins the pump and 410s this worker's watchers (clients
            # re-list against a surviving worker)
            self._shard.stop()
        with self._watchers_lock:
            live = list(self._live_watchers)
            self._live_watchers.clear()
        for w in live:
            w.stop()  # handler threads write their final chunk and exit
        self.httpd.server_close()
        # thread-lifecycle audit: serve_forever returns after shutdown();
        # join so a stopped server leaves NO live accept thread behind
        # (the restart chaos tests assert this)
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------- dispatch

    def handle(self, h: BaseHTTPRequestHandler, method: str) -> None:
        start = time.monotonic()
        # per-REQUEST metric marker on a per-CONNECTION handler object:
        # keep-alive serves many requests through one h, so the batch
        # flag must reset here or every request after one batch POST
        # would be mislabeled ':batch' (and dropped from the SLO gate)
        h._batch_request = False
        # per-request body-consumption marker (same per-connection
        # handler object reuse hazard as _batch_request): _send_error's
        # keep-alive framing guard must not trust an earlier request's
        # flag
        h._body_consumed = False
        parsed = urllib.parse.urlsplit(h.path)
        path = parsed.path.rstrip("/")
        query = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        # Long-running requests (watches) are exempt from the in-flight
        # limit, or thousands of agents' watches would starve every other
        # request (ref: pkg/apiserver/handlers.go longRunningRequestRE).
        long_running = (query.get("watch") in ("true", "1")
                        or query.get("follow") in ("true", "1")
                        or "/watch/" in path or path.endswith("/watch")
                        or path.endswith("/portforward")
                        or path.endswith("/attach")
                        or path.endswith("/exec")
                        # health stays shed-exempt: it is the retrying
                        # client's breaker probe and the LB liveness
                        # check — a saturated server must still answer
                        # "alive" or every breaker stays open
                        or path in ("/healthz", "/healthz/ping")
                        # /metrics too: the fleet scraper must keep
                        # reading THROUGH a 429/503 storm — the storm
                        # is exactly what the series needs to show
                        # (Prometheus' own scrape would also bypass an
                        # ingress shedder on the metrics port)
                        or path == "/metrics")
        if not long_running and not self._inflight.acquire(blocking=False):
            # sheds-per-resource: the saturation signal dashboards and
            # the chaos/scale gates read (ref: apiserver
            # dropped_requests metric, pkg/apiserver/handlers.go:83)
            self.metrics.inc("apiserver_dropped_requests",
                             {"resource": _authz_target(path)[0] or "none"})
            err = TooManyRequests("too many requests in flight")
            err.retry_after = self.shed_retry_after
            self._send_error(h, err)
            return
        # the SERVER span: extracted traceparent (or a fresh trace) for
        # every routed request, installed as the current context so
        # registry/store spans nest under it. A span exists per request
        # ARRIVAL — an injected client-side fault never reaches here,
        # and a bare POST is never replayed after ambiguous loss, which
        # together are why "one server span per committed object" holds
        # under chaos (tests/test_obs.py). Self-observation endpoints
        # are excluded, as are breaker/LB health probes.
        tracer = obs.tracer()
        server_span = obs.NOOP
        if tracer.enabled and path not in ("/healthz", "/healthz/ping",
                                           "/metrics", "/debug/trace"):
            res0 = _authz_target(path)[0]
            server_span = tracer.start_span(
                f"apiserver {method} {res0 or path}",
                parent=obs.parse_traceparent(h.headers.get("traceparent")),
                attrs={"verb": method, "resource": res0 or "none"})
        span_status = "error"
        try:
            # handler chain order per master.go:702,710:
            # authenticate -> 401, authorize -> 403, then route.
            # healthz stays open (load balancers / liveness probes carry
            # no credentials).
            health_path = path in ("/healthz", "/healthz/ping")
            # the verified TLS peer subject travels to authenticators as
            # a pseudo-header (the reference's x509 request authenticator
            # reads req.TLS.PeerCertificates). Strip any client-supplied
            # copy first — it would be a trivial spoof otherwise.
            if "X-Peer-Certificate" in h.headers:
                del h.headers["X-Peer-Certificate"]
            if self._tls:
                try:
                    peer = h.connection.getpeercert()
                except (ValueError, OSError):
                    peer = None
                if peer and peer.get("subject"):
                    h.headers["X-Peer-Certificate"] = json.dumps(
                        peer["subject"])
            user = None
            if not health_path:
                user, ok = authenticate_request(self.authenticator, h.headers)
                if not ok:
                    raise Unauthorized("authentication required")
            if self.authorizer is not None and not health_path:
                resource, namespace = _authz_target(path)
                # the node proxy's /exec relay runs commands on the node:
                # a GET in transport, a write in effect — never authorize
                # it under a read-only grant. Match on the SAME normalized
                # segments the router uses (raw-path matching is bypassable
                # with empty segments: /proxy/nodes/n1//exec/...)
                norm = "/" + "/".join(p for p in path.split("/") if p)
                write_effect = bool(_EXEC_PROXY_RE.search(norm)
                                    or _PORTFORWARD_RE.search(norm))
                attrs = AuthorizerAttributes(
                    user=user,
                    read_only=(method == "GET" and not write_effect),
                    resource=resource, namespace=namespace)
                if not self.authorizer.authorize(attrs):
                    name = user.name if user else "unknown"
                    raise Forbidden(f"user {name!r} cannot "
                                    f"{method} {resource or path}")
            with obs.use(server_span):
                self._route(h, method, path, query)
            span_status = "ok"
        except ApiError as e:
            span_status = f"error:{e.code}"
            self._send_error(h, e)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # crash-only server, but report the request
            self._send_error(h, ApiError(f"internal error: {e!r}"))
        finally:
            tracer.end(server_span, status=span_status)
            if not long_running:
                self._inflight.release()
            # per-verb AND per-resource service time, server-side — the
            # reference's apiserver metrics shape (pkg/apiserver/metrics/
            # metrics.go:33-62 RequestLatency{verb,resource}); the SLO
            # suite gates on these summaries, not on client probes.
            # Excluded from the gated summary, as the reference's
            # HighLatencyRequests excludes them (metrics_util.go:194):
            # long-running requests (a watch open for minutes is not a
            # slow GET), and N-object batch POSTs, which get their own
            # ':batch' resource label (one 128-pod create is not a
            # representative single-request sample)
            if not long_running:
                res_label = _authz_target(path)[0] or "none"
                if getattr(h, "_batch_request", False):
                    res_label += ":batch"
                self.metrics.observe(
                    "apiserver_request_latencies_microseconds",
                    (time.monotonic() - start) * 1e6,
                    {"verb": method, "resource": res_label})
            self.metrics.inc("apiserver_request_count", {"verb": method})
            self.metrics.inc(APISERVER_WORKER_REQUESTS,
                             {"worker": str(self.worker_index)})

    def _route(self, h, method: str, path: str, query: dict) -> None:
        if path == "/healthz" or path == "/healthz/ping":
            return self._send_raw(h, 200, b"ok", "text/plain")
        if path == "/metrics":
            return self._send_raw(h, 200, self.metrics.render().encode(),
                                  "text/plain; version=0.0.4")
        if path == "/debug/trace":
            # the span buffer next to /metrics: ?format=perfetto
            # (default) is trace-event JSON for ui.perfetto.dev /
            # chrome://tracing; ?format=spans is the raw span dump
            # tools/trace_report.py analyzes
            t = obs.tracer()
            if query.get("format") == "spans":
                body = json.dumps([s.to_dict() for s in t.spans()])
            else:
                body = t.export_json()
            return self._send_raw(h, 200, body.encode(),
                                  "application/json")
        if path == "/swaggerapi":
            from .swagger import swagger_api
            return self._send_json(h, 200, swagger_api(self.url))
        if path in ("/ui", "/ui/"):
            # the client-side dashboard (pkg/ui role): a static shell —
            # no cluster data is rendered server-side; the app lists and
            # watches through the public REST API from the browser
            from .ui_app import UI_APP_HTML
            return self._send_raw(h, 200, UI_APP_HTML.encode(),
                                  "text/html; charset=utf-8")
        if path in ("/ui/server", "/ui/server/"):
            # the server-rendered variant stays for curl-style use
            from .swagger import ui_page
            return self._send_raw(
                h, 200,
                ui_page(self.registry,
                        namespace=query.get("namespace", "")).encode(),
                "text/html; charset=utf-8")
        if path == "/api":
            return self._send_json(h, 200, {
                "kind": "APIVersions",
                "versions": ["v1"] if self._v1_enabled else []})
        if path == "/apis":
            groups = [{"name": "extensions",
                       "versions": [{"groupVersion": "extensions/v1beta1",
                                     "version": "v1beta1"}]}] \
                if self._ext_enabled else []
            for g, kinds in sorted(
                    self.registry.third_party_groups().items()):
                versions = sorted({v for _, v in kinds.values()})
                groups.append({"name": g, "versions": [
                    {"groupVersion": f"{g}/{v}", "version": v}
                    for v in versions]})
            return self._send_json(h, 200, {"kind": "APIGroupList",
                                            "groups": groups})
        from .registry import EXTENSIONS_RESOURCES
        if path in ("/api/v1", ""):
            if not self._v1_enabled:
                raise NotFound(name="api/v1 disabled by --runtime-config")
            return self._send_json(h, 200, {
                "kind": "APIResourceList", "groupVersion": "v1",
                "resources": [
                    {"name": n, "namespaced": i.namespaced, "kind": i.kind}
                    for n, i in sorted(RESOURCES.items())
                    if n not in EXTENSIONS_RESOURCES]})
        if path == "/apis/extensions/v1beta1":
            if not self._ext_enabled:
                raise NotFound(
                    name="extensions/v1beta1 disabled by --runtime-config")
            return self._send_json(h, 200, {
                "kind": "APIResourceList",
                "groupVersion": "extensions/v1beta1",
                "resources": [
                    {"name": n, "namespaced": i.namespaced, "kind": i.kind}
                    for n, i in sorted(RESOURCES.items())
                    if n in EXTENSIONS_RESOURCES
                    and n not in self._disabled_resources]})

        parts = [p for p in path.split("/") if p]
        # strip "api/v1" or "apis/extensions/v1beta1" (one flat registry
        # serves both groups; the reference mounts the extensions group at
        # its own prefix, master.go:1049) — enforcing --runtime-config
        # group/resource switches at the mount point
        if len(parts) >= 3 and parts[0] == "apis" and \
                parts[1] == "extensions" and parts[2] == "v1beta1":
            if not self._ext_enabled:
                raise NotFound(
                    name="extensions/v1beta1 disabled by --runtime-config")
            parts = parts[3:]
        elif len(parts) >= 2 and parts[0] == "api" and parts[1] == "v1":
            if not self._v1_enabled:
                raise NotFound(name="api/v1 disabled by --runtime-config")
            parts = parts[2:]
        elif parts[0] == "apis" and len(parts) >= 2:
            # dynamic third-party groups (master.go:972
            # InstallThirdPartyResource): /apis/<group>[/<version>/...]
            return self._route_third_party(h, method, parts[1:], query)
        else:
            raise NotFound(f"path {path!r} not found")
        if not parts:
            raise NotFound(f"path {path!r} not found")

        if self._rc_gating:
            # one flat registry serves BOTH mounts, so group/resource
            # switches must classify the TARGET resource, not trust the
            # prefix the caller picked (else a disabled group remains
            # reachable by swapping prefixes, or a disabled resource via
            # the legacy watch/ path). _authz_target is the one path
            # grammar (watch/proxy prefixes, the namespaces
            # status/finalize carve-out) — reuse it, don't re-derive it.
            res, _ = _authz_target(path)
            if res in EXTENSIONS_RESOURCES:
                if not self._ext_enabled or res in self._disabled_resources:
                    raise NotFound(
                        name=f"{res} disabled by --runtime-config")
            elif res and not self._v1_enabled:
                raise NotFound(
                    name=f"{res} (api/v1) disabled by --runtime-config")

        namespace = ""
        if (parts[0] == "namespaces" and len(parts) >= 3
                and parts[2] not in ("status", "finalize")):
            # /namespaces/{ns}/{resource}...
            namespace, parts = parts[1], parts[2:]
        elif parts[0] == "namespaces":
            # the namespaces resource itself, incl. its own subresources:
            # /api/v1/namespaces[/{name}[/status|/finalize]]
            pass
        # also accept the legacy /api/v1/watch/... prefix
        is_watch_path = parts[0] == "watch"
        if is_watch_path:
            parts = parts[1:]
            if parts and parts[0] == "namespaces" and len(parts) >= 2:
                namespace, parts = parts[1], parts[2:]

        if not parts:
            raise NotFound(f"path {path!r} not found")
        # node proxy: /api/v1/proxy/nodes/{name}/{kubelet path...}
        # (ref: pkg/apiserver ProxyHandler + master.go "proxy/nodes")
        if parts[0] == "proxy" and len(parts) >= 3 and parts[1] == "nodes":
            # any-method relay (ref: pkg/apiserver/proxy.go:52
            # ServeHTTP has no method filter — kubectl proxy write
            # round-trips are a reference capability). Forward the
            # ORIGINAL query string: the flattened `query` dict drops
            # repeated params (kubelet /exec takes repeated ?command=)
            raw_q = urllib.parse.urlsplit(h.path).query
            return self._proxy_node(h, parts[2], "/".join(parts[3:]),
                                    raw_q, method=method,
                                    body=self._proxy_body(h, method),
                                    ctype=h.headers.get("Content-Type",
                                                        ""))
        # pod/service proxy:
        # /api/v1/proxy/namespaces/{ns}/{pods|services}/{id[:port]}/...
        # (ref: apiserver ProxyHandler + pod/strategy.go:199 +
        # service/rest.go:288 ResourceLocation)
        if (parts[0] == "proxy" and len(parts) >= 5
                and parts[1] == "namespaces"
                and parts[3] in ("pods", "services")):
            raw_q = urllib.parse.urlsplit(h.path).query
            return self._proxy_workload(h, parts[3], parts[2], parts[4],
                                        "/".join(parts[5:]), raw_q,
                                        method=method,
                                        body=self._proxy_body(h, method),
                                        ctype=h.headers.get("Content-Type",
                                                            ""))
        resource = parts[0]
        name = parts[1] if len(parts) > 1 else ""
        sub = parts[2] if len(parts) > 2 else ""
        watching = is_watch_path or query.get("watch") in ("true", "1")

        if method == "GET":
            if resource == "pods" and sub == "log":
                # ref: pod log subresource — the apiserver relays to the
                # node's kubelet server (pkg/registry/pod/etcd LogREST ->
                # kubelet /containerLogs, server.go:242)
                return self._serve_pod_log(h, namespace, name, query)
            if resource == "pods" and sub == "portforward":
                return self._serve_port_forward(h, namespace, name, query)
            if resource == "pods" and sub == "attach":
                return self._serve_attach(h, namespace, name, query)
            if resource == "pods" and sub == "exec" and \
                    self._wants_websocket(h):
                return self._serve_exec_ws(h, namespace, name, query)
            if sub == "scale":
                scale = self.registry.get_scale(resource, name, namespace)
                return self._send_json(h, 200,
                                       self.scheme.encode_dict(scale))
            if watching and not name:
                return self._serve_watch(h, resource, namespace, query)
            if not name:
                info = Registry.info(resource)
                # segment version read BEFORE the list: a write landing
                # between the list and a version read taken after it
                # would cache these (pre-write) bytes under the
                # post-write version — readers would then reuse stale
                # bytes. Read-before instead: the same interleave now
                # caches under the OLD version, which the next reader
                # sees as expired and rebuilds (a wasted cache slot,
                # never a stale serve).
                # never byte-cache TTL'd resources (events expire
                # passively — no write bumps the version) or computed
                # ones (componentstatuses is probed live per request;
                # its segment version would sit at 0 forever and pin
                # the first response)
                wv = (getattr(self.registry.store, "write_version", None)
                      if not info.ttl
                      and resource != "componentstatuses" else None)
                seg_ver = (wv(Registry.prefix(resource)) if wv is not None
                           else None)
                # two cache tiers: per-object fragments (serde.wire_json
                # — a 5k-node LIST was ~1.9s of reflective encode before
                # them) and the WHOLE response body keyed by (list args)
                # and validated by segment write version: repeated LISTs
                # between writes reduce to a socket write WITHOUT
                # touching the store — checked BEFORE registry.list, so
                # a hit skips the per-object selector scan entirely (5k
                # kubelets polling nodeName-filtered pod LISTs would
                # otherwise pay an O(pods) filter pass per poll only to
                # throw the result away). A hit must also still be
                # WATCHABLE: the cached bytes embed the resourceVersion
                # the list was built at, and a write-quiet resource's
                # segment version never moves while busier segments
                # roll the shared watch window forward — serving an
                # aged-out rev forever would livelock that resource's
                # list->watch->410 recovery loop. TTL'd resources
                # (events) expire passively — no write bumps the
                # version, so their bytes never cache (wv None above).
                ck = (resource, namespace,
                      query.get("labelSelector", ""),
                      query.get("fieldSelector", ""))
                cached = (self._list_bytes_cache.get(ck)
                          if seg_ver is not None else None)
                if cached is not None and cached[0] == seg_ver:
                    floor_fn = getattr(self.registry.store, "watch_floor",
                                       None)
                    if floor_fn is None or cached[1] >= floor_fn():
                        return self._send_raw(h, 200, cached[2],
                                              "application/json")
                items, rev = self.registry.list(
                    resource, namespace,
                    query.get("labelSelector", ""),
                    query.get("fieldSelector", ""))
                body = self.scheme.encode_list_bytes(info.kind, items,
                                                     str(rev))
                if seg_ver is not None:
                    if len(self._list_bytes_cache) >= 32:
                        self._list_bytes_cache.clear()
                    self._list_bytes_cache[ck] = (seg_ver, rev, body)
                return self._send_raw(h, 200, body, "application/json")
            obj = self.registry.get(resource, name, namespace)
            return self._send_json(h, 200, self.scheme.encode_dict(obj))

        if method == "POST":
            body = self._read_body(h)
            if isinstance(body, list):
                h._batch_request = True  # metrics: ':batch' label
            if resource == "bindings" and isinstance(body, list):
                # batched bindings tile: one store pass, per-pod conflict
                # semantics (registry.bind_batch)
                bindings = [self.scheme.decode_dict(b) for b in body]
                pods = self.registry.bind_batch(bindings, namespace)
                return self._send_json(h, 201, self.scheme.encode_list(
                    "Pod", pods, "0"))
            if isinstance(body, list) and not name and not sub:
                # batched create: one store window, one watch flush
                # (write-side analogue of the bindings tile above);
                # collection URLs only — named/subresource POSTs (e.g.
                # pods/{name}/binding) keep their own handlers
                objs = [self.scheme.decode_dict(b) for b in body]
                created = self.registry.create_batch(resource, objs,
                                                     namespace)
                info = Registry.info(resource)
                return self._send_json(h, 201, self.scheme.encode_list(
                    info.kind, created, "0"))
            obj = self.scheme.decode_dict(body)
            if resource == "pods" and sub == "binding":
                created = self.registry.bind(obj, namespace)
            else:
                created = self.registry.create(resource, obj, namespace)
            return self._send_json(h, 201, self.scheme.encode_dict(created))

        if method == "PUT":
            if not name:
                raise MethodNotSupported("PUT requires a resource name")
            body = self._read_body(h)
            obj = self.scheme.decode_dict(body)
            if sub == "status":
                updated = self.registry.update_status(resource, obj, namespace)
            elif sub == "scale":
                updated = self.registry.update_scale(resource, name, obj,
                                                     namespace)
            elif sub == "finalize" and resource == "namespaces":
                updated = self.registry.finalize_namespace(obj)
            elif sub:
                raise NotFound(f"subresource {sub!r} not found")
            else:
                updated = self.registry.update(resource, obj, namespace)
            return self._send_json(h, 200, self.scheme.encode_dict(updated))

        if method == "PATCH":
            if not name:
                raise MethodNotSupported("PATCH requires a resource name")
            if sub:
                raise MethodNotSupported(
                    "PATCH on subresources is not supported")
            # the patch TYPE rides the Content-Type (ref:
            # pkg/api/types.go:2065 PatchType); absent defaults to
            # strategic like kubectl's own patches
            ctype = (h.headers.get("Content-Type", "")
                     .split(";")[0].strip().lower()
                     or Registry.PATCH_STRATEGIC)
            if ctype == "application/json":
                ctype = Registry.PATCH_STRATEGIC
            body = self._read_body(h)
            patched = self.registry.patch(resource, name, body, namespace,
                                          patch_type=ctype)
            return self._send_json(h, 200, self.scheme.encode_dict(patched))

        if method == "DELETE":
            if not name:
                deleted = self.registry.delete_collection(
                    resource, namespace,
                    query.get("labelSelector", ""),
                    query.get("fieldSelector", ""))
                info = Registry.info(resource)
                return self._send_json(h, 200, self.scheme.encode_list(
                    info.kind, deleted))
            # DeleteOptions ride the DELETE body (kind DeleteOptions,
            # gracePeriodSeconds; pkg/apiserver/resthandler.go Delete);
            # a query param is accepted for curl ergonomics
            grace = None
            uid = None
            if query.get("gracePeriodSeconds", "") != "":
                try:
                    grace = int(query["gracePeriodSeconds"])
                except ValueError:
                    raise BadRequest("gracePeriodSeconds: not an integer")
            if int(h.headers.get("Content-Length") or 0) > 0:
                body = self._read_body(h)
                if isinstance(body, dict) and body:
                    opts = self.scheme.decode_dict(
                        body, expect=api_types.DeleteOptions) \
                        if body.get("kind") == "DeleteOptions" else None
                    if opts is not None:
                        if opts.grace_period_seconds is not None:
                            grace = opts.grace_period_seconds
                        if opts.preconditions is not None \
                                and opts.preconditions.uid:
                            uid = opts.preconditions.uid
            obj = self.registry.delete(resource, name, namespace,
                                       grace_period_seconds=grace,
                                       uid=uid)
            return self._send_json(h, 200, self.scheme.encode_dict(obj))

        raise MethodNotSupported(f"method {method} not supported")

    # -------------------------------------------- third-party resources

    def _route_third_party(self, h, method: str, parts: list,
                           query: dict) -> None:
        """REST verbs for dynamically-registered groups (the CRD
        ancestor; ref: pkg/registry/thirdpartyresourcedata + the
        per-group APIGroupVersion master.go builds)."""
        from .registry import decode_third_party, encode_third_party
        group = parts[0]
        groups = self.registry.third_party_groups()
        if group not in groups:
            raise NotFound(f"group {group!r} not found")
        if len(parts) == 1:  # group discovery
            versions = sorted({v for _, v in groups[group].values()})
            return self._send_json(h, 200, {
                "kind": "APIGroup", "name": group,
                "versions": [{"groupVersion": f"{group}/{v}",
                              "version": v} for v in versions]})
        version, rest = parts[1], parts[2:]
        if not rest:  # version discovery
            declared_versions = {v for _, v in groups[group].values()}
            if version not in declared_versions:
                raise NotFound(
                    f"group {group!r} has no version {version!r}")
            return self._send_json(h, 200, {
                "kind": "APIResourceList",
                "groupVersion": f"{group}/{version}",
                "resources": [
                    {"name": plural, "namespaced": True, "kind": kind}
                    for plural, (kind, v) in sorted(
                        groups[group].items()) if v == version]})
        namespace = ""
        if rest[0] == "namespaces" and len(rest) >= 2:
            namespace, rest = rest[1], rest[2:]
        if not rest:
            raise NotFound("resource required")
        plural = rest[0]
        name = rest[1] if len(rest) > 1 else ""
        kind, declared = self.registry.third_party_kind(group, plural,
                                                        groups=groups)
        if version != declared:
            raise NotFound(
                f"group {group!r} serves version {declared!r}")
        gv = f"{group}/{version}"
        encode = lambda obj: encode_third_party(obj, kind, gv)  # noqa: E731

        if method == "GET":
            if query.get("watch") in ("true", "1") and not name:
                rv = query.get("resourceVersion")
                deadline = self._watch_deadline(query)
                watcher = self.registry.third_party_watch(
                    group, plural, namespace,
                    int(rv) if rv not in (None, "") else None,
                    checked=True)
                self.metrics.inc("apiserver_watch_count",
                                 {"resource": f"{group}/{plural}"})
                if self._wants_websocket(h):
                    return self._serve_watch_websocket(h, watcher, encode,
                                                       deadline=deadline)
                return self._stream_watch_events(h, watcher, encode,
                                                 deadline=deadline)
            if not name:
                items, rev = self.registry.third_party_list(
                    group, plural, namespace, checked=True)
                return self._send_json(h, 200, {
                    "kind": kind + "List", "apiVersion": gv,
                    "metadata": {"resourceVersion": str(rev)},
                    "items": [encode(i) for i in items]})
            obj = self.registry.third_party_get(
                group, plural, name, namespace or "default", checked=True)
            return self._send_json(h, 200, encode(obj))
        if method == "POST":
            obj = decode_third_party(self._read_body(h))
            created = self.registry.third_party_create(
                group, plural, obj, namespace, checked=True)
            return self._send_json(h, 201, encode(created))
        if method == "PUT":
            if not name:
                raise MethodNotSupported("PUT requires a resource name")
            obj = decode_third_party(self._read_body(h))
            # the URL names the object; the body must not redirect the
            # write elsewhere (typed PUT enforces the same)
            obj.metadata.name = name
            if namespace:
                obj.metadata.namespace = namespace
            updated = self.registry.third_party_update(
                group, plural, obj, namespace, checked=True)
            return self._send_json(h, 200, encode(updated))
        if method == "DELETE":
            if not name:
                raise MethodNotSupported("DELETE requires a name")
            deleted = self.registry.third_party_delete(
                group, plural, name, namespace or "default", checked=True)
            return self._send_json(h, 200, encode(deleted))
        raise MethodNotSupported(f"method {method} not supported")

    # ----------------------------------------------------- kubelet relay

    def _kubelet_base(self, node_name: str) -> str:
        from .relay import kubelet_base_for
        return kubelet_base_for(self.registry, node_name)

    # when a master tunneler is running, master->node GETs ride the
    # tunnels (ref: master.go wires tunneler.Dial into the node-proxy
    # transport); set by Master after the tunneler starts
    tunnel_dial = None

    def _tunnel_conn(self, host: str, port: int):
        """One tunnel leg, with every dial failure mapped to 502 (a
        wedged node raises TimeoutError — an OSError, not a
        ConnectionError — and must not surface as a 500)."""
        try:
            return self.tunnel_dial(host, port)
        except (ConnectionError, OSError) as e:
            raise BadGateway(f"tunnel to {host}: {e}")

    def _node_ws(self, host: str, port: int, path: str):
        """Websocket leg to a kubelet: through the tunnel when the
        master tunneler is running (master.go wires tunneler.Dial into
        the whole node-proxy transport — streaming legs included),
        direct otherwise."""
        from ..utils import wsstream
        if self.tunnel_dial is not None:
            conn = self._tunnel_conn(host, port)
            try:
                return wsstream.client_connect(host, port, path,
                                               sock=conn)
            except BaseException:
                conn.close()
                raise
        return wsstream.client_connect(host, port, path)

    def _relay(self, h, url: str, method: str = "GET",
               body: "bytes | None" = None, ctype: str = "") -> None:
        if self.tunnel_dial is not None:
            parsed = urllib.parse.urlsplit(url)
            host, port = parsed.hostname, parsed.port or 80
            path = parsed.path + (f"?{parsed.query}" if parsed.query
                                  else "")
            from .tunneler import http_request_over
            conn = self._tunnel_conn(host, port)
            try:
                status, rtype, rbody = http_request_over(
                    conn, host, path, method=method, body=body,
                    content_type=ctype)
            except (ConnectionError, OSError, ValueError) as e:
                raise BadGateway(f"tunneled relay {host}: {e}")
            finally:
                conn.close()
            return self._send_raw(h, status, rbody, rtype)
        from .relay import fetch_kubelet_response
        status, rtype, rbody = fetch_kubelet_response(
            url, method=method, body=body, content_type=ctype)
        self._send_raw(h, status, rbody, rtype)

    def _serve_port_forward(self, h, namespace: str, name: str,
                            query: dict) -> None:
        """GET /pods/{name}/portforward?port=N, websocket upgrade: the
        apiserver leg of port forwarding — relays frames to the owning
        kubelet's /portForward endpoint (ref: pkg/registry/pod/etcd
        PortForwardREST -> kubelet server.go PortForward; SPDY there,
        websocket here)."""
        import urllib.parse as _parse

        from ..utils import wsstream

        pod = self.registry.get("pods", name, namespace)
        if not pod.spec.node_name:
            raise BadRequest(f"pod {name!r} is not scheduled yet")
        port = query.get("port", "")
        base = self._kubelet_base(pod.spec.node_name)
        split = _parse.urlsplit(base)
        path = (f"/portForward/{namespace}/{name}"
                f"?port={_parse.quote(port)}")
        try:
            up = self._node_ws(split.hostname, split.port, path)
        except (ConnectionError, OSError, BadGateway) as e:
            raise BadGateway(f"kubelet portForward: {e}")
        try:
            if not wsstream.server_handshake(h):
                return

            def down_write(b: bytes) -> None:
                h.wfile.write(b)
                h.wfile.flush()

            wsstream.relay_ws(h.rfile.read, down_write, up)
        finally:
            up.close()
            h.close_connection = True

    def _serve_attach(self, h, namespace: str, name: str,
                      query: dict) -> None:
        """GET /pods/{name}/attach?container=&stdin=, websocket upgrade
        relayed to the owning kubelet's /attach endpoint (ref:
        pkg/registry/pod/etcd AttachREST -> kubelet AttachContainer)."""
        import urllib.parse as _parse

        from ..utils import wsstream
        from .relay import resolve_pod_container

        container, base = resolve_pod_container(
            self.registry, namespace, name, query.get("container", ""))
        params = {k: query[k] for k in ("stdin",) if k in query}
        q = ("?" + _parse.urlencode(params)) if params else ""
        split = _parse.urlsplit(base)
        path = f"/attach/{namespace}/{name}/{container}{q}"
        try:
            up = self._node_ws(split.hostname, split.port, path)
        except (ConnectionError, OSError, BadGateway) as e:
            raise BadGateway(f"kubelet attach: {e}")
        try:
            if not wsstream.server_handshake(h):
                return

            def down_write(b: bytes) -> None:
                h.wfile.write(b)
                h.wfile.flush()

            wsstream.relay_ws(h.rfile.read, down_write, up)
        finally:
            up.close()
            h.close_connection = True

    def _serve_exec_ws(self, h, namespace: str, name: str,
                       query: dict) -> None:
        """GET /pods/{name}/exec?command=...&container=&stdin= with a
        websocket upgrade: relayed to the owning kubelet's interactive
        /exec endpoint (ref: pkg/registry/pod/etcd ExecREST -> kubelet
        ExecInContainer, server.go:242). Non-upgrade exec requests keep
        the one-shot node-proxy path."""
        import urllib.parse as _parse

        from ..utils import wsstream
        from .relay import exec_admission, resolve_pod_container

        container, base = resolve_pod_container(
            self.registry, namespace, name, query.get("container", ""))
        # CONNECT admission (DenyExecOnPrivileged) gates this relay
        # exactly like the one-shot node-proxy exec path — the
        # websocket variant must not be an admission bypass
        exec_admission(self.registry, f"exec/{namespace}/{name}/{container}")
        # the dispatch query dict collapses repeats; command is
        # multi-valued, so re-parse it from the raw request line
        raw_q = _parse.parse_qs(_parse.urlsplit(h.path).query)
        params = [("command", c) for c in raw_q.get("command", [])]
        if "stdin" in query:
            params.append(("stdin", query["stdin"]))
        q = ("?" + _parse.urlencode(params)) if params else ""
        split = _parse.urlsplit(base)
        path = f"/exec/{namespace}/{name}/{container}{q}"
        try:
            up = self._node_ws(split.hostname, split.port, path)
        except (ConnectionError, OSError, BadGateway) as e:
            raise BadGateway(f"kubelet exec: {e}")
        try:
            if not wsstream.server_handshake(h):
                return

            def down_write(b: bytes) -> None:
                h.wfile.write(b)
                h.wfile.flush()

            wsstream.relay_ws(h.rfile.read, down_write, up)
        finally:
            up.close()
            h.close_connection = True

    def _serve_pod_log(self, h, namespace: str, name: str,
                       query: dict) -> None:
        from .relay import container_log_url
        params = {k: query[k] for k in ("tailLines", "follow",
                                        "previous")
                  if k in query}
        url = container_log_url(self.registry, namespace, name,
                                query.get("container", ""),
                                urllib.parse.urlencode(params))
        if query.get("follow") in ("true", "1"):
            return self._relay_stream(h, url)
        self._relay(h, url)

    def _relay_stream_tunneled(self, h, url: str) -> None:
        """The follow-logs relay over a tunnel leg: headers parsed, then
        body pieces copied through as they arrive (the streaming half of
        master.go's tunneler.Dial transport wiring)."""
        from .tunneler import http_stream_over
        parsed = urllib.parse.urlsplit(url)
        host, port = parsed.hostname, parsed.port or 80
        path = parsed.path + (f"?{parsed.query}" if parsed.query else "")
        conn = self._tunnel_conn(host, port)
        try:
            try:
                status, ctype, chunks = http_stream_over(conn, host, path)
            except (ConnectionError, OSError, ValueError) as e:
                raise BadGateway(f"tunneled stream {host}: {e}")
            h.send_response(status)
            h.send_header("Content-Type", ctype)
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()
            try:
                for piece in chunks:
                    h.wfile.write(f"{len(piece):x}\r\n".encode()
                                  + piece + b"\r\n")
                    h.wfile.flush()
                h.wfile.write(b"0\r\n\r\n")
                h.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass  # follower left; closing conn ends the upstream
        finally:
            conn.close()
            h.close_connection = True

    def _relay_stream(self, h, url: str) -> None:
        """Streaming relay (follow logs): pieces copied through as they
        arrive (relay.open_kubelet_stream carries the shared error
        mapping, so a kubelet 404 surfaces as the same typed NotFound
        the in-proc path raises)."""
        import select
        from .relay import open_kubelet_stream
        if self.tunnel_dial is not None:
            return self._relay_stream_tunneled(h, url)
        # transport failures raise BadGateway (JSON status); kubelet HTTP
        # statuses pass through verbatim like the non-follow _relay path
        upstream = open_kubelet_stream(url, verbatim_errors=True)
        code = getattr(upstream, "status", getattr(upstream, "code", 200))
        if code != 200:
            body = upstream.read()
            upstream.close()
            return self._send_raw(h, code, body, "text/plain")
        # Disconnect watchdog: with a quiet container nothing is ever
        # written downstream, so a vanished follower would otherwise pin
        # this thread in upstream.read1 forever. The follower sends no
        # bytes after its GET — a readable client socket means EOF/reset;
        # closing upstream unblocks the read loop.
        gone = threading.Event()

        def watchdog():
            while not gone.is_set():
                try:
                    readable, _, _ = select.select([h.connection], [], [],
                                                   0.5)
                except (ValueError, OSError):
                    return  # handler already closed the client socket
                if readable and not gone.is_set():
                    try:
                        upstream.close()
                    except Exception:
                        pass
                    return

        threading.Thread(target=watchdog, daemon=True,
                         name="log-relay-watchdog").start()
        try:
            h.send_response(200)
            h.send_header("Content-Type", "text/plain")
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()
            while True:
                data = upstream.read1(65536)
                if not data:
                    break
                h.wfile.write(f"{len(data):x}\r\n".encode())
                h.wfile.write(data + b"\r\n")
                h.wfile.flush()
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, ValueError, OSError):
            # broken upstream or watchdog-closed stream: no valid
            # terminator possible — drop the connection so the follower
            # gets EOF instead of hanging on a keep-alive socket
            h.close_connection = True
        finally:
            gone.set()
            upstream.close()

    @staticmethod
    def _proxy_body(h, method: str) -> "bytes | None":
        """Request body for a proxied write (the reference's proxy
        streams it; one-shot reads serve the same verbs here).
        Chunked uploads are rejected rather than half-read: ignoring
        Transfer-Encoding would forward an empty body AND leave the
        chunk bytes on the keep-alive socket to be misparsed as the
        next request line."""
        if method in ("GET", "HEAD"):
            return None
        if "chunked" in (h.headers.get("Transfer-Encoding") or "").lower():
            h.close_connection = True
            raise BadRequest(
                "proxied writes require Content-Length "
                "(chunked request bodies are not supported)")
        try:
            length = int(h.headers.get("Content-Length") or 0)
        except ValueError:
            h.close_connection = True
            raise BadRequest("invalid Content-Length")
        if length < 0:
            # rfile.read(-1) would block on the keep-alive socket until
            # the client hangs up, pinning an in-flight slot
            h.close_connection = True
            raise BadRequest("invalid Content-Length")
        body = h.rfile.read(length) if length else b""
        h._body_consumed = True
        return body

    def _proxy_node(self, h, node_name: str, rest: str,
                    raw_query: str, method: str = "GET",
                    body: "bytes | None" = None,
                    ctype: str = "") -> None:
        from .relay import exec_admission
        # exec admission (DenyExecOnPrivileged): the relay is the
        # CONNECT moment (ref: plugin/pkg/admission/exec)
        exec_admission(self.registry, rest)
        base = self._kubelet_base(node_name)
        self._relay(h, f"{base}/{rest}"
                    + (f"?{raw_query}" if raw_query else ""),
                    method=method, body=body, ctype=ctype)

    @staticmethod
    def _split_name_port(ident: str) -> "tuple[str, str]":
        """'name', 'name:port' or 'http:name:port' (util
        SplitSchemeNamePort; only the http scheme is served here)."""
        bits = ident.split(":")
        if len(bits) == 1:
            return bits[0], ""
        if len(bits) == 2:
            return bits[0], bits[1]
        if len(bits) == 3 and bits[0] == "http":
            return bits[1], bits[2]
        raise BadRequest(f"invalid proxy request {ident!r}")

    def _proxy_workload(self, h, resource: str, namespace: str,
                        ident: str, rest: str, raw_query: str,
                        method: str = "GET",
                        body: "bytes | None" = None,
                        ctype: str = "") -> None:
        """Locate the backend for a pod/service proxy request and relay
        (ref: pkg/registry/pod/strategy.go:199 ResourceLocation — pod
        IP, port defaulting to the first declared container port;
        pkg/registry/service/rest.go:288 — resolve a port number to its
        service-port name, then pick a ready endpoint carrying it)."""
        import random
        name, port = self._split_name_port(ident)
        if resource == "pods":
            pod = self.registry.get("pods", name, namespace)
            if not port:
                for c in pod.spec.containers:
                    if c.ports:
                        port = str(c.ports[0].container_port)
                        break
            if not pod.status.pod_ip or not port:
                raise ServiceUnavailable(
                    f"pod {name!r} has no address/port to proxy to")
            if not port.isdigit():
                raise BadRequest(
                    f"pod proxy port must be numeric, got {port!r}")
            host, hport = pod.status.pod_ip, int(port)
        else:
            svc = self.registry.get("services", name, namespace)
            port_name = port
            if port.isdigit():  # number -> declared port's name
                match = [sp for sp in svc.spec.ports
                         if sp.port == int(port)]
                if not match:
                    raise ServiceUnavailable(
                        f"no service port {port} found for service "
                        f"{name!r}")
                port_name = match[0].name
            elif not port:
                if len(svc.spec.ports) != 1:
                    raise BadRequest(
                        f"service {name!r} has multiple ports; specify "
                        f"one as {name}:port")
                port_name = svc.spec.ports[0].name
            eps = self.registry.get("endpoints", name, namespace)
            candidates = []
            for subset in eps.subsets:
                for ep_port in subset.ports:
                    if ep_port.name == port_name:
                        candidates += [(a.ip, ep_port.port)
                                       for a in subset.addresses]
            if not candidates:
                raise ServiceUnavailable(
                    f"no endpoints available for service {name!r}")
            # random pick spreads load like rest.go:322's random subset
            host, hport = random.choice(candidates)
        self._relay(h, f"http://{host}:{hport}/{rest}"
                    + (f"?{raw_query}" if raw_query else ""),
                    method=method, body=body, ctype=ctype)

    # -------------------------------------------------------------- watch

    @staticmethod
    def _wants_websocket(h) -> bool:
        """(ref: pkg/apiserver/watch.go:44 isWebsocketRequest)"""
        connection = (h.headers.get("Connection") or "").lower()
        upgrade = (h.headers.get("Upgrade") or "").lower()
        return "upgrade" in connection and upgrade == "websocket"

    def _serve_watch(self, h, resource: str, namespace: str, query: dict) -> None:
        rv = query.get("resourceVersion")
        since_rev = int(rv) if rv not in (None, "") else None
        deadline = self._watch_deadline(query)
        watcher = self.registry.watch(resource, namespace, since_rev,
                                      query.get("labelSelector", ""),
                                      query.get("fieldSelector", ""),
                                      shard=self._shard)
        self.metrics.inc("apiserver_watch_count", {"resource": resource})
        if self._wants_websocket(h):
            return self._serve_watch_websocket(h, watcher,
                                               deadline=deadline)
        self._stream_watch_events(h, watcher, self.scheme.encode_dict,
                                  deadline=deadline)

    @staticmethod
    def _encode_watch_object(encode, ev):
        """ERROR events carry an ApiError, not a registered API type —
        they serialize as their Status dict (the reference's watch wire
        sends a Status object; api/client._HttpWatcher decodes exactly
        that via from_status). Letting encode() raise here would write
        a second HTTP response into the half-open chunked body and
        desync the connection."""
        from ..core.errors import ApiError
        if isinstance(ev.object, ApiError):
            return ev.object.status()
        return encode(ev.object)

    @staticmethod
    def _watch_deadline(query: dict):
        """?timeoutSeconds= -> absolute monotonic deadline or None
        (ref: the WatchServer's request timeout, api_installer.go
        TimeoutSeconds): the stream ends cleanly after N seconds and
        the client re-lists/re-watches — the reflector's normal
        recovery path. Parsed BEFORE the watcher registers so a
        malformed value can't leak an unstopped watcher into the
        store; nan/inf reject rather than silently unbounding."""
        raw = query.get("timeoutSeconds", "")
        if raw == "":
            return None
        try:
            timeout = float(raw)
        except ValueError:
            raise BadRequest("timeoutSeconds: not a number")
        if not math.isfinite(timeout) or timeout < 0:
            raise BadRequest(
                "timeoutSeconds: must be a non-negative finite number")
        return time.monotonic() + timeout

    @staticmethod
    def _watch_tick(watcher, deadline):
        """One bounded watcher.next: (event, expired). The deadline caps
        the wait so an expired watch ends within a heartbeat."""
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None, True
            ev = watcher.next(timeout=min(WATCH_HEARTBEAT_SECONDS,
                                          remaining))
        else:
            ev = watcher.next(timeout=WATCH_HEARTBEAT_SECONDS)
        return ev, (deadline is not None
                    and time.monotonic() >= deadline and ev is None)

    def _track_watcher(self, watcher) -> None:
        with self._watchers_lock:
            self._live_watchers.add(watcher)

    def _untrack_watcher(self, watcher) -> None:
        with self._watchers_lock:
            self._live_watchers.discard(watcher)

    def _stream_watch_events(self, h, watcher, encode, deadline=None) -> None:
        """Chunked JSON event stream shared by the typed watch and the
        third-party watch (encode: object -> wire dict)."""
        self._track_watcher(watcher)
        try:
            h.send_response(200)
            h.send_header("Content-Type", "application/json")
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()

            def write_chunk(payload: bytes) -> None:
                h.wfile.write(f"{len(payload):x}\r\n".encode())
                h.wfile.write(payload + b"\r\n")
                h.wfile.flush()

            while True:
                ev, expired = self._watch_tick(watcher, deadline)
                if expired:
                    break
                if ev is None:
                    if watcher.stopped:
                        break
                    write_chunk(b"\n")  # keep-alive blank line
                    continue
                line = json.dumps({
                    "type": ev.type,
                    "object": self._encode_watch_object(encode, ev),
                }).encode() + b"\n"
                write_chunk(line)
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self._untrack_watcher(watcher)
            watcher.stop()

    def _serve_watch_websocket(self, h, watcher, encode=None,
                               deadline=None) -> None:
        """Watch over a websocket (ref: watch.go:89 HandleWS; wire events
        are the same JSON objects, one per text frame). Framing and
        handshake come from utils/wsstream (the pkg/util/wsstream role);
        client frames are drained and discarded like the reference's
        Receive loop (watch.go:96)."""
        from ..utils import wsstream

        if encode is None:
            encode = self.scheme.encode_dict
        self._track_watcher(watcher)
        try:
            if not wsstream.server_handshake(h):
                return
            # event writer and the drain thread's pongs share the pipe
            wlock = threading.Lock()

            def write(b: bytes) -> None:
                with wlock:
                    h.wfile.write(b)
                    h.wfile.flush()

            def drain_client_frames():
                """Read client frames: answer Ping with Pong (RFC 6455
                5.5.3, echoing the payload), stop the watcher on Close
                (or a malformed/oversized frame), discard the rest like
                the reference's Receive loop."""
                try:
                    while True:
                        opcode, payload = wsstream.read_frame(
                            h.rfile.read)
                        if opcode == wsstream.CLOSE:
                            break
                        if opcode == wsstream.PING:
                            wsstream.write_frame(write, payload,
                                                 wsstream.PONG)
                except (ConnectionError, OSError, ValueError):
                    pass
                finally:
                    watcher.stop()

            threading.Thread(target=drain_client_frames,
                             daemon=True).start()

            while True:
                ev, expired = self._watch_tick(watcher, deadline)
                if expired:
                    break
                if ev is None:
                    if watcher.stopped:
                        break
                    wsstream.write_frame(write, b"", wsstream.PING)
                    continue
                line = json.dumps({
                    "type": ev.type,
                    "object": self._encode_watch_object(encode, ev),
                }).encode()
                wsstream.write_frame(write, line, wsstream.TEXT)
            wsstream.write_frame(write, b"", wsstream.CLOSE)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self._untrack_watcher(watcher)
            watcher.stop()
            h.close_connection = True

    # ------------------------------------------------------------- helpers

    @staticmethod
    def _read_body(h) -> dict:
        try:
            length = int(h.headers.get("Content-Length") or 0)
        except ValueError:
            h.close_connection = True
            raise BadRequest("invalid Content-Length")
        if length <= 0:
            raise BadRequest("empty request body")
        raw = h.rfile.read(length)
        h._body_consumed = True
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise BadRequest(f"invalid JSON body: {e}")

    def _send_json(self, h, code: int, payload: dict,
                   extra_headers: Optional[dict] = None) -> None:
        self._send_raw(h, code, json.dumps(payload).encode(),
                       "application/json", extra_headers=extra_headers)

    def _send_error(self, h, err: ApiError) -> None:
        # an error can fire before a body-bearing request's body was
        # read (e.g. PATCH to a subresource -> MethodNotSupported);
        # leftover body bytes would desync HTTP/1.1 keep-alive framing —
        # the next request on the connection parses mid-body. Close
        # unless a body reader ran to completion (a 409 AFTER the read
        # keeps its keep-alive — conflict-heavy CAS traffic must not
        # pay a reconnect per retry).
        if (h.command not in ("GET", "HEAD")
                and not getattr(h, "_body_consumed", False)):
            try:
                # nonzero (incl. negative) means framing can't be
                # trusted; only an explicit 0 / absent header is safe
                pending = int(h.headers.get("Content-Length") or 0) != 0
            except ValueError:
                pending = True  # unparseable: can't trust the framing
            if pending or h.headers.get("Transfer-Encoding"):
                h.close_connection = True
        extra = None
        retry_after = getattr(err, "retry_after", None)
        if retry_after:
            # fractional values allowed (DIVERGENCES.md: RFC 7231 says
            # integer delta-seconds; sub-second shed windows would all
            # round to the same wave otherwise)
            extra = {"Retry-After": f"{retry_after:g}"}
        try:
            self._send_json(h, err.code, err.status(), extra_headers=extra)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    @staticmethod
    def _send_raw(h, code: int, payload: bytes, ctype: str,
                  extra_headers: Optional[dict] = None) -> None:
        h.send_response(code)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(payload)))
        for k, v in (extra_headers or {}).items():
            h.send_header(k, v)
        h.end_headers()
        h.wfile.write(payload)


class ApiServerPool:
    """N apiserver workers over ONE shared store — the horizontally-
    scaled serving plane (Fleet serving, README). Each worker is a full
    ApiServer on its own port with its own fan-out shard from the
    shared store (attach_fanout_shard), so the watchers a worker serves
    are pumped by that worker's own drain thread: delivery parallelism
    scales with workers instead of queuing behind the single committer-
    drained publisher. Reads and writes all land on the same store
    (one revision stream, one watch history), so any worker can serve
    any client — the in-proc stand-in for N apiserver processes behind
    a load balancer over shared etcd (DIVERGENCES #33).

    Stores without shard support (anything duck-typed that lacks
    attach_fanout_shard) still pool fine: those workers serve watches
    off the store's default delivery path.

    restart(i) models one apiserver process bouncing behind the LB:
    the old worker's watchers get 410 (ERROR + close, via shard.stop),
    and the replacement binds the SAME port — in-flight connections
    queue in the listen backlog instead of landing refused, so a
    scraper or client that retries sees a blip, not an outage."""

    def __init__(self, registry: Registry, n_workers: int = 2,
                 host: str = "127.0.0.1",
                 metrics: Optional[MetricsRegistry] = None,
                 **server_kwargs):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.registry = registry
        self.host = host
        self.metrics = metrics
        self._server_kwargs = dict(server_kwargs)
        self.workers: list = []
        for i in range(n_workers):
            self.workers.append(self._build(i, port=0))

    def _build(self, index: int, port: int) -> ApiServer:
        store = self.registry.store
        shard = (store.attach_fanout_shard(f"worker-{index}")
                 if hasattr(store, "attach_fanout_shard") else None)
        return ApiServer(self.registry, host=self.host, port=port,
                         metrics=self.metrics, worker_index=index,
                         fanout_shard=shard, **self._server_kwargs)

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "ApiServerPool":
        for w in self.workers:
            w.start()
        return self

    def stop(self) -> None:
        for w in self.workers:
            w.stop()

    def restart(self, index: int) -> ApiServer:
        """Bounce worker `index` in place (rolling-restart chaos): stop
        the old instance (accept thread joined, shard pump joined,
        watchers 410'd), then bind a fresh instance — fresh shard
        cursor, fresh handler state — on the SAME port."""
        old = self.workers[index]
        port = old.port
        old.stop()
        neu = self._build(index, port=port)
        self.workers[index] = neu
        neu.start()
        return neu

    # ------------------------------------------------------------- helpers

    def urls(self) -> list:
        return [w.url for w in self.workers]

    def shards(self) -> list:
        return [w._shard for w in self.workers if w._shard is not None]

    def alive_threads(self) -> list:
        """Every live thread the pool owns (restart chaos asserts this
        is empty after stop): accept threads + shard pumps."""
        out = []
        for w in self.workers:
            t = w._thread
            if t is not None and t.is_alive():
                out.append(t)
            sh = w._shard
            if sh is not None and sh._thread is not None \
                    and sh._thread.is_alive():
                out.append(sh._thread)
        return out
