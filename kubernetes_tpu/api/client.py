"""Clients: one interface, in-process and HTTP transports.

Reference: pkg/client/unversioned (fluent REST client, request.go). Agents,
controllers and the scheduler are written against `Client`; the kubemark-style
in-process harness wires them straight to the Registry (zero serialization),
while real deployments go over HTTP with identical semantics — mirroring how
the reference's integration tests wire components directly to an in-process
master (test/integration/framework/master_utils.go:92).
"""

from __future__ import annotations

import http.client
import json
import logging
import socket
import threading
import urllib.parse
import urllib.request
from typing import Any, Iterator, List, Optional, Tuple

from .. import obs
from ..core import types as api
from ..core.errors import (ApiError, BadGateway, BadRequest, NotFound,
                           from_status)
from ..core.scheme import Scheme, default_scheme
from ..core.watch import Event, Watcher
from .registry import Registry
from .retry import RetryPolicy

logger = logging.getLogger("kubernetes_tpu.client")


class Client:
    """Verb interface over resources. Implementations: InProcClient,
    HttpClient."""

    def create(self, resource: str, obj: Any, namespace: str = "") -> Any:
        raise NotImplementedError

    def create_batch(self, resource: str, objs: List[Any],
                     namespace: str = "") -> List[Any]:
        """Create many objects of one resource in a single apiserver
        round-trip / store window (the write-side analogue of
        bind_batch). Default: sequential creates."""
        return [self.create(resource, o, namespace) for o in objs]

    def create_from_template(self, resource: str, template: Any,
                             names: List[str], namespace: str = ""
                             ) -> List[Any]:
        """Columnar bulk create: one template object, many names
        (registry.create_from_template). Default: expand client-side
        into a create_batch — any Client gets the semantics, the
        in-proc registry gets the fast path."""
        from ..core.types import expand_template_rows
        return self.create_batch(resource,
                                 expand_template_rows(template, names),
                                 namespace)

    def get(self, resource: str, name: str, namespace: str = "") -> Any:
        raise NotImplementedError

    def list(self, resource: str, namespace: str = "",
             label_selector: str = "", field_selector: str = ""
             ) -> Tuple[List[Any], int]:
        raise NotImplementedError

    def update(self, resource: str, obj: Any, namespace: str = "") -> Any:
        raise NotImplementedError

    def update_status(self, resource: str, obj: Any, namespace: str = "") -> Any:
        raise NotImplementedError

    def patch(self, resource: str, name: str, patch_body: Any,
              namespace: str = "",
              patch_type: str = "application/strategic-merge-patch+json"
              ) -> Any:
        """Server-side PATCH with the reference's three content types
        (ref: client/unversioned request.go Patch)."""
        raise NotImplementedError

    def get_scale(self, resource: str, name: str,
                  namespace: str = "") -> Any:
        """GET .../{name}/scale (ref: client/unversioned Scales getter)."""
        raise NotImplementedError

    def update_scale(self, resource: str, name: str, scale: Any,
                     namespace: str = "") -> Any:
        raise NotImplementedError

    def update_status_batch(self, resource: str, objs: List[Any],
                            namespace: str = "") -> List[Any]:
        # Default: sequential (the reference wire protocol has no status
        # batching; the in-proc client overrides with one store pass).
        return [self.update_status(resource, o, namespace) for o in objs]

    def delete(self, resource: str, name: str, namespace: str = "",
               grace_period_seconds: Optional[int] = None,
               uid: Optional[str] = None) -> Any:
        raise NotImplementedError

    def watch(self, resource: str, namespace: str = "",
              since_rev: Optional[int] = None, label_selector: str = "",
              field_selector: str = "") -> Watcher:
        raise NotImplementedError

    def bind(self, binding: api.Binding, namespace: str = "") -> Any:
        raise NotImplementedError

    def bind_batch(self, bindings: List[api.Binding],
                   namespace: str = "") -> List[Any]:
        # Default: sequential binds (HTTP transport can't batch in the
        # reference wire protocol; the in-proc client overrides this).
        return [self.bind(b, namespace) for b in bindings]

    def bind_batch_hosts(self, assignments: List[Tuple[str, str, str]]
                         ) -> List[Any]:
        """Columnar bind: (namespace, name, host) rows. Default:
        expand into Binding objects; the in-proc client hands the rows
        straight to the registry."""
        return self.bind_batch([api.Binding(
            metadata=api.ObjectMeta(namespace=ns, name=name),
            target=api.ObjectReference(kind="Node", name=host))
            for ns, name, host in assignments])

    def finalize_namespace(self, obj: api.Namespace) -> Any:
        raise NotImplementedError

    def pod_logs(self, name: str, namespace: str = "default",
                 container: str = "", tail_lines: int = 0,
                 previous: bool = False) -> str:
        """Container logs via the pod `log` subresource (the apiserver
        relays to the node's kubelet server)."""
        raise NotImplementedError

    def node_proxy(self, node_name: str, path: str) -> bytes:
        """GET a kubelet-server path through the node proxy."""
        raise NotImplementedError

    def pod_logs_stream(self, name: str, namespace: str = "default",
                        container: str = ""):
        """Follow a container's log (kubectl logs -f): yields text
        pieces until the container exits or the caller stops."""
        raise NotImplementedError


class InProcClient(Client):
    def __init__(self, registry: Registry):
        self.registry = registry

    def create(self, resource, obj, namespace=""):
        return self.registry.create(resource, obj, namespace)

    def create_batch(self, resource, objs, namespace=""):
        return self.registry.create_batch(resource, objs, namespace)

    def create_from_template(self, resource, template, names, namespace=""):
        return self.registry.create_from_template(resource, template,
                                                  names, namespace)

    def get(self, resource, name, namespace=""):
        return self.registry.get(resource, name, namespace)

    def list(self, resource, namespace="", label_selector="", field_selector=""):
        return self.registry.list(resource, namespace, label_selector,
                                  field_selector)

    def update(self, resource, obj, namespace=""):
        return self.registry.update(resource, obj, namespace)

    def update_status(self, resource, obj, namespace=""):
        return self.registry.update_status(resource, obj, namespace)

    def update_status_batch(self, resource, objs, namespace=""):
        return self.registry.update_status_batch(resource, objs, namespace)

    def patch(self, resource, name, patch_body, namespace="",
              patch_type="application/strategic-merge-patch+json"):
        return self.registry.patch(resource, name, patch_body, namespace,
                                   patch_type=patch_type)

    def get_scale(self, resource, name, namespace=""):
        return self.registry.get_scale(resource, name, namespace)

    def update_scale(self, resource, name, scale, namespace=""):
        return self.registry.update_scale(resource, name, scale, namespace)

    def delete(self, resource, name, namespace="",
               grace_period_seconds=None, uid=None):
        return self.registry.delete(
            resource, name, namespace,
            grace_period_seconds=grace_period_seconds, uid=uid)

    def watch(self, resource, namespace="", since_rev=None,
              label_selector="", field_selector=""):
        return self.registry.watch(resource, namespace, since_rev,
                                   label_selector, field_selector)

    def bind(self, binding, namespace=""):
        return self.registry.bind(binding, namespace)

    def bind_batch(self, bindings, namespace=""):
        return self.registry.bind_batch(bindings, namespace)

    def bind_batch_hosts(self, assignments):
        return self.registry.bind_batch_hosts(assignments)

    def pod_logs(self, name, namespace="default", container="",
                 tail_lines=0, previous=False):
        # even in-proc, the kubelet is across the network: resolve the
        # node's daemon endpoint and fetch (same relay ApiServer does)
        from .relay import container_log_url, fetch_kubelet
        params = []
        if tail_lines:
            params.append(f"tailLines={tail_lines}")
        if previous:
            params.append("previous=true")
        url = container_log_url(
            self.registry, namespace, name, container, "&".join(params))
        return fetch_kubelet(url).decode()

    def node_proxy(self, node_name, path):
        # in-proc: the same relay ApiServer performs, incl. the exec
        # CONNECT admission moment
        from .relay import exec_admission, fetch_kubelet, kubelet_base_for
        exec_admission(self.registry, path)
        base = kubelet_base_for(self.registry, node_name)
        return fetch_kubelet(f"{base}/{path}")

    def portforward_open(self, name, namespace, port):
        """-> an upgraded websocket socket carrying the pod's TCP port
        as binary frames. In-proc skips the apiserver leg and dials the
        kubelet directly (same frames either way)."""
        import urllib.parse as up
        from ..utils import wsstream
        from .relay import kubelet_base_for
        pod = self.registry.get("pods", name, namespace)
        if not pod.spec.node_name:
            raise BadRequest(f"pod {name!r} is not scheduled yet")
        base = kubelet_base_for(self.registry, pod.spec.node_name)
        split = up.urlsplit(base)
        return wsstream.client_connect(
            split.hostname, split.port,
            f"/portForward/{namespace}/{name}?port={port}")

    def attach_open(self, name, namespace, container="", stdin=False):
        """-> an upgraded websocket: the container's live output as
        binary frames (and stdin upstream when asked). In-proc dials
        the kubelet directly."""
        import urllib.parse as up
        from ..utils import wsstream
        from .relay import resolve_pod_container
        container, base = resolve_pod_container(self.registry, namespace,
                                                name, container)
        split = up.urlsplit(base)
        q = "?stdin=true" if stdin else ""
        return wsstream.client_connect(
            split.hostname, split.port,
            f"/attach/{namespace}/{name}/{container}{q}")

    def exec_open(self, name, namespace, cmd, container="", stdin=False):
        """-> an upgraded websocket for INTERACTIVE exec: output as
        binary frames, stdin upstream, a final TEXT {"exitCode": N}
        frame before CLOSE. In-proc dials the kubelet directly."""
        import urllib.parse as up
        from ..utils import wsstream
        from .relay import resolve_pod_container
        container, base = resolve_pod_container(self.registry, namespace,
                                                name, container)
        split = up.urlsplit(base)
        params = [("command", c) for c in cmd]
        if stdin:
            params.append(("stdin", "true"))
        q = "?" + up.urlencode(params)
        return wsstream.client_connect(
            split.hostname, split.port,
            f"/exec/{namespace}/{name}/{container}{q}")

    def pod_logs_stream(self, name, namespace="default", container=""):
        from .relay import (container_log_url, iter_http_stream,
                            open_kubelet_stream)
        url = container_log_url(self.registry, namespace, name, container,
                                "follow=true")
        return iter_http_stream(open_kubelet_stream(url))

    def finalize_namespace(self, obj):
        return self.registry.finalize_namespace(obj)


class _HttpWatcher(Watcher):
    """Adapts a chunked HTTP watch stream to the Watcher interface by
    pumping parsed events from a reader thread. Holds the raw connection so
    stop() can shutdown() the socket — closing the buffered response instead
    would block on the reader's buffer lock until the next heartbeat."""

    def __init__(self, conn, resp, scheme: Scheme, capacity: int = 100_000):
        super().__init__(capacity)
        self._conn = conn
        self._resp = resp
        self._scheme = scheme
        #: True when the stream died mid-flight (not a clean server end
        #: or a deliberate stop()) — Reflector logs the reconnect and
        #: backs off instead of treating it as a clean stop
        self.failed = False
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self):
        err: Optional[Exception] = None
        try:
            for raw in self._resp:
                line = raw.strip()
                if not line:
                    continue
                data = json.loads(line)
                obj = data["object"]
                if data["type"] == "ERROR":
                    self.send(Event("ERROR", from_status(obj)))
                    break
                self.send(Event(data["type"], self._scheme.decode_dict(obj)))
        except Exception as e:
            # a deliberate stop() shuts the socket down under the
            # reader — that is a clean stop, not a stream failure
            if not self.stopped:
                err = e
        finally:
            if err is not None:
                self.failed = True
                self.send(Event("ERROR", ApiError(
                    f"watch stream disconnected: {err!r}")))
            self.stop()

    def stop(self):
        try:
            if self._conn.sock is not None:
                self._conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._conn.close()
        except Exception:
            pass
        super().stop()


class HttpClient(Client):
    def __init__(self, base_url: str, scheme: Scheme = default_scheme,
                 timeout: float = 30.0,
                 headers: Optional[dict] = None,
                 ssl_context=None,
                 retry: Optional[RetryPolicy] = None):
        """headers: sent with every request (Authorization etc. — the
        kubeconfig credential role). ssl_context: for https servers —
        CA trust plus an optional client certificate
        (ssl.SSLContext.load_cert_chain), the x509 credential role.
        retry: the resilience policy (api.retry.RetryPolicy) — None
        picks the default (idempotency-aware retries + breaker); pass
        RetryPolicy.disabled() for raw single-shot requests."""
        self.base_url = base_url.rstrip("/")
        self.scheme = scheme
        self.timeout = timeout
        self.headers = dict(headers or {})
        self.ssl_context = ssl_context
        self.retry = retry if retry is not None else RetryPolicy()
        self._breaker = self.retry.make_breaker()

    # ------------------------------------------------------------ plumbing

    def _url(self, resource: str, namespace: str = "", name: str = "",
             sub: str = "", query: Optional[dict] = None) -> str:
        from .registry import EXTENSIONS_RESOURCES
        info = Registry.info(resource)
        group = ("apis/extensions/v1beta1"
                 if resource in EXTENSIONS_RESOURCES else "api/v1")
        parts = [self.base_url, group]
        if info.namespaced and namespace:
            parts += ["namespaces", namespace]
        parts.append(resource)
        if name:
            parts.append(name)
        if sub:
            parts.append(sub)
        url = "/".join(parts)
        if query:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in query.items() if v})
        return url

    def _do(self, method: str, url: str, body: Any = None,
            stream: bool = False, raw_body: Optional[bytes] = None,
            content_type: str = "application/json",
            idempotent: Optional[bool] = None):
        """One REST request under the retry policy. idempotent: None
        defaults to method == GET; verb methods pass True when the
        request carries its own replay guard (uid precondition, CAS
        resourceVersion). Streams bypass retry — their consumers
        (Reflector, log followers) own reconnection.

        Tracing: one root span per logical request; every retry
        attempt is a sibling child span carrying its OWN traceparent
        (fresh span id, shared trace id), so the server's spans show
        which attempt committed and which were lost."""
        tr = obs.tracer()
        if stream:
            ctx = obs.current()
            return self._do_once(
                method, url, body, stream, raw_body, content_type,
                traceparent=obs.format_traceparent(ctx) if ctx else None)
        if idempotent is None:
            idempotent = method in ("GET", "HEAD")
        if not tr.enabled:
            return self.retry.call(
                lambda: self._do_once(method, url, body, False, raw_body,
                                      content_type),
                idempotent=idempotent, breaker=self._breaker,
                probe=self._probe_healthz)
        root = tr.start_span(
            f"http {method}", parent=obs.current(),
            attrs={"path": urllib.parse.urlsplit(url).path})

        def attempt():
            span = tr.start_span(f"http {method} attempt", parent=root)
            try:
                resp = self._do_once(
                    method, url, body, False, raw_body, content_type,
                    traceparent=obs.format_traceparent(span))
            except BaseException:
                tr.end(span, status="error")
                raise
            tr.end(span)
            return resp

        try:
            result = self.retry.call(
                attempt, idempotent=idempotent, breaker=self._breaker,
                probe=self._probe_healthz)
        except BaseException:
            tr.end(root, status="error")
            raise
        tr.end(root)
        return result

    def _probe_healthz(self) -> bool:
        """The breaker's recovery probe: one cheap unretried GET."""
        try:
            resp = urllib.request.urlopen(
                self.base_url + "/healthz", timeout=2.0,
                context=self.ssl_context)
            ok = resp.status == 200
            resp.close()
            return ok
        except Exception:
            return False

    def _do_once(self, method: str, url: str, body: Any = None,
                 stream: bool = False, raw_body: Optional[bytes] = None,
                 content_type: str = "application/json",
                 traceparent: Optional[str] = None):
        data = raw_body
        headers = {"Accept": "application/json", **self.headers}
        if traceparent:
            headers["traceparent"] = traceparent
        if body is not None:
            data = self.scheme.encode(body).encode()
        if data is not None:
            headers["Content-Type"] = content_type
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        try:
            resp = urllib.request.urlopen(
                req, timeout=None if stream else self.timeout,
                context=self.ssl_context)
        except urllib.error.HTTPError as e:
            retry_after = e.headers.get("Retry-After") if e.headers \
                else None
            try:
                status = json.loads(e.read().decode())
            except Exception:
                err = ApiError(f"HTTP {e.code} from {url}")
            else:
                err = from_status(status)
            if retry_after:
                try:
                    err.retry_after = float(retry_after)
                except ValueError:
                    pass
            raise err
        if stream:
            return resp
        payload = resp.read().decode()
        resp.close()
        return json.loads(payload) if payload else None

    def _decode(self, data: dict) -> Any:
        return self.scheme.decode_dict(data)

    # --------------------------------------------------------------- verbs

    def create(self, resource, obj, namespace=""):
        ns = namespace or getattr(obj.metadata, "namespace", "") or "default"
        return self._decode(self._do("POST", self._url(resource, ns), obj))

    def create_batch(self, resource, objs, namespace=""):
        """POST a JSON array: one batched store window server-side.
        Objects are grouped by namespace (the URL names one namespace;
        a mixed-namespace batch becomes one POST per namespace, same
        result order as the input)."""
        if not objs:
            return []
        groups: dict = {}
        for i, o in enumerate(objs):
            ns = (namespace or getattr(o.metadata, "namespace", "")
                  or "default")
            groups.setdefault(ns, []).append((i, o))
        out = [None] * len(objs)
        for ns, members in groups.items():
            payload = json.dumps(
                [self.scheme.encode_dict(o) for _i, o in members]).encode()
            data = self._do("POST", self._url(resource, ns),
                            raw_body=payload)
            kind = data["kind"][:-4] if data["kind"].endswith("List") \
                else data["kind"]
            for (i, _o), item in zip(members, data["items"]):
                out[i] = self._decode({**item, "kind": kind})
        return out

    def get(self, resource, name, namespace=""):
        ns = namespace or "default"
        return self._decode(self._do("GET", self._url(resource, ns, name)))

    def list(self, resource, namespace="", label_selector="", field_selector=""):
        data = self._do("GET", self._url(resource, namespace, query={
            "labelSelector": label_selector, "fieldSelector": field_selector}))
        items = [self._decode({**i, "kind": data["kind"][:-4]})
                 for i in data["items"]]
        rev = int(data["metadata"].get("resourceVersion") or 0)
        return items, rev

    @staticmethod
    def _has_rv(obj) -> bool:
        """A PUT carrying a resourceVersion is CAS — replaying it after
        an ambiguous connection loss surfaces as Conflict, never as a
        silent double-commit, so it is safe to retry."""
        meta = getattr(obj, "metadata", None)
        return bool(getattr(meta, "resource_version", ""))

    def update(self, resource, obj, namespace=""):
        ns = namespace or obj.metadata.namespace
        return self._decode(self._do(
            "PUT", self._url(resource, ns, obj.metadata.name), obj,
            idempotent=self._has_rv(obj)))

    def update_status(self, resource, obj, namespace=""):
        ns = namespace or obj.metadata.namespace
        return self._decode(self._do(
            "PUT", self._url(resource, ns, obj.metadata.name, "status"), obj,
            idempotent=self._has_rv(obj)))

    def patch(self, resource, name, patch_body, namespace="",
              patch_type="application/strategic-merge-patch+json"):
        ns = namespace or "default"
        raw = json.dumps(patch_body).encode()
        return self._decode(self._do(
            "PATCH", self._url(resource, ns, name), raw_body=raw,
            content_type=patch_type))

    def get_scale(self, resource, name, namespace=""):
        ns = namespace or "default"
        return self._decode(self._do(
            "GET", self._url(resource, ns, name, "scale")))

    def update_scale(self, resource, name, scale, namespace=""):
        ns = namespace or "default"
        return self._decode(self._do(
            "PUT", self._url(resource, ns, name, "scale"), scale,
            idempotent=self._has_rv(scale)))

    def delete(self, resource, name, namespace="",
               grace_period_seconds=None, uid=None):
        ns = namespace or "default"
        body = None
        if grace_period_seconds is not None or uid:
            body = api.DeleteOptions(
                grace_period_seconds=grace_period_seconds,
                preconditions=api.Preconditions(uid=uid) if uid else None)
        # uid precondition makes a replay unambiguous: the same object
        # deletes once, a replacement answers Conflict, a completed
        # delete answers NotFound — all terminal signals for callers
        return self._decode(self._do(
            "DELETE", self._url(resource, ns, name), body,
            idempotent=bool(uid)))

    def _ws_connect(self, path: str):
        """Upgrade a websocket to the apiserver carrying this client's
        credentials and TLS posture (the same posture every other
        request gets from _do) — the one place the scheme/port/ssl
        defaulting lives for upgraded streams."""
        import urllib.parse as up
        from ..utils import wsstream
        split = up.urlsplit(self.base_url)
        port_num = split.port or (443 if split.scheme == "https" else 80)
        ctx = None
        if split.scheme == "https":
            import ssl as _ssl
            ctx = self.ssl_context or _ssl.create_default_context()
        return wsstream.client_connect(split.hostname, port_num, path,
                                       headers=self.headers,
                                       ssl_context=ctx)

    def portforward_open(self, name, namespace, port):
        """-> an upgraded websocket socket through the apiserver's
        portforward relay (the remote-kubectl leg)."""
        ns = namespace or "default"
        return self._ws_connect(
            f"/api/v1/namespaces/{ns}/pods/{name}/portforward"
            f"?port={port}")

    def attach_open(self, name, namespace, container="", stdin=False):
        """-> an upgraded websocket through the apiserver's attach
        relay."""
        import urllib.parse as up
        ns = namespace or "default"
        params = {}
        if container:
            params["container"] = container
        if stdin:
            params["stdin"] = "true"
        q = ("?" + up.urlencode(params)) if params else ""
        return self._ws_connect(
            f"/api/v1/namespaces/{ns}/pods/{name}/attach{q}")

    def exec_open(self, name, namespace, cmd, container="", stdin=False):
        """-> an upgraded websocket through the apiserver's exec
        relay (interactive exec; the one-shot path stays node_proxy)."""
        import urllib.parse as up
        ns = namespace or "default"
        params = [("command", c) for c in cmd]
        if container:
            params.append(("container", container))
        if stdin:
            params.append(("stdin", "true"))
        return self._ws_connect(
            f"/api/v1/namespaces/{ns}/pods/{name}/exec?"
            + up.urlencode(params))

    def watch(self, resource, namespace="", since_rev=None,
              label_selector="", field_selector=""):
        url = self._url(resource, namespace, query={
            "watch": "true",
            "labelSelector": label_selector,
            "fieldSelector": field_selector,
            "resourceVersion": "" if since_rev is None else str(since_rev)})
        split = urllib.parse.urlsplit(url)
        if split.scheme == "https":
            conn = http.client.HTTPSConnection(split.hostname, split.port,
                                               context=self.ssl_context)
        else:
            conn = http.client.HTTPConnection(split.hostname, split.port)
        path = split.path + ("?" + split.query if split.query else "")
        watch_headers = {"Accept": "application/json", **self.headers}
        ctx = obs.current()
        if ctx is not None:
            watch_headers["traceparent"] = obs.format_traceparent(ctx)
        conn.request("GET", path, headers=watch_headers)
        resp = conn.getresponse()
        if resp.status != 200:
            body = resp.read().decode()
            conn.close()
            try:
                raise from_status(json.loads(body))
            except json.JSONDecodeError:
                raise ApiError(f"HTTP {resp.status} from {url}")
        return _HttpWatcher(conn, resp, self.scheme)

    def bind(self, binding, namespace=""):
        ns = namespace or binding.metadata.namespace or "default"
        return self._decode(self._do(
            "POST", self._url("bindings", ns), binding))

    def finalize_namespace(self, obj):
        return self._decode(self._do(
            "PUT", self._url("namespaces", "", obj.metadata.name,
                             "finalize"), obj,
            idempotent=self._has_rv(obj)))

    def bind_batch(self, bindings, namespace=""):
        """POST a JSON array to the bindings resource: one batched store
        commit server-side (all-or-nothing; each binding carries its own
        namespace)."""
        if not bindings:
            return []
        payload = json.dumps(
            [self.scheme.encode_dict(b) for b in bindings]).encode()
        data = self._do("POST", self._url("bindings", namespace),
                        raw_body=payload)
        return [self._decode({**i, "kind": "Pod"}) for i in data["items"]]

    def pod_logs(self, name, namespace="default", container="",
                 tail_lines=0, previous=False):
        query = {"container": container}
        if tail_lines:
            query["tailLines"] = str(tail_lines)
        if previous:
            query["previous"] = "true"
        url = self._url("pods", namespace, name, "log", query)
        resp = self._do("GET", url, stream=True)
        try:
            return resp.read().decode()
        finally:
            resp.close()

    def pod_logs_stream(self, name, namespace="default", container=""):
        from .relay import iter_http_stream
        url = self._url("pods", namespace, name, "log",
                        {"container": container, "follow": "true"})
        return iter_http_stream(self._do("GET", url, stream=True))

    def node_proxy(self, node_name: str, path: str) -> bytes:
        """GET through the apiserver's node proxy
        (/api/v1/proxy/nodes/{name}/{path})."""
        url = f"{self.base_url}/api/v1/proxy/nodes/{node_name}/{path}"
        resp = self._do("GET", url, stream=True)
        try:
            return resp.read()
        finally:
            resp.close()


def confirm_pod_deletion(client: Client, pod: Any, attempts: int = 8,
                         backoff_s: float = 0.5,
                         clock=None, rng=None) -> None:
    """The grace-0, uid-guarded delete that completes a graceful pod
    deletion from the node side (real kubelet, hollow kubelet, fleet).
    NotFound/Conflict are terminal — the pod is gone, or a same-name
    replacement took the name; transient API errors retry off-thread
    with jittered backoff, because a marked pod emits no further watch
    events and a dropped confirm would leave it Terminating forever.
    Exhaustion is loud: the pod will sit Terminating until something
    else (a fleet/kubelet restart's re-list) re-drives it, so the
    operator must hear about it.

    clock (utils/clock.Clock) and rng (random.Random) are injectable
    for deterministic harnesses; the defaults are the real clock and
    the process RNG."""
    import random as _random

    from ..core.errors import Conflict, NotFound
    from ..utils.clock import REAL

    clock = clock or REAL
    rng = rng or _random

    def attempt() -> bool:
        try:
            client.delete("pods", pod.metadata.name,
                          pod.metadata.namespace,
                          grace_period_seconds=0, uid=pod.metadata.uid)
        except (NotFound, Conflict):
            pass  # outcome reached (gone or replaced)
        except Exception:
            return False
        return True

    if attempt():
        return

    def retry_loop():
        delay = backoff_s
        for _ in range(attempts - 1):
            # jittered: a fleet confirming thousands of pods against a
            # restarting apiserver must not replay them in one wave
            clock.sleep(delay * (0.5 + rng.random()))
            if attempt():
                return
            delay = min(delay * 2, 5.0)
        logger.warning(
            "confirm_pod_deletion: giving up on %s/%s after %d "
            "attempts; pod stays Terminating until a re-list re-drives "
            "the confirm", pod.metadata.namespace, pod.metadata.name,
            attempts)

    threading.Thread(target=retry_loop, daemon=True,
                     name=f"confirm-del-{pod.metadata.name}").start()
