"""Client-side resilience for the API plane: retry policy + breaker.

Reference: client-go's request retry machinery (rest/request.go
Retry-After handling, util/flowcontrol backoff), reduced to the pieces
this control plane needs — no per-request flowcontrol, one
consecutive-failure circuit breaker per client (DIVERGENCES.md).

Error classification:
  - 429/503 API responses are UNAMBIGUOUS: the server answered without
    committing the verb (the 429 shed happens before routing; a 503
    found no backend to hand the request to). Every verb retries them,
    honoring a server-sent Retry-After.
  - Connection-class failures (URLError, reset, timeout) are AMBIGUOUS:
    the request may or may not have committed server-side. Only
    idempotent requests retry — GET/LIST, DELETE carrying a uid
    precondition, PUT carrying a resourceVersion (a replayed commit
    surfaces as Conflict, a real signal callers already handle). A bare
    POST is never replayed: a duplicate create is not idempotent.

The breaker counts CONSECUTIVE connection-class failures only — any
HTTP response (even an error status) proves the server alive and
resets it. Once open, calls fast-fail without touching the socket; at
most one caller per probe interval GETs /healthz, and a healthy answer
closes the breaker.
"""

from __future__ import annotations

import http.client
import threading
from typing import Callable, Optional

from ..core.errors import ServiceUnavailable
from ..utils.clock import REAL, Clock

#: API status codes every verb may retry (see module docstring).
RETRYABLE_CODES = (429, 503)

#: ambiguous transport failures (urllib.error.URLError is an OSError;
#: socket.timeout, ConnectionError, RemoteDisconnected all land here)
CONNECTION_ERRORS = (OSError, http.client.HTTPException)


class CircuitBreaker:
    """Consecutive-failure breaker with a rate-limited /healthz probe.

    threshold <= 0 disables the breaker entirely (allow() is always
    True and failures are not counted)."""

    def __init__(self, threshold: int = 5, probe_interval: float = 1.0,
                 clock: Optional[Clock] = None):
        self.threshold = threshold
        self.probe_interval = probe_interval
        # all breaker timing is on Clock.monotonic(): probe pacing must
        # not stretch or collapse under a wall-clock step
        self.clock = clock or REAL
        self._lock = threading.Lock()
        self._failures = 0
        self._open = False
        self._next_probe = 0.0

    @property
    def open(self) -> bool:
        return self._open

    def record_failure(self) -> None:
        if self.threshold <= 0:
            return
        with self._lock:
            self._failures += 1
            if self._failures >= self.threshold and not self._open:
                self._open = True
                # probe allowed at once
                self._next_probe = self.clock.monotonic()

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._open = False

    def allow(self, probe: Optional[Callable[[], bool]] = None) -> bool:
        """True if a call may proceed. When open, at most one caller
        per probe_interval runs `probe()`; a healthy probe closes the
        breaker and admits the caller."""
        if not self._open:
            return True
        with self._lock:
            if not self._open:
                return True
            now = self.clock.monotonic()
            if now < self._next_probe:
                return False
            self._next_probe = now + self.probe_interval
        if probe is not None and probe():
            self.record_success()
            return True
        return False


class RetryPolicy:
    """Jittered exponential backoff under a per-call deadline budget.

    seed: fix the jitter stream (chaos/determinism harnesses); None
    draws from the process RNG.

    clock: a utils/clock.Clock — deadline budgets and backoff pacing
    run on its monotonic() axis, so a wall-clock step (NTP correction,
    VM migration) can neither starve a call of its budget nor grant it
    extra attempts, the same jump-immunity contract leader election
    holds (tests/test_retry.py pins it with FakeClock.jump_wall).
    sleep: overrides clock.sleep (tests that only count delays).
    """

    def __init__(self, max_attempts: int = 4,
                 initial_backoff: float = 0.05, max_backoff: float = 2.0,
                 deadline: float = 30.0, jitter: float = 0.5,
                 breaker_threshold: int = 5,
                 breaker_probe_interval: float = 1.0,
                 seed=None, sleep: Optional[Callable] = None,
                 clock: Optional[Clock] = None):
        import random
        self.max_attempts = max(1, max_attempts)
        self.initial_backoff = initial_backoff
        self.max_backoff = max_backoff
        self.deadline = deadline
        self.jitter = jitter
        self.breaker_threshold = breaker_threshold
        self.breaker_probe_interval = breaker_probe_interval
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self.clock = clock or REAL
        self.sleep = sleep or self.clock.sleep

    @classmethod
    def disabled(cls) -> "RetryPolicy":
        """A policy that never retries and never opens the breaker."""
        return cls(max_attempts=1, breaker_threshold=0)

    def make_breaker(self) -> CircuitBreaker:
        return CircuitBreaker(self.breaker_threshold,
                              self.breaker_probe_interval, self.clock)

    def _delay(self, attempt: int,
               retry_after: Optional[float]) -> float:
        base = min(self.max_backoff,
                   self.initial_backoff * (2.0 ** (attempt - 1)))
        with self._rng_lock:
            delay = base * (1.0 + self.jitter * self._rng.random())
        if retry_after:
            # the server named a floor; jittered backoff may exceed it
            delay = max(delay, float(retry_after))
        return delay

    def call(self, fn: Callable, idempotent: bool = False,
             breaker: Optional[CircuitBreaker] = None,
             probe: Optional[Callable[[], bool]] = None):
        """Run fn() under this policy. fn must raise ApiError for HTTP
        status failures and a CONNECTION_ERRORS member for transport
        failures; anything else propagates unretried."""
        from ..core.errors import ApiError
        deadline = (self.clock.monotonic() + self.deadline
                    if self.deadline else None)
        attempt = 0
        while True:
            attempt += 1
            if breaker is not None and not breaker.allow(probe):
                raise ServiceUnavailable(
                    "circuit breaker open: apiserver unreachable "
                    "(awaiting healthy /healthz probe)")
            try:
                result = fn()
            except ApiError as e:
                # any HTTP response proves the server alive
                if breaker is not None:
                    breaker.record_success()
                if e.code not in RETRYABLE_CODES \
                        or attempt >= self.max_attempts:
                    raise
                delay = self._delay(attempt,
                                    getattr(e, "retry_after", None))
                if deadline is not None \
                        and self.clock.monotonic() + delay > deadline:
                    raise
                self.sleep(delay)
            except CONNECTION_ERRORS:
                if breaker is not None:
                    breaker.record_failure()
                if not idempotent or attempt >= self.max_attempts:
                    raise
                delay = self._delay(attempt, None)
                if deadline is not None \
                        and self.clock.monotonic() + delay > deadline:
                    raise
                self.sleep(delay)
            else:
                if breaker is not None:
                    breaker.record_success()
                return result
