"""Client-side caches: reflector, thread-safe store, FIFO, informer.

Reference mapping:
  - Reflector.ListAndWatch (pkg/client/cache/reflector.go:225): list, record
    resourceVersion, watch from it, re-list on 410 Expired.
  - ThreadSafeStore / cache.Store (pkg/client/cache/store.go): keyed object
    cache behind a lock; listers read it.
  - FIFO (pkg/client/cache/fifo.go:168 Pop): coalescing pop-queue of objects —
    the scheduler's pending-pod queue.
  - framework.NewInformer (pkg/controller/framework/controller.go:211):
    reflector + OnAdd/OnUpdate/OnDelete handlers.

Threading model: one reflector thread per watch; handlers run on the
reflector thread (same as the reference's single processLoop goroutine) so a
slow handler backpressures the watch, not the store.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import labels as labelspkg
from ..core.errors import ApiError, Expired
from ..core import watch as watchpkg

logger = logging.getLogger("kubernetes_tpu.cache")


def meta_namespace_key(obj: Any) -> str:
    """ns/name key (ref: cache.MetaNamespaceKeyFunc)."""
    m = obj.metadata
    return f"{m.namespace}/{m.name}" if m.namespace else m.name


class ObjectCache:
    """Thread-safe keyed object store."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._items: Dict[str, Any] = {}
        self._synced = threading.Event()

    def replace(self, items: List[Any]) -> None:
        with self._lock:
            self._items = {meta_namespace_key(o): o for o in items}
        self._synced.set()

    def add(self, obj: Any) -> None:
        with self._lock:
            self._items[meta_namespace_key(obj)] = obj

    update = add

    def delete(self, obj: Any) -> None:
        with self._lock:
            self._items.pop(meta_namespace_key(obj), None)

    def get_by_key(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._items.get(key)

    def list(self, selector: Optional[labelspkg.Selector] = None) -> List[Any]:
        with self._lock:
            items = list(self._items.values())
        if selector is not None and not selector.empty():
            items = [o for o in items if selector.matches(o.metadata.labels)]
        return items

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._items.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_for_sync(self, timeout: float = 30.0) -> bool:
        return self._synced.wait(timeout)


class FIFO:
    """Coalescing object queue; Pop blocks (ref: fifo.go). Replace/add/update
    key by ns/name; a popped object is gone (no processing set — matches the
    reference FIFO, not DeltaFIFO).

    Pop order is priority-then-FIFO: objects carrying `spec.priority`
    (pods) pop highest-priority first, insertion order within a
    priority — the scheduler's pending queue must hand a preempting pod
    the capacity its evictions freed before any lower-priority backlog
    can steal it (the reference's priority scheduling queue; objects
    without the field all rank 0, which degenerates to plain FIFO)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._items: Dict[str, Any] = {}
        self._queue: deque = deque()
        self._stamps: Dict[str, float] = {}
        self._closed = False
        #: queue-wait of the most recently popped object (monotonic
        #: seconds from first enqueue to pop) — the scheduler reads it
        #: right after pop() to time the pipeline's "queue" stage; a
        #: plain attribute is enough because the pending queue has one
        #: consumer (matches the reference's single scheduling loop)
        self.last_pop_wait = 0.0

    def add(self, obj: Any) -> None:
        key = meta_namespace_key(obj)
        with self._cond:
            if key not in self._items:
                self._queue.append(key)
                # first-enqueue stamp: coalesced updates keep the
                # original arrival time (the pod has been waiting since
                # it first showed up, not since its last update)
                self._stamps.setdefault(key, time.monotonic())
            self._items[key] = obj
            self._cond.notify()

    update = add

    def delete(self, obj: Any) -> None:
        with self._cond:
            key = meta_namespace_key(obj)
            self._items.pop(key, None)
            self._stamps.pop(key, None)
            # key stays in deque; pop skips dead keys (add() may re-queue the
            # same key later — pop's items-membership check dedupes)

    @staticmethod
    def _priority_of(obj: Any) -> int:
        spec = getattr(obj, "spec", None)
        return getattr(spec, "priority", 0) or 0

    def pop(self, timeout: Optional[float] = None) -> Optional[Any]:
        with self._cond:
            while True:
                # one sweep: compact dead keys out of the deque and pick
                # the highest-priority live key (first-seen wins a tie,
                # so an all-default queue pops in insertion order)
                best_key = None
                best_prio = 0
                live: deque = deque()
                while self._queue:
                    key = self._queue.popleft()
                    if key not in self._items:
                        continue  # deleted while queued
                    live.append(key)
                    prio = self._priority_of(self._items[key])
                    if best_key is None or prio > best_prio:
                        best_key, best_prio = key, prio
                self._queue = live
                if best_key is not None:
                    self._queue.remove(best_key)
                    stamp = self._stamps.pop(best_key, None)
                    self.last_pop_wait = (
                        time.monotonic() - stamp
                        if stamp is not None else 0.0)
                    return self._items.pop(best_key)
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def list(self) -> List[Any]:
        """Snapshot of pending objects (does not consume them)."""
        with self._cond:
            return list(self._items.values())

    def contains(self, key: str) -> bool:
        with self._cond:
            return key in self._items

    def __len__(self) -> int:
        # _items holds exactly the pending objects (popped/deleted keys are
        # removed), so this never double-counts re-added keys.
        with self._cond:
            return len(self._items)


#: Reflector re-list backoff: starts at the old fixed 50ms, doubles to
#: the cap with full jitter. 16 controllers x N informers against a
#: restarting apiserver settle at ~0.2 attempts/s per informer instead
#: of hammering it at 20/s each (the thundering-herd relist storm).
RELIST_BACKOFF_INITIAL = 0.05
RELIST_BACKOFF_MAX = 5.0
#: a list+watch session that survived this long was healthy — its
#: eventual death reconnects fast instead of inheriting stale backoff
HEALTHY_SESSION_S = 1.0


class Reflector:
    """List+watch a resource into a target (ObjectCache, FIFO, or handler
    triple). Crash-only: any watch error falls back to re-list, under
    capped jittered exponential backoff."""

    def __init__(self, client, resource: str, namespace: str = "",
                 label_selector: str = "", field_selector: str = "",
                 on_add: Optional[Callable[[Any], None]] = None,
                 on_update: Optional[Callable[[Any, Any], None]] = None,
                 on_delete: Optional[Callable[[Any], None]] = None,
                 store: Optional[Any] = None,
                 resync_period: float = 0.0,
                 backoff_initial: float = RELIST_BACKOFF_INITIAL,
                 backoff_max: float = RELIST_BACKOFF_MAX):
        self.client = client
        self.resource = resource
        self.namespace = namespace
        self.label_selector = label_selector
        self.field_selector = field_selector
        # selectors are immutable per reflector: parse once, not per event
        self._parsed_fields = None
        self._fields_fn = None
        self._field_match = None
        if field_selector:
            from ..core import fields as fieldspkg
            from .registry import (Registry, convert_field_selector,
                                   field_matcher)
            # same field-label conversion the server applies (legacy
            # aliases like spec.host rewrite; without it the client-side
            # re-check below would filter on the unconverted key and
            # drop every event the server-side selector admits)
            self._parsed_fields = convert_field_selector(
                resource, fieldspkg.parse(field_selector))
            info = Registry.info(resource)
            self._fields_fn = info.fields_fn
            # the shared matcher: compiled attribute reads for the
            # common selectors, the dict path otherwise
            self._field_match = field_matcher(info, self._parsed_fields)
        self._parsed_labels = (labelspkg.parse(label_selector)
                               if label_selector else None)
        self.store = store
        self.on_add = on_add
        self.on_update = on_update
        self.on_delete = on_delete
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watcher: Optional[watchpkg.Watcher] = None
        self._known: Dict[str, Any] = {}
        self.last_sync_rev = 0
        self.resync_period = resync_period
        self._last_resync = 0.0
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        #: observability for the fault tier: how many times the run
        #: loop recovered from a failed list/watch session
        self.reconnects = 0

    # The server-side field selector also filters here client-side because
    # watch events are not field-filtered by the in-proc store (the reference
    # filters in the apiserver; filtering at both ends is harmless).
    def _matches(self, obj: Any) -> bool:
        if self._field_match is not None and not self._field_match(obj):
            return False
        if self._parsed_labels is not None and \
                not self._parsed_labels.matches(obj.metadata.labels):
            return False
        return True

    def _list_and_watch(self) -> None:
        items, rev = self.client.list(self.resource, self.namespace,
                                      self.label_selector, self.field_selector)
        self.last_sync_rev = rev
        if self.store is not None and hasattr(self.store, "replace"):
            self.store.replace(items)
        else:
            for o in items:
                if self.store is not None:
                    self.store.add(o)
        # Diff against what we knew before this (re-)list so handlers see
        # exactly one on_add per object lifetime, on_delete for objects that
        # vanished while the watch was down, and on_update for ones that
        # changed (ref: DeltaFIFO Replace emits Sync/Delete deltas).
        new_known = {meta_namespace_key(o): o for o in items}
        for key, old in self._known.items():
            if key not in new_known:
                if self.store is not None and not hasattr(self.store, "replace"):
                    self.store.delete(old)
                if self.on_delete:
                    self.on_delete(old)
        for key, obj in new_known.items():
            old = self._known.get(key)
            if old is None:
                if self.on_add:
                    self.on_add(obj)
            elif old.metadata.resource_version != obj.metadata.resource_version:
                if self.on_update:
                    self.on_update(old, obj)
        self._known = prev = new_known  # aliased: the watch loop mutates it

        # selectors ride to the server: the store filters watch events
        # before they ever reach this watcher's queue (the client-side
        # _matches check stays — plain Watchers from tests and fakes
        # deliver unfiltered streams)
        w = self.client.watch(self.resource, self.namespace, since_rev=rev,
                              label_selector=self.label_selector,
                              field_selector=self.field_selector)
        self._watcher = w
        self._last_resync = time.monotonic()
        while not self._stop.is_set():
            ev = w.next(timeout=1.0)
            if (self.resync_period > 0 and self.on_update is not None
                    and time.monotonic() - self._last_resync
                    >= self.resync_period):
                # periodic resync: replay the known set through
                # on_update so LEVEL-driven controllers make progress
                # whose triggering condition produced no event on their
                # watched resource (the reference's informer resync —
                # DeltaFIFO Sync deltas; framework/controller.go
                # NewInformer resyncPeriod)
                self._last_resync = time.monotonic()
                for obj in list(prev.values()):
                    self.on_update(obj, obj)
            if ev is None:
                if w.stopped:
                    if getattr(w, "failed", False):
                        # mid-stream disconnect (HTTP watcher marks it;
                        # the ERROR event may have been shed by a full
                        # queue) — surface it so the run loop logs the
                        # reconnect and backs off
                        raise ApiError(
                            f"watch stream for {self.resource} failed")
                    return  # clean stop; outer loop re-lists at once
                continue
            if ev.type == watchpkg.ERROR:
                raise ev.object if isinstance(ev.object, ApiError) \
                    else ApiError(str(ev.object))
            obj = ev.object
            try:
                self.last_sync_rev = int(obj.metadata.resource_version or 0)
            except ValueError:
                pass
            key = meta_namespace_key(obj)
            relevant = self._matches(obj)
            was = prev.get(key)
            if ev.type == watchpkg.DELETED or not relevant:
                if was is not None:
                    prev.pop(key, None)
                    if self.store is not None:
                        self.store.delete(obj)
                    if self.on_delete:
                        self.on_delete(was)
                continue
            prev[key] = obj
            if self.store is not None:
                self.store.add(obj)
            if was is None:
                if self.on_add:
                    self.on_add(obj)
            else:
                if self.on_update:
                    self.on_update(was, obj)

    def run_once(self) -> None:
        self._list_and_watch()

    def _run(self) -> None:
        import random
        rng = random.Random()
        delay = self.backoff_initial
        while not self._stop.is_set():
            started = time.monotonic()
            try:
                self._list_and_watch()
                delay = self.backoff_initial  # clean stop: healthy server
            except Expired:
                # too-old resourceVersion: the server is healthy and
                # asking for a re-list — immediate, no backoff
                delay = self.backoff_initial
                continue
            except Exception as e:
                if self._stop.is_set():
                    return
                if time.monotonic() - started >= HEALTHY_SESSION_S:
                    # the session was established and lived — this is a
                    # fresh failure, not a continuing outage
                    delay = self.backoff_initial
                self.reconnects += 1
                logger.info("reflector %s: %r; re-list in <=%.2fs",
                            self.resource, e, delay)
                # full jitter: N informers re-listing against a
                # restarting apiserver spread out instead of herding
                self._stop.wait(delay * rng.random())
                delay = min(delay * 2.0, self.backoff_max)

    def start(self) -> "Reflector":
        self._thread = threading.Thread(
            target=self._run, name=f"reflector-{self.resource}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._watcher is not None:
            self._watcher.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)


class Informer:
    """Cache + reflector + handlers (ref: framework.NewInformer)."""

    def __init__(self, client, resource: str, namespace: str = "",
                 label_selector: str = "", field_selector: str = "",
                 on_add=None, on_update=None, on_delete=None,
                 resync_period: float = 0.0):
        self.cache = ObjectCache()
        self.reflector = Reflector(
            client, resource, namespace, label_selector, field_selector,
            on_add=on_add, on_update=on_update, on_delete=on_delete,
            store=self.cache, resync_period=resync_period)

    def start(self) -> "Informer":
        self.reflector.start()
        return self

    def stop(self) -> None:
        self.reflector.stop()

    @property
    def has_synced(self) -> bool:
        return self.cache.has_synced


# ------------------------------------------------------------------ listers

class StoreToPodLister:
    """(ref: pkg/client/cache/listers.go StoreToPodLister)"""

    def __init__(self, cache: ObjectCache):
        self.cache = cache

    def list(self, selector: Optional[labelspkg.Selector] = None) -> List[Any]:
        return self.cache.list(selector)

    def exists(self, pod: Any) -> bool:
        return self.cache.get_by_key(meta_namespace_key(pod)) is not None


class StoreToNodeLister:
    def __init__(self, cache: ObjectCache):
        self.cache = cache

    def list(self) -> List[Any]:
        return self.cache.list()


class StoreToServiceLister:
    """get_pod_services: services whose selector matches the pod's labels
    (ref: listers.go GetPodServices — empty-selector services match nothing
    there; we mirror that)."""

    def __init__(self, cache: ObjectCache):
        self.cache = cache

    def list(self) -> List[Any]:
        return self.cache.list()

    def get_pod_services(self, pod: Any) -> List[Any]:
        out = []
        for svc in self.cache.list():
            if svc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = svc.spec.selector
            if not sel:
                continue
            if labelspkg.selector_from_set(sel).matches(pod.metadata.labels):
                out.append(svc)
        return out


class StoreToReplicationControllerLister:
    def __init__(self, cache: ObjectCache):
        self.cache = cache

    def list(self) -> List[Any]:
        return self.cache.list()

    def get_pod_controllers(self, pod: Any) -> List[Any]:
        out = []
        for rc in self.cache.list():
            if rc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = rc.spec.selector
            if not sel:
                continue
            if labelspkg.selector_from_set(sel).matches(pod.metadata.labels):
                out.append(rc)
        return out
