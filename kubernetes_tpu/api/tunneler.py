"""Master->node tunneler.

Reference: pkg/master/tunneler.go — on clouds where the master cannot
reach node networks directly, master-originated node traffic (healthz,
kubelet API, pod proxying) rides secured tunnels the master maintains
to every node: an address-sync loop (1s cadence, backing off to ~10s
while healthy), a 5-minute full refresh, Dial() through a tunnel, and
SecondsSinceSync() feeding a master healthz gate.

TPU-native transport: there is no sshd in the picture, so the tunnel
leg is a websocket to the node kubelet's /tunnel endpoint, which dials
node-locally on the master's behalf (kubelet/server.py _tunnel) — the
same role sshd's direct-tcpip channel plays for the reference, with
the same loop structure and health surface. One divergence: the
reference holds one persistent SSH transport per node and multiplexes
dials over it; here each dial opens its own websocket leg (HTTP
keep-alive infrastructure makes per-dial legs cheap, and a dead node
fails the dial instead of a shared transport).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import wsstream

# (node_name, host, kubelet_port) per node
AddressFunc = Callable[[], List[Tuple[str, str, int]]]

TUNNEL_SYNC_HEALTHZ_MAX_S = 600  # ref: master.go tunnel healthz gate


class TunnelConn:
    """Socket-like view of one websocket tunnel leg: sendall/recv/close
    over binary frames (the client side of utils/wsstream.bridge)."""

    def __init__(self, ws: socket.socket):
        self._ws = ws
        self._buf = b""
        self._eof = False

    def sendall(self, data: bytes) -> None:
        wsstream.write_frame(self._ws.sendall, data, wsstream.BINARY,
                             mask=True)

    def recv(self, n: int) -> bytes:
        while not self._buf and not self._eof:
            try:
                opcode, payload = wsstream.read_frame(self._ws.recv)
            except TimeoutError:
                # a settimeout() expiry is the caller's signal (the
                # tunneled log-stream idle bound), NOT end-of-stream
                raise
            except (ConnectionError, OSError):
                # socket semantics: a recv blocked across shutdown()
                # (or an abruptly dead tunnel leg) reads EOF, it does
                # not raise — the relay pumps treat b"" as done
                self._eof = True
                break
            if opcode == wsstream.CLOSE:
                self._eof = True
                break
            if opcode == wsstream.BINARY and payload:
                self._buf += payload
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def close(self) -> None:
        try:
            wsstream.write_frame(self._ws.sendall, b"", wsstream.CLOSE,
                                 mask=True)
        except (ConnectionError, OSError):
            pass
        self._ws.close()

    def settimeout(self, t) -> None:
        self._ws.settimeout(t)

    def shutdown(self, how: int = socket.SHUT_RDWR) -> None:
        """socket.shutdown analogue so relay teardown paths
        (utils/wsstream.relay_ws) can unblock a peer pump thread:
        best-effort CLOSE frame, then shut the underlying socket so a
        blocked read returns immediately."""
        try:
            wsstream.write_frame(self._ws.sendall, b"", wsstream.CLOSE,
                                 mask=True)
        except (ConnectionError, OSError):
            pass
        self._eof = True
        try:
            self._ws.shutdown(how)
        except OSError:
            pass


def http_get_over(conn: TunnelConn, host: str, path: str,
                  timeout: float = 30.0):
    """One HTTP GET over an open tunnel leg (see http_request_over)."""
    return http_request_over(conn, host, path, timeout=timeout)


def http_request_over(conn: TunnelConn, host: str, path: str,
                      timeout: float = 30.0, method: str = "GET",
                      body: "bytes | None" = None,
                      content_type: str = ""):
    """One HTTP request over an open tunnel leg -> (status,
    content_type, body). HTTP/1.0 with Connection: close keeps the
    framing trivial (read to EOF) — the tunneled requests are the
    master's one-shot node calls (healthz, /pods, /stats, and the
    any-method proxy relay), exactly the SSH tunnel's traffic in the
    reference (master.go wires tunneler.Dial into the node-proxy
    transport; pkg/apiserver/proxy.go:52 relays every verb)."""
    conn.settimeout(timeout)
    head = (f"{method} {path} HTTP/1.0\r\nHost: {host}\r\n"
            f"Connection: close\r\n")
    if body is not None:
        head += f"Content-Length: {len(body)}\r\n"
        if content_type:
            head += f"Content-Type: {content_type}\r\n"
    conn.sendall(head.encode() + b"\r\n" + (body or b""))
    buf = b""
    while True:
        piece = conn.recv(65536)
        if not piece:
            break
        buf += piece
    head, _, body = buf.partition(b"\r\n\r\n")
    lines = head.split(b"\r\n")
    try:
        status = int(lines[0].split()[1])
    except (IndexError, ValueError):
        raise ConnectionError(
            f"malformed tunneled response: {lines[0][:100]!r}")
    ctype = "text/plain"
    for line in lines[1:]:
        if line.lower().startswith(b"content-type:"):
            ctype = line.split(b":", 1)[1].strip().decode()
    return status, ctype, body


def http_stream_over(conn: TunnelConn, host: str, path: str,
                     timeout: float = 30.0):
    """Streaming HTTP GET over a tunnel leg -> (status, content_type,
    chunk iterator). The iterator yields body pieces as they arrive
    until EOF (the follow-logs relay); the caller closes conn."""
    conn.settimeout(timeout)
    conn.sendall(f"GET {path} HTTP/1.0\r\nHost: {host}\r\n"
                 f"Connection: close\r\n\r\n".encode())
    buf = b""
    while b"\r\n\r\n" not in buf:
        piece = conn.recv(65536)
        if not piece:
            break
        buf += piece
    head, _, leftover = buf.partition(b"\r\n\r\n")
    lines = head.split(b"\r\n")
    try:
        status = int(lines[0].split()[1])
    except (IndexError, ValueError):
        raise ConnectionError(
            f"malformed tunneled response: {lines[0][:100]!r}")
    ctype = "text/plain"
    chunked = False
    for line in lines[1:]:
        if line.lower().startswith(b"content-type:"):
            ctype = line.split(b":", 1)[1].strip().decode()
        elif line.lower().startswith(b"transfer-encoding:") and \
                b"chunked" in line.lower():
            chunked = True

    def raw():
        # a follow stream can sit quiet for minutes between pieces, so
        # the handshake timeout must not tear the body phase down — but
        # a WEDGED node (a failure mode this deployment hits) must not
        # pin an apiserver handler thread forever either: bound the
        # idle gap at 15 min and let the timeout release the thread
        conn.settimeout(900.0)
        if leftover:
            yield leftover
        while True:
            piece = conn.recv(65536)
            if not piece:
                return
            yield piece

    if not chunked:
        return status, ctype, raw()

    def dechunked():
        # the kubelet streams follow bodies chunked; relaying the raw
        # framing would hand the client size lines as content — decode
        # the inner layer and yield clean payload pieces
        buf = b""
        src = raw()
        for piece in src:
            buf += piece
            while True:
                nl = buf.find(b"\r\n")
                if nl < 0:
                    break
                try:
                    size = int(buf[:nl].split(b";")[0], 16)
                except ValueError:
                    raise ConnectionError(
                        f"bad chunk size line: {buf[:nl][:40]!r}")
                if size == 0:
                    return
                # need size bytes + trailing CRLF after the size line
                while len(buf) < nl + 2 + size + 2:
                    try:
                        more = next(src)
                    except StopIteration:
                        return
                    buf += more
                yield buf[nl + 2:nl + 2 + size]
                buf = buf[nl + 2 + size + 2:]

    return status, ctype, dechunked()


class Tunneler:
    """(ref: tunneler.go:36 Tunneler interface)"""

    def run(self, address_func: AddressFunc) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        raise NotImplementedError

    def dial(self, host: str, port: int) -> TunnelConn:
        raise NotImplementedError

    def seconds_since_sync(self) -> int:
        raise NotImplementedError


class WsTunneler(Tunneler):
    """Maintains one verified tunnel endpoint per node (ref:
    SSHTunneler + util.SSHTunnelList)."""

    def __init__(self, sync_interval: float = 1.0,
                 healthy_sleep: float = 9.0,
                 refresh_interval: float = 300.0,
                 dial_timeout: float = 10.0, clock=time):
        self.sync_interval = sync_interval
        self.healthy_sleep = healthy_sleep
        self.refresh_interval = refresh_interval
        self.dial_timeout = dial_timeout
        self._clock = clock
        self._tunnels: Dict[str, Tuple[str, int]] = {}  # host -> (host, port)
        self._lock = threading.Lock()
        self._last_sync = 0.0
        self._stop: Optional[threading.Event] = None
        self._address_func: Optional[AddressFunc] = None
        self._threads: List[threading.Thread] = []

    # -------------------------------------------------------- lifecycle

    def run(self, address_func: AddressFunc) -> None:
        if self._stop is not None:
            return  # ref: Run is idempotent (tunneler.go:69)
        self._stop = threading.Event()
        self._address_func = address_func
        t1 = threading.Thread(target=self._sync_loop, daemon=True,
                              name="tunnel-sync")
        t2 = threading.Thread(target=self._refresh_loop, daemon=True,
                              name="tunnel-refresh")
        self._threads = [t1, t2]
        t1.start()
        t2.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()

    # ------------------------------------------------------------ loops

    def _verify(self, host: str, port: int) -> bool:
        """A tunnel endpoint is healthy when the kubelet answers a TCP
        connect (the SSH analogue: the transport handshake succeeds)."""
        try:
            with socket.create_connection((host, port),
                                          timeout=self.dial_timeout):
                return True
        except OSError:
            return False

    def _load(self, force: bool = False) -> None:
        addrs = self._address_func() if self._address_func else []
        want = {host: (host, port) for _name, host, port in addrs}
        with self._lock:
            changed = set(want) != set(self._tunnels)
        if not (changed or force):
            with self._lock:
                self._last_sync = self._clock.time()
            return
        verified = {h: hp for h, hp in want.items()
                    if self._verify(hp[0], hp[1])}
        with self._lock:
            self._tunnels = verified
            self._last_sync = self._clock.time()

    def _sync_loop(self) -> None:
        # ref: setupSecureProxy's 1s Until loop that sleeps ~10s while
        # tunnels exist
        while not self._stop.is_set():
            try:
                self._load()
            except Exception:
                pass  # crash-only: next tick retries
            with self._lock:
                healthy = bool(self._tunnels)
            self._stop.wait(self.sync_interval
                            + (self.healthy_sleep if healthy else 0.0))

    def _refresh_loop(self) -> None:
        # ref: the 5-minute full replaceTunnels loop
        while not self._stop.is_set():
            self._stop.wait(self.refresh_interval)
            if self._stop.is_set():
                return
            try:
                self._load(force=True)
            except Exception:
                pass

    # ------------------------------------------------------------- dial

    def dial(self, host: str, port: int) -> TunnelConn:
        """Open a tunnel leg to (host, port) through that node's own
        tunnel endpoint (the target is node-local from the kubelet's
        point of view). Divergence from the SSH list's pick-any-tunnel
        behavior: the kubelet /tunnel leg deliberately refuses
        non-local targets, so only tunneled nodes are dialable — the
        master's node traffic (healthz, kubelet API, pod relays) is
        exactly that set."""
        with self._lock:
            entry = self._tunnels.get(host)
        if entry is None:
            raise ConnectionError(
                f"no healthy tunnel to {host!r} (targets must be "
                f"tunneled nodes)")
        k_host, k_port = entry
        # dial the node's REGISTERED kubelet address, not loopback: a
        # kubelet bound only to its InternalIP serves nothing on
        # 127.0.0.1, and the kubelet-side node-local check admits its
        # own bind address (kubelet/server.py _tunnel)
        ws = wsstream.client_connect(
            k_host, k_port,
            f"/tunnel?host={k_host}&port={port}",
            timeout=self.dial_timeout)
        return TunnelConn(ws)

    def seconds_since_sync(self) -> int:
        with self._lock:
            then = self._last_sync
        return int(self._clock.time() - then)

    def healthy(self) -> bool:
        """The master healthz gate (ref: master.go IsTunnelSyncHealthy:
        lastSync within 600s)."""
        return self.seconds_since_sync() < TUNNEL_SYNC_HEALTHZ_MAX_S

    def tunnel_count(self) -> int:
        with self._lock:
            return len(self._tunnels)
