"""Shared kubelet-relay plumbing: the ApiServer's node proxy and the
in-proc client implement the same relay (resolve the node's daemon
endpoint, fetch, map errors; exec paths pass the CONNECT admission
moment first). One implementation, two mounts."""

from __future__ import annotations

import urllib.error
import urllib.request
from typing import Optional

from ..core.errors import BadGateway, NotFound


def exec_admission(registry, rest_path: str) -> None:
    """Run the CONNECT admission for a kubelet exec relay path
    (`exec/{ns}/{pod}/{container}...`) — DenyExecOnPrivileged's moment
    (ref: plugin/pkg/admission/exec). Non-exec paths are a no-op."""
    segments = [s for s in rest_path.split("?")[0].split("/") if s]
    if segments and segments[0] == "exec" and len(segments) >= 3 \
            and registry.admission is not None:
        registry.admission("CONNECT", "pods/exec", None,
                           segments[1], segments[2])


def fetch_kubelet(url: str, timeout: float = 30.0) -> bytes:
    """GET a kubelet-server URL with the client-side error mapping: 404
    passes through as NotFound, anything else wrong becomes 502."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            raise NotFound(e.read().decode(errors="replace"))
        raise BadGateway(f"kubelet answered {e.code}")
    except (urllib.error.URLError, OSError) as e:
        raise BadGateway(f"kubelet unreachable: {e}")


def fetch_kubelet_response(url: str, timeout: float = 30.0,
                           method: str = "GET",
                           body: "bytes | None" = None,
                           content_type: str = ""):
    """Any-method verbatim HTTP relay -> (status, content_type, body):
    backend statuses pass through untouched; only transport failures
    become 502 (what the ApiServer proxy forwards). The reference's
    ProxyHandler relays every verb with the request body intact
    (pkg/apiserver/proxy.go:52 ServeHTTP — no method filter)."""
    headers = {}
    if content_type:
        headers["Content-Type"] = content_type
    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return (resp.status, resp.headers.get("Content-Type",
                                                  "text/plain"),
                    resp.read())
    except urllib.error.HTTPError as e:
        return e.code, "text/plain", e.read()
    except (urllib.error.URLError, OSError) as e:
        raise BadGateway(f"kubelet unreachable: {e}")


def open_kubelet_stream(url: str, verbatim_errors: bool = False):
    """Open a follow-stream to the kubelet; caller closes.

    verbatim_errors=False (in-proc clients): typed error mapping —
    404 -> NotFound, other HTTP errors -> 502.
    verbatim_errors=True (the ApiServer's HTTP relay): kubelet HTTP
    statuses return as the response object itself (HTTPError doubles as
    one) so the proxy can pass status + body through untouched, exactly
    like its non-follow _relay path. Transport failures are 502 both
    ways."""
    try:
        return urllib.request.urlopen(url, timeout=None)
    except urllib.error.HTTPError as e:
        if verbatim_errors:
            return e
        if e.code == 404:
            raise NotFound(e.read().decode(errors="replace"))
        raise BadGateway(f"kubelet answered {e.code}")
    except (urllib.error.URLError, OSError) as e:
        raise BadGateway(f"kubelet unreachable: {e}")


def iter_http_stream(resp):
    """Yield decoded text pieces from a live HTTP response as they
    arrive (read1: return as soon as ANY data is buffered — a plain
    read(n) would block until n bytes amass, defeating `logs -f`)."""
    try:
        while True:
            data = resp.read1(65536)
            if not data:
                return
            yield data.decode(errors="replace")
    finally:
        resp.close()


def kubelet_base_for(registry, node_name: str) -> str:
    """Resolve a node's kubelet base URL from the registry, mapping a
    missing endpoint to NotFound."""
    from ..kubelet.server import kubelet_base_url

    node = registry.get("nodes", node_name)
    try:
        return kubelet_base_url(node)
    except KeyError as e:
        raise NotFound(str(e))


def resolve_pod_container(registry, namespace: str, name: str,
                          container: str = ""):
    """-> (container, kubelet base URL): scheduled-check,
    single-container defaulting, daemon-endpoint lookup. The ONE
    implementation behind the log, attach, and port-forward paths —
    container defaulting must not drift between them."""
    from ..core.errors import BadRequest

    pod = registry.get("pods", name, namespace)
    if not pod.spec.node_name:
        raise BadRequest(f"pod {name!r} is not scheduled yet")
    if not container:
        if len(pod.spec.containers) > 1:
            raise BadRequest(
                f"pod {name!r} has several containers; name one")
        container = pod.spec.containers[0].name
    return container, kubelet_base_for(registry, pod.spec.node_name)


def container_log_url(registry, namespace: str, name: str,
                      container: str = "", query: str = "") -> str:
    """Resolve a pod's kubelet containerLogs URL (see
    resolve_pod_container).

    query: pre-encoded query string without the '?' (e.g. 'follow=true')."""
    container, base = resolve_pod_container(registry, namespace, name,
                                            container)
    url = f"{base}/containerLogs/{namespace}/{name}/{container}"
    return url + (f"?{query}" if query else "")
