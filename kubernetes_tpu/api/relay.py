"""Shared kubelet-relay plumbing: the ApiServer's node proxy and the
in-proc client implement the same relay (resolve the node's daemon
endpoint, fetch, map errors; exec paths pass the CONNECT admission
moment first). One implementation, two mounts."""

from __future__ import annotations

import urllib.error
import urllib.request
from typing import Optional

from ..core.errors import BadGateway, NotFound


def exec_admission(registry, rest_path: str) -> None:
    """Run the CONNECT admission for a kubelet exec relay path
    (`exec/{ns}/{pod}/{container}...`) — DenyExecOnPrivileged's moment
    (ref: plugin/pkg/admission/exec). Non-exec paths are a no-op."""
    segments = [s for s in rest_path.split("?")[0].split("/") if s]
    if segments and segments[0] == "exec" and len(segments) >= 3 \
            and registry.admission is not None:
        registry.admission("CONNECT", "pods/exec", None,
                           segments[1], segments[2])


def fetch_kubelet(url: str, timeout: float = 30.0) -> bytes:
    """GET a kubelet-server URL with the client-side error mapping: 404
    passes through as NotFound, anything else wrong becomes 502."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        if e.code == 404:
            raise NotFound(e.read().decode(errors="replace"))
        raise BadGateway(f"kubelet answered {e.code}")
    except (urllib.error.URLError, OSError) as e:
        raise BadGateway(f"kubelet unreachable: {e}")


def fetch_kubelet_response(url: str, timeout: float = 30.0):
    """GET for a verbatim HTTP relay -> (status, content_type, body):
    kubelet statuses pass through untouched; only transport failures
    become 502 (what the ApiServer proxy forwards)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return (resp.status, resp.headers.get("Content-Type",
                                                  "text/plain"),
                    resp.read())
    except urllib.error.HTTPError as e:
        return e.code, "text/plain", e.read()
    except (urllib.error.URLError, OSError) as e:
        raise BadGateway(f"kubelet unreachable: {e}")


def open_kubelet_stream(url: str):
    """Open a follow-stream to the kubelet with the relay's error
    mapping (404 -> NotFound, transport -> 502); caller closes."""
    try:
        return urllib.request.urlopen(url, timeout=None)
    except urllib.error.HTTPError as e:
        if e.code == 404:
            raise NotFound(e.read().decode(errors="replace"))
        raise BadGateway(f"kubelet answered {e.code}")
    except (urllib.error.URLError, OSError) as e:
        raise BadGateway(f"kubelet unreachable: {e}")


def iter_http_stream(resp):
    """Yield decoded text pieces from a live HTTP response as they
    arrive (read1: return as soon as ANY data is buffered — a plain
    read(n) would block until n bytes amass, defeating `logs -f`)."""
    try:
        while True:
            data = resp.read1(65536)
            if not data:
                return
            yield data.decode(errors="replace")
    finally:
        resp.close()


def kubelet_base_for(registry, node_name: str) -> str:
    """Resolve a node's kubelet base URL from the registry, mapping a
    missing endpoint to NotFound."""
    from ..kubelet.server import kubelet_base_url

    node = registry.get("nodes", node_name)
    try:
        return kubelet_base_url(node)
    except KeyError as e:
        raise NotFound(str(e))
