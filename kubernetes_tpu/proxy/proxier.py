"""iptables-mode proxier: DNAT rule synthesis.

Reference: pkg/proxy/iptables/proxier.go — chains KUBE-SERVICES /
KUBE-NODEPORTS (:57-60), per-service KUBE-SVC-<hash> and per-endpoint
KUBE-SEP-<hash> chains, probability-split jump rules, full rebuild in
syncProxyRules (:453) on every services/endpoints change.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Tuple

from ..core import types as api
from .config import EndpointsConfig, ServiceConfig
from .iptables import IPTablesInterface, TABLE_NAT

KUBE_SERVICES_CHAIN = "KUBE-SERVICES"     # proxier.go:57
KUBE_NODEPORTS_CHAIN = "KUBE-NODEPORTS"   # proxier.go:58


def _chain_hash(*parts: str) -> str:
    """(ref: proxier.go servicePortChainName — hashed, upper, truncated)"""
    digest = hashlib.sha256("/".join(parts).encode()).hexdigest()
    return digest[:16].upper()


def service_chain(namespace: str, name: str, port: str) -> str:
    return "KUBE-SVC-" + _chain_hash(namespace, name, port)


def endpoint_chain(namespace: str, name: str, port: str,
                   endpoint: str) -> str:
    return "KUBE-SEP-" + _chain_hash(namespace, name, port, endpoint)


class IPTablesProxier:
    """Pure-iptables service proxy (DNAT; no packets traverse userspace)."""

    def __init__(self, iptables: IPTablesInterface,
                 client=None):
        self.iptables = iptables
        self._services: List[api.Service] = []
        self._endpoints: Dict[Tuple[str, str], api.Endpoints] = {}
        self._lock = threading.Lock()
        # serializes rule rebuilds — the services and endpoints feeds run
        # on separate reflector threads (the reference's proxier.mu)
        self._sync_lock = threading.Lock()
        self._service_config: Optional[ServiceConfig] = None
        self._endpoints_config: Optional[EndpointsConfig] = None
        if client is not None:
            self._service_config = ServiceConfig(client,
                                                 self.on_service_update)
            self._endpoints_config = EndpointsConfig(
                client, self.on_endpoints_update)

    # ------------------------------------------------------ config feed

    def on_service_update(self, services: List[api.Service]) -> None:
        with self._lock:
            self._services = list(services)
        self.sync_proxy_rules()

    def on_endpoints_update(self, endpoints: List[api.Endpoints]) -> None:
        with self._lock:
            self._endpoints = {(e.metadata.namespace, e.metadata.name): e
                               for e in endpoints}
        self.sync_proxy_rules()

    # ------------------------------------------------------------- sync

    def sync_proxy_rules(self) -> None:
        """Full rebuild (ref: proxier.go:453 syncProxyRules)."""
        with self._sync_lock:
            self._sync_proxy_rules_locked()

    def _sync_proxy_rules_locked(self) -> None:
        ipt = self.iptables
        with self._lock:
            services = list(self._services)
            endpoints_map = dict(self._endpoints)

        ipt.ensure_chain(TABLE_NAT, KUBE_SERVICES_CHAIN)
        ipt.ensure_chain(TABLE_NAT, KUBE_NODEPORTS_CHAIN)
        # root jumps: without these the synthesized chain graph is
        # unreachable — the reference installs PREROUTING/OUTPUT ->
        # KUBE-SERVICES in iptablesInit and the dst-type LOCAL ->
        # KUBE-NODEPORTS jump at the end of KUBE-SERVICES
        # (proxier.go:57-60, syncProxyRules)
        ipt.ensure_rule(TABLE_NAT, "PREROUTING",
                        "-m", "comment", "--comment",
                        "kubernetes service portals",
                        "-j", KUBE_SERVICES_CHAIN)
        ipt.ensure_rule(TABLE_NAT, "OUTPUT",
                        "-m", "comment", "--comment",
                        "kubernetes service portals",
                        "-j", KUBE_SERVICES_CHAIN)
        ipt.flush_chain(TABLE_NAT, KUBE_SERVICES_CHAIN)
        ipt.flush_chain(TABLE_NAT, KUBE_NODEPORTS_CHAIN)

        wanted_chains = {KUBE_SERVICES_CHAIN, KUBE_NODEPORTS_CHAIN}
        for svc in services:
            cluster_ip = svc.spec.cluster_ip
            if not cluster_ip or cluster_ip == "None":
                continue
            key = (svc.metadata.namespace, svc.metadata.name)
            eps = endpoints_map.get(key)
            for port in svc.spec.ports:
                port_name = port.name or str(port.port)
                svc_chain = service_chain(key[0], key[1], port_name)
                wanted_chains.add(svc_chain)
                ipt.ensure_chain(TABLE_NAT, svc_chain)
                ipt.flush_chain(TABLE_NAT, svc_chain)
                # clusterIP:port -> service chain
                ipt.ensure_rule(
                    TABLE_NAT, KUBE_SERVICES_CHAIN,
                    "-m", "comment", "--comment",
                    f"{key[0]}/{key[1]}:{port_name} cluster IP",
                    "-m", port.protocol.lower(), "-p",
                    port.protocol.lower(),
                    "-d", f"{cluster_ip}/32", "--dport", str(port.port),
                    "-j", svc_chain)
                # externalIPs route like a second cluster IP (ref:
                # proxier.go:237,327 — one DNAT entry per external IP
                # into the same service chain)
                for ext_ip in (svc.spec.external_ips or []):
                    ipt.ensure_rule(
                        TABLE_NAT, KUBE_SERVICES_CHAIN,
                        "-m", "comment", "--comment",
                        f"{key[0]}/{key[1]}:{port_name} external IP",
                        "-m", port.protocol.lower(), "-p",
                        port.protocol.lower(),
                        "-d", f"{ext_ip}/32", "--dport", str(port.port),
                        "-j", svc_chain)
                if port.node_port:
                    ipt.ensure_rule(
                        TABLE_NAT, KUBE_NODEPORTS_CHAIN,
                        "-m", "comment", "--comment",
                        f"{key[0]}/{key[1]}:{port_name}",
                        "-m", port.protocol.lower(), "-p",
                        port.protocol.lower(),
                        "--dport", str(port.node_port),
                        "-j", svc_chain)

                targets = self._endpoint_targets(eps, port)
                n = len(targets)
                affinity = svc.spec.session_affinity == "ClientIP"
                sep_chains = [endpoint_chain(key[0], key[1], port_name, t)
                              for t in targets]
                # SEP chains must exist before any -j references them
                for sep_chain in sep_chains:
                    wanted_chains.add(sep_chain)
                    ipt.ensure_chain(TABLE_NAT, sep_chain)
                    ipt.flush_chain(TABLE_NAT, sep_chain)
                if affinity:
                    # ClientIP stickiness: a client recently served by
                    # an endpoint re-enters its SEP chain directly
                    # (-m recent rcheck before the probability split;
                    # the SEP chain stamps --set) — proxier.go writes
                    # these alongside the random-split rules
                    for sep_chain in sep_chains:
                        ipt.ensure_rule(
                            TABLE_NAT, svc_chain,
                            "-m", "recent", "--name", sep_chain,
                            "--rcheck", "--seconds", "10800", "--reap",
                            "-j", sep_chain)
                for i, target in enumerate(targets):
                    sep_chain = sep_chains[i]
                    if affinity:
                        ipt.ensure_rule(
                            TABLE_NAT, sep_chain,
                            "-m", "recent", "--name", sep_chain,
                            "--set")
                    ipt.ensure_rule(
                        TABLE_NAT, sep_chain,
                        "-m", port.protocol.lower(), "-p",
                        port.protocol.lower(),
                        "-j", "DNAT", "--to-destination", target)
                    # probability split: each remaining rule picks
                    # 1/(n-i), the last is unconditional (proxier.go
                    # writeLine ... --probability)
                    if i < n - 1:
                        ipt.ensure_rule(
                            TABLE_NAT, svc_chain,
                            "-m", "statistic", "--mode", "random",
                            "--probability", f"{1.0 / (n - i):.5f}",
                            "-j", sep_chain)
                    else:
                        ipt.ensure_rule(TABLE_NAT, svc_chain,
                                        "-j", sep_chain)
                if not targets:
                    # no endpoints: reject (proxier.go REJECT for empty)
                    ipt.ensure_rule(
                        TABLE_NAT, svc_chain,
                        "-j", "REJECT", "--reject-with",
                        "icmp-port-unreachable")

        # the nodeports jump goes LAST in KUBE-SERVICES: only traffic
        # addressed to a local address falls through to nodeport
        # matching (proxier.go "--dst-type LOCAL -j KUBE-NODEPORTS")
        ipt.ensure_rule(TABLE_NAT, KUBE_SERVICES_CHAIN,
                        "-m", "comment", "--comment",
                        "kubernetes service nodeports",
                        "-m", "addrtype", "--dst-type", "LOCAL",
                        "-j", KUBE_NODEPORTS_CHAIN)

        # GC chains for services that no longer exist
        for chain in ipt.list_chains(TABLE_NAT):
            if chain.startswith(("KUBE-SVC-", "KUBE-SEP-")) and \
                    chain not in wanted_chains:
                ipt.flush_chain(TABLE_NAT, chain)
                ipt.delete_chain(TABLE_NAT, chain)

    @staticmethod
    def _endpoint_targets(eps: Optional[api.Endpoints],
                          port: api.ServicePort) -> List[str]:
        if eps is None:
            return []
        out = []
        for subset in eps.subsets:
            # strict name equality, empty matching empty — an unnamed
            # service port must not absorb every port of a multi-port
            # subset (pkg/api/v1 endpoint port matching semantics)
            for ep_port in subset.ports:
                if ep_port.name != (port.name or ""):
                    continue
                for addr in subset.addresses:
                    out.append(f"{addr.ip}:{ep_port.port}")
        return sorted(set(out))

    def run(self) -> "IPTablesProxier":
        """Start the watch-driven feeds (requires a client)."""
        if self._service_config:
            self._service_config.start()
        if self._endpoints_config:
            self._endpoints_config.start()
        return self

    def stop(self) -> None:
        if self._service_config:
            self._service_config.stop()
        if self._endpoints_config:
            self._endpoints_config.stop()
