"""Userspace-mode proxier: real TCP and UDP proxies with round-robin
balancing.

Reference: pkg/proxy/userspace/{proxier,roundrobin,proxysocket}.go —
one listening socket per service port, NextEndpoint round-robins across
the service's endpoints (with optional client-IP session affinity).
TCP shuttles bytes both ways per accepted connection; UDP tracks
clients in a conntrack cache with an idle timeout (udpIdleTimeout,
proxier.go:88,140) and pumps replies back through the service socket.
Functional in-process: connections and datagrams really balance.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core import types as api
from .config import EndpointsConfig, ServiceConfig


class RoundRobinLoadBalancer:
    """(ref: roundrobin.go LoadBalancerRR)"""

    def __init__(self, affinity_ttl: float = 180.0 * 60.0):
        # 180 MINUTES: the reference's ttlMinutes=180 default
        # (roundrobin.go NewLoadBalancerRR) — three hours, not 180s
        self._endpoints: Dict[Tuple[str, str, str], List[str]] = {}
        self._index: Dict[Tuple[str, str, str], int] = {}
        # (service, client_ip) -> (endpoint, stamp) when session affinity
        self._affinity: Dict[Tuple[Tuple[str, str, str], str],
                             Tuple[str, float]] = {}
        self._affinity_on: Dict[Tuple[str, str, str], bool] = {}
        self.affinity_ttl = affinity_ttl
        self._lock = threading.Lock()

    def set_session_affinity(self, key: Tuple[str, str, str],
                             on: bool) -> None:
        with self._lock:
            self._affinity_on[key] = on

    def on_endpoints_update(self, endpoints: List[api.Endpoints]) -> None:
        """(ref: roundrobin.go OnUpdate — state rebuilt per service)"""
        with self._lock:
            fresh: Dict[Tuple[str, str, str], List[str]] = {}
            for eps in endpoints:
                for subset in eps.subsets:
                    for port in subset.ports:
                        # keyed by port NAME only ("" when unnamed, valid
                        # for single-port services) — the service side
                        # keys the same way, so unnamed ports resolve
                        key = (eps.metadata.namespace, eps.metadata.name,
                               port.name or "")
                        fresh.setdefault(key, []).extend(
                            f"{a.ip}:{port.port}" for a in subset.addresses)
            self._endpoints = {k: sorted(set(v)) for k, v in fresh.items()}
            for key in list(self._index):
                if key not in self._endpoints:
                    del self._index[key]

    def next_endpoint(self, key: Tuple[str, str, str],
                      client_ip: str = "") -> Optional[str]:
        """(ref: roundrobin.go NextEndpoint)"""
        with self._lock:
            endpoints = self._endpoints.get(key)
            if not endpoints:
                return None
            if client_ip and self._affinity_on.get(key):
                hit = self._affinity.get((key, client_ip))
                if hit and hit[0] in endpoints and \
                        time.time() - hit[1] < self.affinity_ttl:
                    self._affinity[(key, client_ip)] = (hit[0], time.time())
                    return hit[0]
            i = self._index.get(key, 0) % len(endpoints)
            self._index[key] = i + 1
            chosen = endpoints[i]
            if client_ip and self._affinity_on.get(key):
                self._affinity[(key, client_ip)] = (chosen, time.time())
            return chosen


class _PortProxy:
    """One listening socket shuttling to balanced endpoints."""

    def __init__(self, balancer: RoundRobinLoadBalancer,
                 key: Tuple[str, str, str], host: str = "127.0.0.1",
                 port: int = 0):
        self.balancer = balancer
        self.key = key
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(64)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self.sock.accept()
            except OSError:
                if self._stop.is_set():
                    return  # closed by stop(): the loop is done
                # transient accept failure (ECONNABORTED, EMFILE under
                # load): the listener is still bound — exiting here
                # would wedge the service port forever while the proxy
                # stays registered (proxysocket.go ProxyLoop continues
                # on non-closed errors)
                time.sleep(0.1)
                continue
            threading.Thread(target=self._handle, args=(conn, addr[0]),
                             daemon=True).start()

    def _handle(self, conn: socket.socket, client_ip: str) -> None:
        target = self.balancer.next_endpoint(self.key, client_ip)
        if target is None:
            conn.close()
            return
        host, _, port = target.rpartition(":")
        try:
            upstream = socket.create_connection((host, int(port)),
                                                timeout=5)
            # the connect timeout must not become a read timeout — a slow
            # backend response would OSError the pump and half-close the
            # client mid-request
            upstream.settimeout(None)
        except OSError:
            conn.close()
            return
        for a, b in ((conn, upstream), (upstream, conn)):
            threading.Thread(target=self._pump, args=(a, b),
                             daemon=True).start()

    @staticmethod
    def _pump(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            # propagate EOF as a half-close only: the reverse pump keeps
            # relaying the response (classic request/shutdown protocols)
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass


class _UdpPortProxy:
    """One UDP service socket with per-client connection tracking.

    Reference: proxysocket.go udpProxySocket + clientCache — datagrams
    from a new client dial a balanced backend (a connected UDP socket);
    replies pump back through the service socket to that client; an
    idle client expires after udpIdleTimeout (proxier.go:88,140 — the
    conntrack entry's lifetime) and its backend socket closes. DNS —
    the canonical kubernetes service — rides this path."""

    BUF = 4096  # proxysocket.go:199 whole-packet buffer

    def __init__(self, balancer: RoundRobinLoadBalancer,
                 key: Tuple[str, str, str], host: str = "127.0.0.1",
                 port: int = 0, idle_timeout: float = 10.0):
        self.balancer = balancer
        self.key = key
        self.idle_timeout = idle_timeout
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        # client addr -> connected backend socket (the clientCache)
        self._clients: Dict[Tuple[str, int], socket.socket] = {}
        # client addr -> monotonic stamp of the LAST datagram either
        # direction (the conntrack deadline the reference resets on
        # every client write AND every reply, proxysocket.go
        # SetDeadline) — reply-pump recv timeouts consult it so a
        # one-way flow (statsd-style) never expires mid-stream
        self._last_seen: Dict[Tuple[str, int], float] = {}
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def active_clients(self) -> int:
        with self._lock:
            return len(self._clients)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, cli = self.sock.recvfrom(self.BUF)
            except OSError:
                if self._stop.is_set() or self.sock.fileno() < 0:
                    return
                continue  # transient (ENOBUFS/ICMP noise): keep serving
            backend = self._backend_for(cli)
            if backend is None:
                continue  # no endpoints: drop, like the reference
            try:
                backend.send(data)
                with self._lock:
                    self._last_seen[cli] = time.monotonic()
            except OSError:
                with self._lock:
                    self._clients.pop(cli, None)
                    self._last_seen.pop(cli, None)

    def _backend_for(self, cli: Tuple[str, int]
                     ) -> Optional[socket.socket]:
        with self._lock:
            backend = self._clients.get(cli)
            if backend is not None:
                return backend
            target = self.balancer.next_endpoint(self.key, cli[0])
            if target is None:
                return None
            host, _, port = target.rpartition(":")
            backend = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                backend.connect((host, int(port)))
            except OSError:
                backend.close()
                return None
            # the idle bound IS the conntrack TTL: traffic in either
            # direction resets it; expiry closes the backend and
            # forgets the client
            backend.settimeout(self.idle_timeout)
            self._clients[cli] = backend
            self._last_seen[cli] = time.monotonic()
            threading.Thread(target=self._reply_pump,
                             args=(cli, backend), daemon=True).start()
            return backend

    def _reply_pump(self, cli: Tuple[str, int],
                    backend: socket.socket) -> None:
        """(proxysocket.go proxyClient — replies ride the SERVICE
        socket so they come from the address the client sent to).
        A recv timeout only expires the entry when the whole flow —
        including client->backend datagrams — has been idle for the
        TTL; an empty datagram is legal UDP payload, not EOF."""
        try:
            while not self._stop.is_set():
                try:
                    data = backend.recv(self.BUF)
                except socket.timeout:
                    with self._lock:
                        seen = self._last_seen.get(cli, 0.0)
                    if time.monotonic() - seen >= self.idle_timeout:
                        return  # idle conntrack expiry
                    continue    # one-way flow still alive: keep waiting
                with self._lock:
                    self._last_seen[cli] = time.monotonic()
                self.sock.sendto(data, cli)
        except OSError:
            pass
        finally:
            with self._lock:
                if self._clients.get(cli) is backend:
                    del self._clients[cli]
                self._last_seen.pop(cli, None)
            backend.close()

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            self._last_seen.clear()
        for backend in clients:
            try:
                backend.close()
            except OSError:
                pass
        try:
            self.sock.close()
        except OSError:
            pass


class UserspaceProxier:
    """(ref: userspace/proxier.go Proxier — OnServiceUpdate opens/closes
    port proxies; localhost ports stand in for the service portal IPs).

    Virtual addresses (cluster IP, spec.externalIPs) are not
    materialized in this mode — the reference's userspace proxier
    programs iptables portals for them (openPortal over the service +
    public IPs); here the iptables MODE (proxy/proxier.py) carries
    that role, including the per-externalIP DNAT entries, while this
    mode's local stand-in ports cover the functional TCP/UDP relay
    semantics (affinity, conntrack, node ports)."""

    def __init__(self, client=None,
                 balancer: Optional[RoundRobinLoadBalancer] = None,
                 udp_idle_timeout: float = 10.0,
                 node_address: str = ""):
        self.balancer = balancer or RoundRobinLoadBalancer()
        self.udp_idle_timeout = udp_idle_timeout
        # NodePort listeners bind this address; "" = wildcard, so node
        # ports are reachable from other hosts like the reference's
        # claimNodePort (proxier.go) — portal-port proxies stay on
        # loopback (they stand in for virtual service IPs)
        self.node_address = node_address
        self._proxies: Dict[Tuple[str, str, str], object] = {}
        self._node_proxies: Dict[Tuple[str, str, str], object] = {}
        self._last_wanted: Dict[Tuple[str, str, str],
                                Tuple[str, int]] = {}
        self._stopped = threading.Event()
        self._lock = threading.Lock()
        self._service_config = None
        self._endpoints_config = None
        if client is not None:
            self._service_config = ServiceConfig(client,
                                                 self.on_service_update)
            self._endpoints_config = EndpointsConfig(
                client, self.balancer.on_endpoints_update)

    def on_service_update(self, services: List[api.Service]) -> None:
        # proto rides the wanted-map so a port that changes protocol
        # (proxier.go treats that as close-and-reopen) gets a fresh
        # proxy of the right kind; node_port rides it too — a NodePort
        # service ALSO listens on its fixed node port (proxier.go
        # openNodePort: the userspace mode claims host node ports)
        wanted: Dict[Tuple[str, str, str], "tuple[str, int]"] = {}
        for svc in services:
            for port in svc.spec.ports:
                key = (svc.metadata.namespace, svc.metadata.name,
                       port.name or "")
                wanted[key] = ((port.protocol or "TCP").upper(),
                               port.node_port or 0)
                self.balancer.set_session_affinity(
                    key, svc.spec.session_affinity == "ClientIP")
        with self._lock:
            self._last_wanted = wanted
            for key, proxy in list(self._proxies.items()):
                is_udp = isinstance(proxy, _UdpPortProxy)
                want = wanted.get(key)
                if want is None or (want[0] == "UDP") != is_udp:
                    self._proxies.pop(key).close()
            for key, node_proxy in list(self._node_proxies.items()):
                is_udp = isinstance(node_proxy, _UdpPortProxy)
                want = wanted.get(key)
                if (want is None or want[1] != node_proxy.port
                        or (want[0] == "UDP") != is_udp):
                    # gone, renumbered, or protocol-flipped: close (the
                    # reopen below gets the right kind)
                    self._node_proxies.pop(key).close()
            for key, (proto, node_port) in wanted.items():
                if key not in self._proxies:
                    self._proxies[key] = (
                        _UdpPortProxy(self.balancer, key,
                                      idle_timeout=self.udp_idle_timeout)
                        if proto == "UDP"
                        else _PortProxy(self.balancer, key))
            self._open_node_ports_locked()

    def _open_node_ports_locked(self) -> None:
        """Claim fixed node ports for NodePort services, both protocols
        (proxier.go openNodePort); a failed bind is logged and retried
        by the periodic timer — the config feed alone is change-driven
        and would never revisit it."""
        import logging
        for key, (proto, node_port) in self._last_wanted.items():
            if not node_port or key in self._node_proxies:
                continue
            try:
                self._node_proxies[key] = (
                    _UdpPortProxy(self.balancer, key, port=node_port,
                                  host=self.node_address,
                                  idle_timeout=self.udp_idle_timeout)
                    if proto == "UDP"
                    else _PortProxy(self.balancer, key, port=node_port,
                                    host=self.node_address))
            except OSError as e:
                logging.warning("node port %d for %s: %s", node_port,
                                "/".join(key[:2]), e)

    def _node_port_retry_loop(self) -> None:
        while not self._stopped.wait(10.0):
            with self._lock:
                if any(np and k not in self._node_proxies
                       for k, (_, np) in self._last_wanted.items()):
                    self._open_node_ports_locked()

    def port_for(self, namespace: str, name: str, port_name: str = ""
                 ) -> Optional[int]:
        with self._lock:
            proxy = self._proxies.get((namespace, name, port_name or ""))
            return proxy.port if proxy else None

    def run(self) -> "UserspaceProxier":
        """Start the watch-driven feeds (requires a client) and the
        node-port bind retry timer."""
        if self._service_config:
            self._service_config.start()
        if self._endpoints_config:
            self._endpoints_config.start()
        threading.Thread(target=self._node_port_retry_loop,
                         daemon=True, name="nodeport-retry").start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._service_config:
            self._service_config.stop()
        if self._endpoints_config:
            self._endpoints_config.stop()
        with self._lock:
            for proxy in self._proxies.values():
                proxy.close()
            self._proxies.clear()
            for proxy in self._node_proxies.values():
                proxy.close()
            self._node_proxies.clear()
