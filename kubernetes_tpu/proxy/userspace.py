"""Userspace-mode proxier: a real TCP proxy with round-robin balancing.

Reference: pkg/proxy/userspace/{proxier,roundrobin}.go — one listening
socket per service port, NextEndpoint round-robins across the service's
endpoints (with optional client-IP session affinity), bytes shuttled
both ways. Functional in-process: connections really balance.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core import types as api
from .config import EndpointsConfig, ServiceConfig


class RoundRobinLoadBalancer:
    """(ref: roundrobin.go LoadBalancerRR)"""

    def __init__(self, affinity_ttl: float = 180.0):
        self._endpoints: Dict[Tuple[str, str, str], List[str]] = {}
        self._index: Dict[Tuple[str, str, str], int] = {}
        # (service, client_ip) -> (endpoint, stamp) when session affinity
        self._affinity: Dict[Tuple[Tuple[str, str, str], str],
                             Tuple[str, float]] = {}
        self._affinity_on: Dict[Tuple[str, str, str], bool] = {}
        self.affinity_ttl = affinity_ttl
        self._lock = threading.Lock()

    def set_session_affinity(self, key: Tuple[str, str, str],
                             on: bool) -> None:
        with self._lock:
            self._affinity_on[key] = on

    def on_endpoints_update(self, endpoints: List[api.Endpoints]) -> None:
        """(ref: roundrobin.go OnUpdate — state rebuilt per service)"""
        with self._lock:
            fresh: Dict[Tuple[str, str, str], List[str]] = {}
            for eps in endpoints:
                for subset in eps.subsets:
                    for port in subset.ports:
                        # keyed by port NAME only ("" when unnamed, valid
                        # for single-port services) — the service side
                        # keys the same way, so unnamed ports resolve
                        key = (eps.metadata.namespace, eps.metadata.name,
                               port.name or "")
                        fresh.setdefault(key, []).extend(
                            f"{a.ip}:{port.port}" for a in subset.addresses)
            self._endpoints = {k: sorted(set(v)) for k, v in fresh.items()}
            for key in list(self._index):
                if key not in self._endpoints:
                    del self._index[key]

    def next_endpoint(self, key: Tuple[str, str, str],
                      client_ip: str = "") -> Optional[str]:
        """(ref: roundrobin.go NextEndpoint)"""
        with self._lock:
            endpoints = self._endpoints.get(key)
            if not endpoints:
                return None
            if client_ip and self._affinity_on.get(key):
                hit = self._affinity.get((key, client_ip))
                if hit and hit[0] in endpoints and \
                        time.time() - hit[1] < self.affinity_ttl:
                    self._affinity[(key, client_ip)] = (hit[0], time.time())
                    return hit[0]
            i = self._index.get(key, 0) % len(endpoints)
            self._index[key] = i + 1
            chosen = endpoints[i]
            if client_ip and self._affinity_on.get(key):
                self._affinity[(key, client_ip)] = (chosen, time.time())
            return chosen


class _PortProxy:
    """One listening socket shuttling to balanced endpoints."""

    def __init__(self, balancer: RoundRobinLoadBalancer,
                 key: Tuple[str, str, str], host: str = "127.0.0.1",
                 port: int = 0):
        self.balancer = balancer
        self.key = key
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(64)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn, addr[0]),
                             daemon=True).start()

    def _handle(self, conn: socket.socket, client_ip: str) -> None:
        target = self.balancer.next_endpoint(self.key, client_ip)
        if target is None:
            conn.close()
            return
        host, _, port = target.rpartition(":")
        try:
            upstream = socket.create_connection((host, int(port)),
                                                timeout=5)
            # the connect timeout must not become a read timeout — a slow
            # backend response would OSError the pump and half-close the
            # client mid-request
            upstream.settimeout(None)
        except OSError:
            conn.close()
            return
        for a, b in ((conn, upstream), (upstream, conn)):
            threading.Thread(target=self._pump, args=(a, b),
                             daemon=True).start()

    @staticmethod
    def _pump(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            # propagate EOF as a half-close only: the reverse pump keeps
            # relaying the response (classic request/shutdown protocols)
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass


class UserspaceProxier:
    """(ref: userspace/proxier.go Proxier — OnServiceUpdate opens/closes
    port proxies; localhost ports stand in for the service portal IPs)"""

    def __init__(self, client=None,
                 balancer: Optional[RoundRobinLoadBalancer] = None):
        self.balancer = balancer or RoundRobinLoadBalancer()
        self._proxies: Dict[Tuple[str, str, str], _PortProxy] = {}
        self._lock = threading.Lock()
        self._service_config = None
        self._endpoints_config = None
        if client is not None:
            self._service_config = ServiceConfig(client,
                                                 self.on_service_update)
            self._endpoints_config = EndpointsConfig(
                client, self.balancer.on_endpoints_update)

    def on_service_update(self, services: List[api.Service]) -> None:
        wanted: Dict[Tuple[str, str, str], api.Service] = {}
        for svc in services:
            for port in svc.spec.ports:
                key = (svc.metadata.namespace, svc.metadata.name,
                       port.name or "")
                wanted[key] = svc
                self.balancer.set_session_affinity(
                    key, svc.spec.session_affinity == "ClientIP")
        with self._lock:
            for key in list(self._proxies):
                if key not in wanted:
                    self._proxies.pop(key).close()
            for key in wanted:
                if key not in self._proxies:
                    self._proxies[key] = _PortProxy(self.balancer, key)

    def port_for(self, namespace: str, name: str, port_name: str = ""
                 ) -> Optional[int]:
        with self._lock:
            proxy = self._proxies.get((namespace, name, port_name or ""))
            return proxy.port if proxy else None

    def run(self) -> "UserspaceProxier":
        """Start the watch-driven feeds (requires a client)."""
        if self._service_config:
            self._service_config.start()
        if self._endpoints_config:
            self._endpoints_config.start()
        return self

    def stop(self) -> None:
        if self._service_config:
            self._service_config.stop()
        if self._endpoints_config:
            self._endpoints_config.stop()
        with self._lock:
            for proxy in self._proxies.values():
                proxy.close()
            self._proxies.clear()
