"""kube-proxy: the services -> endpoints dataplane.

Reference: pkg/proxy — two modes, both driven by the same watch feed
(pkg/proxy/config):

- iptables mode (pkg/proxy/iptables/proxier.go:453 syncProxyRules):
  synthesize DNAT rule chains (KUBE-SERVICES / KUBE-NODEPORTS /
  per-service KUBE-SVC-* / per-endpoint KUBE-SEP-*) against an iptables
  interface (pkg/util/iptables); tested against the fake the reference
  also uses (pkg/util/iptables/testing).
- userspace mode (pkg/proxy/userspace/proxier.go): a real in-process TCP
  proxy per service port with a round-robin load balancer
  (roundrobin.go) — functional here, not hollow: connections actually
  balance across endpoints.
"""

from .config import ServiceConfig, EndpointsConfig
from .iptables import FakeIPTables, IPTablesInterface
from .proxier import IPTablesProxier
from .userspace import RoundRobinLoadBalancer, UserspaceProxier

__all__ = [
    "ServiceConfig", "EndpointsConfig", "FakeIPTables",
    "IPTablesInterface", "IPTablesProxier", "RoundRobinLoadBalancer",
    "UserspaceProxier",
]
