"""Proxy config feed: services + endpoints watches -> handler callbacks.

Reference: pkg/proxy/config/{config,api}.go — ServiceConfig and
EndpointsConfig each deliver the FULL current state to their handlers on
every change (OnServiceUpdate(allServices)), which is what lets the
proxiers rebuild rules idempotently.
"""

from __future__ import annotations

import threading
from typing import Callable, List

from ..api.cache import Informer
from ..core import types as api


class _FullStateConfig:
    """Coalescing full-state delivery: informer events set a dirty flag;
    one delivery thread drains it (at most one rebuild per batch), so an
    initial sync of N objects triggers ~one delivery, not N — the
    reference rate-limits proxier syncs the same way."""

    COALESCE_DELAY = 0.02

    def __init__(self, client, resource: str, deliver: Callable):
        self._deliver = deliver
        self._dirty = threading.Event()
        self._stopped = threading.Event()
        self._thread = None
        self.informer = Informer(
            client, resource,
            on_add=lambda obj: self._dirty.set(),
            on_update=lambda old, new: self._dirty.set(),
            on_delete=lambda obj: self._dirty.set())

    def _loop(self) -> None:
        while not self._stopped.is_set():
            if not self._dirty.wait(timeout=0.5):
                continue
            # small window for the rest of the batch to arrive
            self._stopped.wait(self.COALESCE_DELAY)
            self._dirty.clear()
            try:
                self._deliver(self.informer.cache.list())
            except Exception:
                self._dirty.set()  # failed delivery: retry next pass

    def start(self):
        self.informer.start()
        self._dirty.set()  # initial full-state delivery
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="proxy-config")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        self.informer.stop()


class ServiceConfig(_FullStateConfig):
    """(ref: config.go NewServiceConfig; handler.OnServiceUpdate)"""

    def __init__(self, client, on_service_update: Callable[[List[api.Service]], None]):
        super().__init__(client, "services", on_service_update)


class EndpointsConfig(_FullStateConfig):
    """(ref: config.go NewEndpointsConfig; handler.OnEndpointsUpdate)"""

    def __init__(self, client,
                 on_endpoints_update: Callable[[List[api.Endpoints]], None]):
        super().__init__(client, "endpoints", on_endpoints_update)
