"""iptables interface + fake.

Reference: pkg/util/iptables (the exec-ing wrapper the proxier drives)
and pkg/util/iptables/testing (the fake kubemark's hollow-proxy uses).
The real binary isn't exercised here — the hollow/fake is the supported
execution mode, exactly like the reference's hollow-node proxy
(pkg/kubemark/hollow_proxy.go: fakeiptables.NewFake).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

TABLE_NAT = "nat"


class IPTablesInterface:
    """(ref: iptables.Interface — the subset syncProxyRules uses)"""

    def ensure_chain(self, table: str, chain: str) -> bool:
        """Returns True if the chain already existed."""
        raise NotImplementedError

    def flush_chain(self, table: str, chain: str) -> None:
        raise NotImplementedError

    def delete_chain(self, table: str, chain: str) -> None:
        raise NotImplementedError

    def ensure_rule(self, table: str, chain: str, *args: str) -> bool:
        """Append-if-absent. Returns True if the rule already existed."""
        raise NotImplementedError

    def list_chains(self, table: str) -> List[str]:
        raise NotImplementedError

    def list_rules(self, table: str, chain: str) -> List[Tuple[str, ...]]:
        raise NotImplementedError


class FakeIPTables(IPTablesInterface):
    def __init__(self):
        self._tables: Dict[str, Dict[str, List[Tuple[str, ...]]]] = {}
        self._lock = threading.Lock()

    def _table(self, table: str) -> Dict[str, List[Tuple[str, ...]]]:
        return self._tables.setdefault(table, {})

    def ensure_chain(self, table: str, chain: str) -> bool:
        with self._lock:
            t = self._table(table)
            existed = chain in t
            t.setdefault(chain, [])
            return existed

    def flush_chain(self, table: str, chain: str) -> None:
        with self._lock:
            self._table(table)[chain] = []

    def delete_chain(self, table: str, chain: str) -> None:
        with self._lock:
            self._table(table).pop(chain, None)

    def ensure_rule(self, table: str, chain: str, *args: str) -> bool:
        with self._lock:
            rules = self._table(table).setdefault(chain, [])
            if args in rules:
                return True
            rules.append(args)
            return False

    def list_chains(self, table: str) -> List[str]:
        with self._lock:
            return sorted(self._table(table))

    def list_rules(self, table: str, chain: str) -> List[Tuple[str, ...]]:
        with self._lock:
            return list(self._table(table).get(chain, []))
