"""iptables interface + fake.

Reference: pkg/util/iptables (the exec-ing wrapper the proxier drives)
and pkg/util/iptables/testing (the fake kubemark's hollow-proxy uses).
The real binary isn't exercised here — the hollow/fake is the supported
execution mode, exactly like the reference's hollow-node proxy
(pkg/kubemark/hollow_proxy.go: fakeiptables.NewFake).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

TABLE_NAT = "nat"


class IPTablesInterface:
    """(ref: iptables.Interface — the subset syncProxyRules uses)"""

    def ensure_chain(self, table: str, chain: str) -> bool:
        """Returns True if the chain already existed."""
        raise NotImplementedError

    def flush_chain(self, table: str, chain: str) -> None:
        raise NotImplementedError

    def delete_chain(self, table: str, chain: str) -> None:
        raise NotImplementedError

    def ensure_rule(self, table: str, chain: str, *args: str) -> bool:
        """Append-if-absent. Returns True if the rule already existed."""
        raise NotImplementedError

    def list_chains(self, table: str) -> List[str]:
        raise NotImplementedError

    def list_rules(self, table: str, chain: str) -> List[Tuple[str, ...]]:
        raise NotImplementedError


class FakeIPTables(IPTablesInterface):
    def __init__(self):
        self._tables: Dict[str, Dict[str, List[Tuple[str, ...]]]] = {}
        self._lock = threading.Lock()

    def _table(self, table: str) -> Dict[str, List[Tuple[str, ...]]]:
        return self._tables.setdefault(table, {})

    def ensure_chain(self, table: str, chain: str) -> bool:
        with self._lock:
            t = self._table(table)
            existed = chain in t
            t.setdefault(chain, [])
            return existed

    def flush_chain(self, table: str, chain: str) -> None:
        with self._lock:
            self._table(table)[chain] = []

    def delete_chain(self, table: str, chain: str) -> None:
        with self._lock:
            self._table(table).pop(chain, None)

    def ensure_rule(self, table: str, chain: str, *args: str) -> bool:
        with self._lock:
            rules = self._table(table).setdefault(chain, [])
            if args in rules:
                return True
            rules.append(args)
            return False

    def list_chains(self, table: str) -> List[str]:
        with self._lock:
            return sorted(self._table(table))

    def list_rules(self, table: str, chain: str) -> List[Tuple[str, ...]]:
        with self._lock:
            return list(self._table(table).get(chain, []))


class ExecIPTables(IPTablesInterface):
    """The exec-ing adapter (ref: pkg/util/iptables runner — shells out
    to the iptables binary). `runner` is injectable for tests; the
    default requires the binary and netfilter privileges, which hollow
    deployments don't have — they use FakeIPTables instead."""

    def __init__(self, runner=None, binary: str = "iptables"):
        import subprocess

        self.binary = binary
        self._run = runner or (lambda args: subprocess.run(
            args, capture_output=True, text=True, timeout=30))

    def _exec(self, *args: str):
        result = self._run([self.binary, *args])
        return result

    def _check(self, *args: str) -> None:
        result = self._exec(*args)
        if result.returncode != 0:
            raise RuntimeError(
                f"{self.binary} {' '.join(args)}: "
                f"{(result.stderr or '').strip()}")

    def ensure_chain(self, table: str, chain: str) -> bool:
        if self._exec("-t", table, "-L", chain, "-n").returncode == 0:
            return True
        self._check("-t", table, "-N", chain)
        return False

    def flush_chain(self, table: str, chain: str) -> None:
        self._check("-t", table, "-F", chain)

    def delete_chain(self, table: str, chain: str) -> None:
        self._check("-t", table, "-X", chain)

    def ensure_rule(self, table: str, chain: str, *args: str) -> bool:
        if self._exec("-t", table, "-C", chain, *args).returncode == 0:
            return True
        self._check("-t", table, "-A", chain, *args)
        return False

    def list_chains(self, table: str) -> List[str]:
        result = self._exec("-t", table, "-S")
        if result.returncode != 0:
            return []
        # "-P BUILTIN policy" and "-N USER-CHAIN" lines declare chains
        return [line.split()[1] for line in result.stdout.splitlines()
                if line.startswith(("-N ", "-P "))]

    def list_rules(self, table: str, chain: str) -> List[Tuple[str, ...]]:
        result = self._exec("-t", table, "-S", chain)
        if result.returncode != 0:
            return []
        return [tuple(line.split()[2:])
                for line in result.stdout.splitlines()
                if line.startswith("-A ")]
