"""`python -m kubernetes_tpu <component>` — the hyperkube entry
(ref: cmd/hyperkube/main.go:42)."""

import sys

from .hyperkube import main

sys.exit(main())
