"""Generic dataclass <-> JSON-dict serde.

The reference generates conversion/deep-copy code per type
(pkg/api/deep_copy_generated.go, pkg/api/v1/conversion_generated.go); here a
single reflective codec handles all API types: snake_case python fields map to
camelCase wire keys, nested dataclasses / lists / dicts / Quantity recurse,
and unset (None / empty) fields are omitted on the wire like Go's
`json:",omitempty"` tags.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Optional, Type, TypeVar, get_args, get_origin, get_type_hints

from .quantity import Quantity, parse_quantity

T = TypeVar("T")

_hints_cache: Dict[type, Dict[str, Any]] = {}

# Deprecated wire-key aliases, per dataclass: alias wire key -> python
# field name. The one the reference carries in v1 is
# `serviceAccount` <-> `serviceAccountName` (pkg/api/v1/types.go
# PodSpec.DeprecatedServiceAccount). On decode the alias fills the
# field only when the canonical key is absent or empty
# (pkg/api/v1/defaults.go copies DeprecatedServiceAccount into
# ServiceAccountName when the latter is unset); on encode the alias is
# emitted alongside the canonical key whenever the value is non-empty
# (conversion.go convert_api_PodSpec_To_v1_PodSpec mirrors the value
# into both). Populated by core.types at import.
WIRE_ALIASES: Dict[type, Dict[str, str]] = {}


def _camel(name: str) -> str:
    parts = name.split("_")
    out = parts[0] + "".join(p[:1].upper() + p[1:] for p in parts[1:])
    # Wire names like hostIP / podIP / clusterIP / externalID / podCIDR.
    for suf, rep in (("Ip", "IP"), ("Ips", "IPs"), ("Id", "ID"),
                     ("Cidr", "CIDR"), ("Uid", "UID"),
                     ("Url", "URL"), ("Tcp", "TCP"), ("Udp", "UDP"),
                     ("Pid", "PID"), ("Ipc", "IPC")):
        if out.endswith(suf):
            out = out[: -len(suf)] + rep
    return out


def _hints(cls: type) -> Dict[str, Any]:
    h = _hints_cache.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _hints_cache[cls] = h
    return h


def _unwrap_optional(tp: Any) -> Any:
    if get_origin(tp) is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def to_wire(obj: Any) -> Any:
    """Dataclass instance -> plain JSON-able structure, omitting empties."""
    if obj is None:
        return None
    if isinstance(obj, Quantity):
        return str(obj)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out: Dict[str, Any] = {}
        hints = _hints(type(obj))
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if v is None:
                continue
            # Optional[...] fields use None for absence, so a non-None
            # value is PRESENT even when all-default: `emptyDir: {}` on a
            # volume selects the volume type by existing. Dropping it
            # would decode back as None — lossy, unlike the cases below.
            optional = (get_origin(hints.get(f.name)) is typing.Union
                        and type(None) in get_args(hints[f.name]))
            if optional:
                out[_camel(f.name)] = to_wire(v)
                continue
            # omitempty relative to the declared default: a field at its
            # default decodes back identically, so dropping it is lossless
            # (and `replicas=0` still serializes, since its default is 1).
            if f.default is not dataclasses.MISSING and v == f.default:
                continue
            w = to_wire(v)
            if w is None or w == {} or w == []:
                continue
            out[_camel(f.name)] = w
        aliases = WIRE_ALIASES.get(type(obj))
        if aliases:
            for alias, fname in aliases.items():
                v = getattr(obj, fname)
                if v:
                    out[alias] = to_wire(v)
        return out
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    if isinstance(obj, bool) or isinstance(obj, (int, float, str)):
        return obj
    raise TypeError(f"cannot serialize {type(obj)!r}")


def from_wire(cls: Type[T], data: Any) -> T:
    """Plain JSON structure -> typed dataclass instance (lenient: unknown
    wire keys are ignored, missing keys take dataclass defaults)."""
    return _from_wire(cls, data)


def _from_wire(tp: Any, data: Any) -> Any:
    tp = _unwrap_optional(tp)
    if data is None:
        return None
    if tp is Quantity:
        return parse_quantity(data)
    if tp is Any:
        return data
    origin = get_origin(tp)
    if origin in (list, tuple):
        (elem,) = get_args(tp) or (Any,)
        vals = [_from_wire(elem, v) for v in data]
        return tuple(vals) if origin is tuple else vals
    if origin is dict:
        args = get_args(tp)
        vtp = args[1] if len(args) == 2 else Any
        return {k: _from_wire(vtp, v) for k, v in data.items()}
    if dataclasses.is_dataclass(tp):
        hints = _hints(tp)
        kwargs: Dict[str, Any] = {}
        wire_map = {_camel(f.name): f.name for f in dataclasses.fields(tp)}
        for wk, wv in (data or {}).items():
            fname = wire_map.get(wk)
            if fname is None:
                continue
            kwargs[fname] = _from_wire(hints[fname], wv)
        aliases = WIRE_ALIASES.get(tp)
        if aliases and isinstance(data, dict):
            for alias, fname in aliases.items():
                if alias in data and not kwargs.get(fname):
                    kwargs[fname] = _from_wire(hints[fname], data[alias])
        return tp(**kwargs)
    if tp is float and isinstance(data, int):
        return float(data)
    if tp is int and isinstance(data, float) and data == int(data):
        return int(data)
    return data


def wire_json(obj: Any) -> str:
    """JSON fragment for one API object, cached on the object keyed by
    its resourceVersion — the serialization row of the watch cache's
    job (pkg/storage/cacher.go keeps decoded objects; one hot LIST of
    5k nodes was ~1.9s of reflective re-walk per request without this,
    over the 1s API SLO all by itself).

    Safe because stored objects are frozen by the store contract and a
    non-empty resourceVersion changes on every store write. The two
    clone paths cannot serve stale fragments: dataclasses.replace
    reruns __init__ (no private attrs survive) and types.fast_replace
    strips the cache attribute explicitly (a modified clone shares its
    metadata/rv until the store restamps it, so the rv alone would not
    invalidate)."""
    import json as _json
    meta = getattr(obj, "metadata", None)
    rv = getattr(meta, "resource_version", "") if meta is not None else ""
    if rv:
        c = obj.__dict__.get("_wire_json")
        if c is not None and c[0] == rv:
            return c[1]
    s = _json.dumps(to_wire(obj))
    if rv:
        obj.__dict__["_wire_json"] = (rv, s)
    return s
