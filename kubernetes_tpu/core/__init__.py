from .quantity import Quantity, parse_quantity
from .errors import (
    ApiError,
    NotFound,
    AlreadyExists,
    Conflict,
    Invalid,
    BadRequest,
    Expired,
)
