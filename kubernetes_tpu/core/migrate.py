"""Storage-version migration: rewrite every stored object through the
current codec.

Reference: hack/test-update-storage-objects.sh — the reference
upgrades stored objects across API versions by reading each object and
writing it back through the new binary's codec (kubectl get | replace
against an apiserver running the target --storage-versions); its
pkg/conversion machinery (4,120 LoC of generated converters) does the
shape change in flight.

Here one wire version is served (DIVERGENCES #8), so migration's job
is NORMALIZATION: a store populated by an older build may hold JSON
with legacy/unknown fields (serde.from_wire drops them) or miss
newer fields (dataclass defaults fill them); rewriting re-encodes
every object in the current shape. A `transform` hook carries true
cross-version conversions (field renames, semantic rewrites) the day
there are two shapes — the role the reference's conversion functions
play.

Two entry points, mirroring the reference's two halves:
  - migrate_store(store): embedded path — walk a Store/NativeStore
    directly (the native store holds serialized bytes, so this is the
    real storage rewrite).
  - migrate_via_api(client): live-cluster path — GET each resource
    list and PUT every object back, exactly the script's
    kubectl-get-replace loop.

Both bump resourceVersions (so watchers observe MODIFIED, like any
write) and are idempotent — a second run rewrites again with no
semantic change.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .errors import NotFound

REGISTRY_PREFIX = "/registry/"


@dataclass
class MigrationReport:
    scanned: int = 0
    rewritten: int = 0
    failed: List[str] = field(default_factory=list)
    by_prefix: Dict[str, int] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"scanned": self.scanned, "rewritten": self.rewritten,
                "failed": self.failed, "by_prefix": self.by_prefix}


def migratable_resources() -> List[str]:
    """Every stored resource kind (componentstatuses are computed per
    request, never stored — the reference's script skips them too)."""
    from ..api.registry import RESOURCES
    return sorted(r for r in RESOURCES if r != "componentstatuses")


def migrate_store(store, transform: Optional[Callable] = None,
                  resources: Optional[List[str]] = None
                  ) -> MigrationReport:
    """Rewrite every stored object through the current codec.

    Works on both backends: list() decodes through the CURRENT
    from_wire (legacy fields drop, missing fields default), the
    optional transform applies the cross-version conversion, and a
    CAS write re-stores the object in the current encoding. Conflicts
    (a live writer won the race) re-read and retry via
    guaranteed_update — migration must never clobber newer state.
    (ThirdPartyResourceData lives under its own /registry/thirdparty/
    layout and is stored AS the carrier type, so the standard walk
    covers the declarations while custom objects re-encode through
    their carrier on read.)"""
    from ..api.registry import RESOURCES, Registry

    report = MigrationReport()
    for seg in (resources or migratable_resources()):
        info = RESOURCES.get(seg)
        if info is None:
            report.failed.append(f"{seg}: unknown resource")
            continue
        try:
            items, _rev = store.list(f"/registry/{seg}/")
        except Exception as e:
            # a corrupt value fails the whole segment's decode (list
            # is the only enumeration the store API affords) — report
            # it and KEEP WALKING the other resources
            report.failed.append(f"/registry/{seg}/: list: {e!r}")
            continue
        for obj in items:
            report.scanned += 1
            meta = obj.metadata
            key = Registry.key(seg, meta.namespace, meta.name)
            try:
                def rewrite(cur, _t=transform):
                    return _t(cur) if _t is not None else cur

                store.guaranteed_update(key, rewrite)
                report.rewritten += 1
                report.by_prefix[seg] = report.by_prefix.get(seg, 0) + 1
            except NotFound:
                # deleted (or TTL-expired: events) between list and
                # rewrite — the race migrate_via_api also tolerates;
                # a gone object needs no migration
                pass
            except Exception as e:  # keep walking; report stragglers
                report.failed.append(f"{key}: {e!r}")
    # custom-object data rides its own /registry/thirdparty/ layout
    # (registry.third_party_key): enumerate via the stored TPR
    # declarations so at-rest custom resources get rewritten too
    if resources is None:
        _migrate_third_party(store, transform, report)
    return report


def _migrate_third_party(store, transform, report: MigrationReport
                         ) -> None:
    from ..api.registry import extract_group_and_kind

    try:
        tprs, _ = store.list("/registry/thirdpartyresources/")
    except Exception as e:
        report.failed.append(f"thirdpartyresources: list: {e!r}")
        return
    for tpr in tprs:
        try:
            _kind, group, plural = extract_group_and_kind(tpr)
        except Exception as e:
            report.failed.append(
                f"tpr {tpr.metadata.name}: {e!r}")
            continue
        prefix = f"/registry/thirdparty/{group}/{plural}/"
        try:
            items, _ = store.list(prefix)
        except Exception as e:
            report.failed.append(f"{prefix}: list: {e!r}")
            continue
        for obj in items:
            report.scanned += 1
            meta = obj.metadata
            key = f"{prefix}{meta.namespace}/{meta.name}"
            try:
                def rewrite(cur, _t=transform):
                    return _t(cur) if _t is not None else cur

                store.guaranteed_update(key, rewrite)
                report.rewritten += 1
                report.by_prefix["thirdparty"] = \
                    report.by_prefix.get("thirdparty", 0) + 1
            except Exception as e:
                report.failed.append(f"{key}: {e!r}")


def migrate_via_api(client, resources: Optional[List[str]] = None
                    ) -> MigrationReport:
    """The live-cluster half: list each resource through the API and
    PUT every object straight back (the reference script's
    kubectl get | kubectl replace loop) — the apiserver re-encodes
    through its current codec on the way to storage."""
    from ..core.errors import Conflict, NotFound

    report = MigrationReport()
    if resources is None:
        resources = migratable_resources()
    for resource in resources:
        try:
            items, _ = client.list(resource, "")
        except Exception as e:
            report.failed.append(f"{resource}: list: {e!r}")
            continue
        for obj in items:
            report.scanned += 1
            try:
                client.update(resource, obj, obj.metadata.namespace)
                report.rewritten += 1
                report.by_prefix[resource] = \
                    report.by_prefix.get(resource, 0) + 1
            except (Conflict, NotFound):
                pass  # a live writer moved it; its write IS current
            except Exception as e:
                report.failed.append(
                    f"{resource}/{obj.metadata.name}: {e!r}")
    return report
