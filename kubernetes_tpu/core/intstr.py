"""IntOrString: a value that is either an absolute count or a
"25%"-style percentage string (ref: pkg/util/intstr + pkg/util/util.go
GetIntOrPercentValue/GetValueFromPercent). Deployment rollout bounds
and ingress/service backend ports ride the wire in this shape."""

from __future__ import annotations

import math


def resolve_int_or_percent(v, total: int) -> int:
    """IntOrString -> absolute count against `total` (v1.1 ceils BOTH
    maxSurge and maxUnavailable percentages, pkg/util/util.go:151).
    Invalid strings raise ValueError; callers either surface it as a
    validation error (registry) or retry with backoff (controllers)."""
    if isinstance(v, str):
        return math.ceil(int(v.replace("%", "").strip()) * total / 100)
    return int(v)
