"""Field selectors.

Reference: pkg/fields. The grammar is a comma-joined list of key=value /
key==value / key!=value terms over a flat map of field names. The scheduler's
load-bearing use is `spec.nodeName=` to watch only unassigned pods
(reference: plugin/pkg/scheduler/factory/factory.go:260-262); nodes use
`spec.unschedulable=false` (factory.go:281-285).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class FieldSelector:
    # tuples of (key, value, negate)
    terms: Tuple[Tuple[str, str, bool], ...] = ()

    def matches(self, fields: Dict[str, str]) -> bool:
        for key, value, negate in self.terms:
            actual = fields.get(key, "")
            if (actual == value) == negate:
                return False
        return True

    def empty(self) -> bool:
        return not self.terms

    def __str__(self) -> str:
        return ",".join(
            f"{k}!={v}" if neg else f"{k}={v}" for k, v, neg in self.terms
        )


def parse(s: Optional[str]) -> FieldSelector:
    s = (s or "").strip()
    if not s:
        return FieldSelector()
    terms = []
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            k, v = part.split("!=", 1)
            terms.append((k.strip(), v.strip(), True))
        elif "==" in part:
            k, v = part.split("==", 1)
            terms.append((k.strip(), v.strip(), False))
        elif "=" in part:
            k, v = part.split("=", 1)
            terms.append((k.strip(), v.strip(), False))
        else:
            raise ValueError(f"invalid field selector term {part!r}")
    return FieldSelector(tuple(terms))
