"""Watch primitives: typed event stream with bounded-queue fan-out.

Reference: pkg/watch (Interface, Event, Mux/Broadcaster). A watcher is an
iterator of (event_type, object); the broadcaster fans a stream out to many
watchers, dropping slow ones rather than blocking the writer (the reference's
Mux uses a full-channel policy; we mirror "stop the laggard" which is also
what the apiserver Cacher does).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Iterator, List, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
ERROR = "ERROR"


@dataclass(frozen=True)
class Event:
    type: str
    object: Any


class Watcher:
    """A single watch stream. Iterate to receive events; `stop()` ends it.

    One condition variable guards the queue AND the event-capacity
    counter: the store's fan-out calls send() once per watcher per write,
    so the previous two-lock layout (reserve lock + queue.Queue's mutex)
    paid double under the 30-writer benchmark load."""

    def __init__(self, capacity: int = 1000):
        self.capacity = capacity
        self._cond = threading.Condition()
        # items are Event or List[Event] (a batched send occupies one
        # slot but counts as len(events) toward capacity, so laggard
        # detection and the memory bound survive send_many)
        self._dq: deque = deque()
        self._count = 0
        self._stopped = threading.Event()
        # consumer-side buffer for batched sends; consumer-thread only
        self._pending: "deque[Event]" = deque()

    def send(self, event: Event) -> bool:
        """Enqueue an event without blocking. Returns False if the watcher is
        stopped or its queue is full (laggard — callers drop such watchers)."""
        if self._stopped.is_set():
            return False
        with self._cond:
            if self._count + 1 > self.capacity and self._count > 0:
                return False
            self._count += 1
            self._dq.append(event)
            self._cond.notify()
        return True

    def send_many(self, events: List[Event], owned: bool = False) -> bool:
        """Enqueue a batch as ONE queue slot — the store's tile-commit
        fan-out (30k bindings = a handful of puts per watcher instead of
        30k lock/notify cycles each). Consumers unwrap transparently.
        A single batch larger than capacity is admitted into an EMPTY
        watcher (it isn't lagging — the commit is just big); a watcher
        already holding events gets the strict bound.

        owned=True: the caller hands the list over and never touches it
        again (the store's publisher builds one fresh list per watcher
        per batch) — skip the defensive copy."""
        if not events:
            return True
        if self._stopped.is_set():
            return False
        n = len(events)
        with self._cond:
            if self._count + n > self.capacity and self._count > 0:
                return False
            self._count += n
            self._dq.append(events if owned else list(events))
            self._cond.notify()
        return True

    def fail(self, err: Any) -> None:
        """Terminate the stream with a visible ERROR event, then stop.

        The laggard path (the cacher's 410-Gone semantics,
        pkg/storage/cacher.go terminateAllWatchers): a watcher whose
        queue overran gets ONE final ERROR carrying the ApiError — past
        the capacity bound, deliberately, because the bound exists to
        limit data events, and a silent stop() here looks identical to
        a clean server-side close, so the client would never know to
        re-list. Consumers drain the backlog, see the ERROR, and
        recover via list + re-watch. Idempotent after stop()."""
        if self._stopped.is_set():
            return
        with self._cond:
            if self._stopped.is_set():
                return
            self._count += 1
            self._dq.append(Event(ERROR, err))
            self._cond.notify()
        self.stop()

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        with self._cond:
            self._cond.notify_all()

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def _take(self) -> Any:
        """Pop one queued item under the lock (caller holds _cond)."""
        item = self._dq.popleft()
        self._count -= len(item) if isinstance(item, list) else 1
        return item

    def __iter__(self) -> Iterator[Event]:
        while True:
            while self._pending:
                yield self._pending.popleft()
            with self._cond:
                while not self._dq:
                    if self._stopped.is_set():
                        # drain-then-stop: queued events were delivered
                        # above; nothing arrives after stop()
                        return
                    self._cond.wait()
                item = self._take()
            if isinstance(item, list):
                self._pending.extend(item)
            else:
                yield item

    def take_all(self) -> List[Event]:
        """Drain everything queued right now, without blocking — one
        lock hold for the whole backlog. The consumer-side counterpart
        of send_many: a 10k-watcher fan-out bench popping events one
        next() at a time would spend its wall-clock on lock churn
        instead of delivery."""
        out: List[Event] = list(self._pending)
        self._pending.clear()
        with self._cond:
            while self._dq:
                item = self._take()
                if isinstance(item, list):
                    out.extend(item)
                else:
                    out.append(item)
        return out

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Blocking pop with timeout; None on timeout or stop."""
        if self._pending:
            return self._pending.popleft()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while not self._dq:
                if self._stopped.is_set():
                    return None
                if deadline is None:
                    self._cond.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    return None
            item = self._take()
        if isinstance(item, list):
            self._pending.extend(item)
            return self._pending.popleft()
        return item


class Broadcaster:
    """Fan one event stream out to many watchers (ref: pkg/watch/mux.go)."""

    def __init__(self, queue_len: int = 1000):
        self._watchers: List[Watcher] = []
        self._lock = threading.Lock()
        self._queue_len = queue_len

    def watch(self) -> Watcher:
        w = Watcher(self._queue_len)
        with self._lock:
            self._watchers.append(w)
        return w

    def action(self, event_type: str, obj: Any) -> None:
        ev = Event(event_type, obj)
        with self._lock:
            alive = []
            for w in self._watchers:
                if w.stopped:
                    continue
                if w.send(ev):
                    alive.append(w)
                else:
                    w.stop()  # drop the laggard
            self._watchers = alive

    def shutdown(self) -> None:
        with self._lock:
            for w in self._watchers:
                w.stop()
            self._watchers = []
