"""Watch primitives: typed event stream with bounded-queue fan-out.

Reference: pkg/watch (Interface, Event, Mux/Broadcaster). A watcher is an
iterator of (event_type, object); the broadcaster fans a stream out to many
watchers, dropping slow ones rather than blocking the writer (the reference's
Mux uses a full-channel policy; we mirror "stop the laggard" which is also
what the apiserver Cacher does).
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Iterator, List, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
ERROR = "ERROR"


@dataclass(frozen=True)
class Event:
    type: str
    object: Any


_SENTINEL = object()


class Watcher:
    """A single watch stream. Iterate to receive events; `stop()` ends it."""

    def __init__(self, capacity: int = 1000):
        self.capacity = capacity
        self._q: "queue.Queue" = queue.Queue()
        self._stopped = threading.Event()
        # capacity is counted in EVENTS (a batched send occupies one
        # queue slot but many events), so laggard detection and the
        # memory bound survive send_many; producer-side lock only
        self._count = 0
        self._count_lock = threading.Lock()
        # consumer-side buffer for batched sends (one queue slot may hold
        # a whole tile's events); consumer-thread only, no lock needed
        self._pending: "deque[Event]" = deque()

    def _reserve(self, n: int) -> bool:
        with self._count_lock:
            # a single batch larger than capacity is admitted into an
            # EMPTY watcher (it isn't lagging — the commit is just big);
            # a watcher already holding events gets the strict bound
            if self._count + n > self.capacity and self._count > 0:
                return False
            self._count += n
            return True

    def _release(self, n: int) -> None:
        with self._count_lock:
            self._count -= n

    def send(self, event: Event) -> bool:
        """Enqueue an event without blocking. Returns False if the watcher is
        stopped or its queue is full (laggard — callers drop such watchers)."""
        if self._stopped.is_set() or not self._reserve(1):
            return False
        self._q.put_nowait(event)
        return True

    def send_many(self, events: List[Event]) -> bool:
        """Enqueue a batch as ONE queue slot — the store's tile-commit
        fan-out (30k bindings = a handful of puts per watcher instead of
        30k lock/notify cycles each). Consumers unwrap transparently."""
        if not events:
            return True
        if self._stopped.is_set() or not self._reserve(len(events)):
            return False
        self._q.put_nowait(list(events))
        return True

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        # the queue itself is unbounded (capacity is enforced by the
        # event counter in send/send_many), so the sentinel always lands
        self._q.put_nowait(_SENTINEL)

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def __iter__(self) -> Iterator[Event]:
        while True:
            while self._pending:
                yield self._pending.popleft()
            item = self._q.get()
            if item is _SENTINEL:
                # Drain-to-sentinel: deliver nothing after stop.
                return
            if isinstance(item, list):
                self._release(len(item))
                self._pending.extend(item)
                continue
            self._release(1)
            yield item

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Blocking pop with timeout; None on timeout or stop."""
        if self._pending:
            return self._pending.popleft()
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is _SENTINEL:
            return None
        if isinstance(item, list):
            self._release(len(item))
            self._pending.extend(item)
            return self._pending.popleft()
        self._release(1)
        return item


class Broadcaster:
    """Fan one event stream out to many watchers (ref: pkg/watch/mux.go)."""

    def __init__(self, queue_len: int = 1000):
        self._watchers: List[Watcher] = []
        self._lock = threading.Lock()
        self._queue_len = queue_len

    def watch(self) -> Watcher:
        w = Watcher(self._queue_len)
        with self._lock:
            self._watchers.append(w)
        return w

    def action(self, event_type: str, obj: Any) -> None:
        ev = Event(event_type, obj)
        with self._lock:
            alive = []
            for w in self._watchers:
                if w.stopped:
                    continue
                if w.send(ev):
                    alive.append(w)
                else:
                    w.stop()  # drop the laggard
            self._watchers = alive

    def shutdown(self) -> None:
        with self._lock:
            for w in self._watchers:
                w.stop()
            self._watchers = []
