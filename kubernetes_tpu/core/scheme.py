"""Scheme and codec: kind <-> type registry, JSON encode/decode.

Reference: pkg/runtime/scheme.go:241 (NewScheme), pkg/runtime/codec.go:27.
The reference maintains internal + versioned types with generated conversions;
we serve a single version ("v1") and convert reflectively (core.serde), so the
scheme is a kind registry plus encode/decode that injects/strips
kind/apiVersion, exactly the contract consumers of runtime.Codec rely on.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Type

from . import types as api
from .errors import BadRequest
from .serde import from_wire, to_wire, wire_json

API_VERSION = "v1"


class Scheme:
    def __init__(self) -> None:
        self._kind_to_type: Dict[str, type] = {}
        self._type_to_kind: Dict[type, str] = {}

    def register(self, kind: str, cls: type) -> None:
        self._kind_to_type[kind] = cls
        self._type_to_kind[cls] = kind

    def kind_for(self, obj: Any) -> str:
        try:
            return self._type_to_kind[type(obj)]
        except KeyError:
            raise BadRequest(f"unregistered type {type(obj).__name__}")

    def type_for(self, kind: str) -> type:
        try:
            return self._kind_to_type[kind]
        except KeyError:
            raise BadRequest(f"no kind {kind!r} is registered")

    # -- codec ------------------------------------------------------------

    def encode_dict(self, obj: Any) -> Dict[str, Any]:
        wire = to_wire(obj)
        wire["kind"] = self.kind_for(obj)
        wire["apiVersion"] = API_VERSION
        return wire

    def encode(self, obj: Any) -> str:
        return json.dumps(self.encode_dict(obj))

    def decode_dict(self, data: Dict[str, Any], expect: Optional[type] = None) -> Any:
        kind = data.get("kind", "")
        if not kind:
            if expect is None:
                raise BadRequest("object has no kind")
            cls = expect
        else:
            cls = self.type_for(kind)
        if expect is not None and cls is not expect:
            raise BadRequest(
                f"expected {self._type_to_kind.get(expect, expect.__name__)}, got {kind}"
            )
        return from_wire(cls, data)

    def decode(self, raw: str, expect: Optional[type] = None) -> Any:
        try:
            data = json.loads(raw)
        except json.JSONDecodeError as e:
            raise BadRequest(f"invalid JSON: {e}")
        return self.decode_dict(data, expect)

    def encode_list(self, kind: str, items, resource_version: str = "") -> Dict[str, Any]:
        return {
            "kind": kind + "List",
            "apiVersion": API_VERSION,
            "metadata": {"resourceVersion": resource_version},
            "items": [to_wire(i) for i in items],
        }

    def encode_list_bytes(self, kind: str, items,
                          resource_version: str = "") -> bytes:
        """encode_list, bytes-for-the-wire, assembled from per-object
        cached JSON fragments (serde.wire_json): a repeat LIST of an
        unchanged 5k-node fleet reuses 5k cached strings instead of
        5k reflective walks. Byte-identical to
        json.dumps(encode_list(...)) (tests pin it)."""
        head = json.dumps({
            "kind": kind + "List",
            "apiVersion": API_VERSION,
            "metadata": {"resourceVersion": resource_version}})
        return (head[:-1] + ', "items": ['
                + ", ".join(wire_json(i) for i in items)
                + "]}").encode()

    def deep_copy(self, obj: Any) -> Any:
        """Round-trip copy (the reference uses generated deep-copy; a codec
        round-trip gives identical semantics for registered types)."""
        return from_wire(type(obj), to_wire(obj))


def new_scheme() -> Scheme:
    s = Scheme()
    s.register("Pod", api.Pod)
    s.register("Node", api.Node)
    s.register("Service", api.Service)
    s.register("Endpoints", api.Endpoints)
    s.register("ReplicationController", api.ReplicationController)
    s.register("Binding", api.Binding)
    s.register("Lease", api.Lease)
    s.register("Event", api.Event)
    s.register("Namespace", api.Namespace)
    s.register("Secret", api.Secret)
    s.register("LimitRange", api.LimitRange)
    s.register("ResourceQuota", api.ResourceQuota)
    s.register("ServiceAccount", api.ServiceAccount)
    s.register("PersistentVolume", api.PersistentVolume)
    s.register("PersistentVolumeClaim", api.PersistentVolumeClaim)
    s.register("PodTemplate", api.PodTemplate)
    s.register("ComponentStatus", api.ComponentStatus)
    # extensions/v1beta1 group (master.go:1049-1091)
    s.register("Scale", api.Scale)
    s.register("DeleteOptions", api.DeleteOptions)
    s.register("Job", api.Job)
    s.register("Deployment", api.Deployment)
    s.register("DaemonSet", api.DaemonSet)
    s.register("HorizontalPodAutoscaler", api.HorizontalPodAutoscaler)
    s.register("Ingress", api.Ingress)
    s.register("ThirdPartyResource", api.ThirdPartyResource)
    # the storage form of custom objects (dynamic kinds encode through
    # encode_third_party on the wire, but stores serialize the carrier)
    s.register("ThirdPartyResourceData", api.ThirdPartyResourceData)
    return s


#: process-wide default scheme, like the reference's api.Scheme singleton
default_scheme = new_scheme()
