"""API object schema — the v1.1 subset the control plane operates on.

Reference: pkg/api/types.go (2161 LoC internal types) and pkg/api/v1/types.go
(wire form). We keep the same object model (ObjectMeta / Spec / Status,
camelCase wire names via serde) for the resources the scheduler, controllers,
agents and CLI need: Pod, Node, Service, Endpoints, ReplicationController,
Binding, Event, Namespace, plus small config resources.

All types are plain dataclasses; serialization is handled reflectively by
core.serde. Although the dataclasses are technically mutable, objects that
have passed through the store are FROZEN by contract (core.store docstring):
never mutate one in place — build modified copies with dataclasses.replace
(cheap shallow copies are safe under the same contract) or scheme.deep_copy,
and write them back through the store's CAS loop.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .quantity import Quantity

# Resource names (ref: pkg/api/types.go ResourceCPU/ResourceMemory/ResourcePods)
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"

# Pod phases (ref: pkg/api/types.go PodPhase)
POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"
POD_UNKNOWN = "Unknown"

# Condition types / statuses
POD_READY = "Ready"
NODE_READY = "Ready"
NODE_OUT_OF_DISK = "OutOfDisk"
CONDITION_TRUE = "True"
CONDITION_FALSE = "False"
CONDITION_UNKNOWN = "Unknown"


def fast_replace(obj, **fields):
    """dataclasses.replace without re-running __init__ — the hot-path
    clone for store revision stamping and binding assignment (measured
    ~3x cheaper; 30k bindings pay it 4x each). Safe because every API
    type here is a plain field dataclass: no __post_init__, no
    __slots__, no InitVar."""
    new = object.__new__(type(obj))
    new.__dict__.update(obj.__dict__)
    new.__dict__.update(fields)
    # a clone is a DIFFERENT object that still carries the original's
    # resourceVersion until the store restamps it — serde.wire_json's
    # rv-keyed fragment cache must not ride along or it would serve
    # the original's bytes for the modified clone
    new.__dict__.pop("_wire_json", None)
    return new


_now_cache = (0, "")  # (unix second, formatted) — timestamps have 1s grain


def expand_template_rows(template, names):
    """One template object -> rows with fresh per-row identity: name
    stamped, uid/resource_version/creation_timestamp cleared so the
    create path restamps them. A server-fetched template must not leak
    its source object's identity — or its age: keeping the fetched
    creation_timestamp would make brand-new rows sort as hours old for
    anything ordering by creation time. One implementation shared by
    Client.create_from_template and the registry's fallback path, so
    identity-reset semantics cannot drift between them."""
    return [fast_replace(template,
                         metadata=fast_replace(template.metadata, name=n,
                                               uid="",
                                               resource_version="",
                                               creation_timestamp=""))
            for n in names]

def now_rfc3339() -> str:
    global _now_cache
    t = int(time.time())
    cached = _now_cache
    if cached[0] != t:
        cached = (t, time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t)))
        _now_cache = cached  # tuple swap is atomic under the GIL
    return cached[1]


@dataclass
class ObjectMeta:
    name: str = ""
    generate_name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    creation_timestamp: str = ""
    deletion_timestamp: Optional[str] = None
    # seconds the object is granted to terminate gracefully, stamped by
    # the graceful-delete path together with deletionTimestamp (ref:
    # pkg/api/types.go ObjectMeta.DeletionGracePeriodSeconds)
    deletion_grace_period_seconds: Optional[int] = None
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    generation: int = 0


@dataclass
class ObjectReference:
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""
    api_version: str = ""
    resource_version: str = ""
    field_path: str = ""


@dataclass
class LocalObjectReference:
    name: str = ""


# ---------------------------------------------------------------- volumes

@dataclass
class GCEPersistentDiskVolumeSource:
    pd_name: str = ""
    fs_type: str = ""
    partition: int = 0
    read_only: bool = False


@dataclass
class AWSElasticBlockStoreVolumeSource:
    volume_id: str = ""
    fs_type: str = ""
    partition: int = 0
    read_only: bool = False


@dataclass
class RBDVolumeSource:
    ceph_monitors: List[str] = field(default_factory=list)
    rbd_image: str = ""
    rbd_pool: str = ""
    fs_type: str = ""
    read_only: bool = False


@dataclass
class EmptyDirVolumeSource:
    medium: str = ""


@dataclass
class HostPathVolumeSource:
    path: str = ""


@dataclass
class NFSVolumeSource:
    server: str = ""
    path: str = ""
    read_only: bool = False


@dataclass
class SecretVolumeSource:
    secret_name: str = ""


@dataclass
class DownwardAPIVolumeFile:
    """(ref: pkg/api/types.go:620 — a file at `path` carrying the pod
    field fieldRef selects; only annotations, labels, name, and
    namespace are supported)"""
    path: str = ""
    field_ref: Optional["ObjectFieldSelector"] = None


@dataclass
class DownwardAPIVolumeSource:
    """(ref: pkg/api/types.go:613 DownwardAPIVolumeSource; an empty
    items list projects the standard metadata field set)"""
    items: List[DownwardAPIVolumeFile] = field(default_factory=list)


@dataclass
class PersistentVolumeClaimVolumeSource:
    claim_name: str = ""
    read_only: bool = False


@dataclass
class GitRepoVolumeSource:
    repository: str = ""
    revision: str = ""


@dataclass
class ISCSIVolumeSource:
    """(ref: pkg/api/types.go ISCSIVolumeSource)"""
    target_portal: str = ""
    iqn: str = ""
    lun: int = 0
    fs_type: str = ""
    read_only: bool = False


@dataclass
class GlusterfsVolumeSource:
    """(ref: pkg/api/types.go GlusterfsVolumeSource)"""
    endpoints_name: str = ""
    path: str = ""
    read_only: bool = False


@dataclass
class CephFSVolumeSource:
    """(ref: pkg/api/types.go CephFSVolumeSource)"""
    monitors: List[str] = field(default_factory=list)
    user: str = ""
    secret_file: str = ""
    read_only: bool = False


@dataclass
class FCVolumeSource:
    """(ref: pkg/api/types.go FCVolumeSource)"""
    target_wwns: List[str] = field(default_factory=list)
    lun: int = 0
    fs_type: str = ""
    read_only: bool = False


@dataclass
class CinderVolumeSource:
    """(ref: pkg/api/types.go CinderVolumeSource)"""
    volume_id: str = ""
    fs_type: str = ""
    read_only: bool = False


@dataclass
class FlockerVolumeSource:
    """(ref: pkg/api/types.go FlockerVolumeSource)"""
    dataset_name: str = ""


@dataclass
class Volume:
    name: str = ""
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    aws_elastic_block_store: Optional[AWSElasticBlockStoreVolumeSource] = None
    rbd: Optional[RBDVolumeSource] = None
    empty_dir: Optional[EmptyDirVolumeSource] = None
    host_path: Optional[HostPathVolumeSource] = None
    nfs: Optional[NFSVolumeSource] = None
    secret: Optional[SecretVolumeSource] = None
    downward_api: Optional[DownwardAPIVolumeSource] = None
    persistent_volume_claim: Optional[PersistentVolumeClaimVolumeSource] = None
    git_repo: Optional[GitRepoVolumeSource] = None
    iscsi: Optional[ISCSIVolumeSource] = None
    glusterfs: Optional[GlusterfsVolumeSource] = None
    cephfs: Optional[CephFSVolumeSource] = None
    fc: Optional[FCVolumeSource] = None
    cinder: Optional[CinderVolumeSource] = None
    flocker: Optional[FlockerVolumeSource] = None


# ---------------------------------------------------------------- containers

@dataclass
class ContainerPort:
    name: str = ""
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class ResourceRequirements:
    limits: Dict[str, Quantity] = field(default_factory=dict)
    requests: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class ObjectFieldSelector:
    """Selects a field of the enclosing pod (ref: pkg/api/types.go
    ObjectFieldSelector; resolved by kubelet/envvars.py)."""
    api_version: str = "v1"
    field_path: str = ""


@dataclass
class EnvVarSource:
    """(ref: pkg/api/types.go:670 EnvVarSource — v1.1 has only
    FieldRef)"""
    field_ref: Optional[ObjectFieldSelector] = None


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""
    value_from: Optional[EnvVarSource] = None


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: bool = False


@dataclass
class ExecAction:
    command: List[str] = field(default_factory=list)


@dataclass
class HTTPGetAction:
    path: str = ""
    port: Any = None
    host: str = ""
    scheme: str = "HTTP"


@dataclass
class TCPSocketAction:
    port: Any = None


@dataclass
class Handler:
    """One action (ref: pkg/api/types.go:816 Handler — the union probes
    and lifecycle hooks share)."""
    exec: Optional[ExecAction] = None
    http_get: Optional[HTTPGetAction] = None
    tcp_socket: Optional[TCPSocketAction] = None


@dataclass
class Lifecycle:
    """(ref: pkg/api/types.go:831 Lifecycle — PostStart runs right
    after a container starts and kills it on failure; PreStop runs
    before a requested kill)"""
    post_start: Optional[Handler] = None
    pre_stop: Optional[Handler] = None


@dataclass
class Probe(Handler):
    """(ref: pkg/api/types.go Probe — literally a Handler embedded
    with timing knobs; inheriting keeps one copy of the action union
    and the identical wire shape)"""
    initial_delay_seconds: int = 0
    timeout_seconds: int = 1
    period_seconds: int = 10
    success_threshold: int = 1
    failure_threshold: int = 3


@dataclass
class Capabilities:
    """(ref: pkg/api/types.go Capabilities — linux capability names to
    grant/revoke at container create)"""
    add: List[str] = field(default_factory=list)
    drop: List[str] = field(default_factory=list)


@dataclass
class SecurityContext:
    """(ref: pkg/api/types.go SecurityContext; applied at the runtime
    boundary by kubelet/securitycontext.py, policed by the
    SecurityContextDeny admission plugin)"""
    capabilities: Optional[Capabilities] = None
    privileged: Optional[bool] = None
    run_as_user: Optional[int] = None
    run_as_non_root: Optional[bool] = None


@dataclass
class Container:
    """privileged is the flat pre-SecurityContext surface kept for
    wire compat; the reference nests it (SecurityContext.Privileged) —
    both are honored (kubelet/securitycontext.effective_privileged)."""
    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    ports: List[ContainerPort] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    image_pull_policy: str = ""
    privileged: bool = False
    security_context: Optional[SecurityContext] = None
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    lifecycle: Optional[Lifecycle] = None
    # ref: pkg/api/types.go:804 + :153 TerminationMessagePathDefault
    termination_message_path: str = "/dev/termination-log"
    # ref: pkg/api/types.go:813 Container.Stdin — only stdin:true
    # containers get a stdin pipe to attach to
    stdin: bool = False


@dataclass
class ContainerStateRunning:
    started_at: str = ""


@dataclass
class ContainerStateTerminated:
    exit_code: int = 0
    reason: str = ""
    message: str = ""  # the termination message (types.go Terminated)
    started_at: str = ""
    finished_at: str = ""


@dataclass
class ContainerStateWaiting:
    reason: str = ""


@dataclass
class ContainerState:
    waiting: Optional[ContainerStateWaiting] = None
    running: Optional[ContainerStateRunning] = None
    terminated: Optional[ContainerStateTerminated] = None


@dataclass
class ContainerStatus:
    name: str = ""
    state: ContainerState = field(default_factory=ContainerState)
    ready: bool = False
    restart_count: int = 0
    image: str = ""
    image_id: str = ""
    container_id: str = ""


# ---------------------------------------------------------------- pods

@dataclass
class PodAffinityTerm:
    """One required co/anti-location constraint: pods matching
    `label_selector` in `namespaces` (empty = the pod's own namespace),
    within the topology domain named by the node label `topology_key`.

    The v1.1 reference has no inter-pod affinity in-tree; this is the
    BASELINE config-4 extension (the quadratic pod x pod term), modeled on
    the scheduler's ServiceAffinity neighborhood semantics
    (predicates.go:334 — implicit affinity inherited from peer pods'
    node labels) generalized to explicit per-pod terms."""
    label_selector: Dict[str, str] = field(default_factory=dict)
    namespaces: List[str] = field(default_factory=list)
    topology_key: str = ""


@dataclass
class PodAffinity:
    required_during_scheduling: List[PodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required_during_scheduling: List[PodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass
class PodSpec:
    volumes: List[Volume] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    restart_policy: str = "Always"
    termination_grace_period_seconds: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    dns_policy: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    service_account_name: str = ""
    node_name: str = ""
    host_network: bool = False
    # host PID/IPC namespace sharing (ref: pkg/api/types.go
    # PodSecurityContext.HostPID/HostIPC, surfaced at the top level of
    # the v1 wire form by pkg/api/v1/conversion.go
    # convert_api_PodSpec_To_v1_PodSpec for v1.0.0 compatibility; the
    # runtime maps them to pid/ipc modes, dockertools/manager.go:1994)
    host_pid: bool = False
    host_ipc: bool = False
    # ref: pkg/api/types.go PodSpec.ImagePullSecrets — resolved by the
    # kubelet into a docker keyring (kubelet/credentialprovider.py)
    image_pull_secrets: List[LocalObjectReference] = field(
        default_factory=list)
    affinity: Optional[Affinity] = None
    # flat integer scheduling priority (higher preempts lower; default 0).
    # DIVERGENCES #35: the reference models this as PriorityClass objects
    # resolved at admission plus a nominatedNodeName protocol; here the
    # resolved integer lives directly on the spec so the device tables
    # can carry it as one i64 column.
    priority: int = 0


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class PodStatus:
    phase: str = ""
    conditions: List[PodCondition] = field(default_factory=list)
    message: str = ""
    reason: str = ""
    host_ip: str = ""
    pod_ip: str = ""
    start_time: Optional[str] = None
    container_statuses: List[ContainerStatus] = field(default_factory=list)


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


# ---------------------------------------------------------------- nodes

@dataclass
class NodeSpec:
    pod_cidr: str = ""
    external_id: str = ""
    provider_id: str = ""
    unschedulable: bool = False


@dataclass
class NodeCondition:
    type: str = ""
    status: str = ""
    last_heartbeat_time: str = ""
    last_transition_time: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class NodeAddress:
    type: str = ""
    address: str = ""


@dataclass
class NodeSystemInfo:
    machine_id: str = ""
    kernel_version: str = ""
    os_image: str = ""
    container_runtime_version: str = ""
    kubelet_version: str = ""


@dataclass
class DaemonEndpoint:
    """(ref: pkg/api/types.go DaemonEndpoint)"""
    port: int = 0


@dataclass
class NodeDaemonEndpoints:
    """Where the node's kubelet server listens
    (ref: pkg/api/types.go NodeDaemonEndpoints; served by
    pkg/kubelet/server.go and consumed by the apiserver node proxy)."""
    kubelet_endpoint: DaemonEndpoint = field(default_factory=DaemonEndpoint)


@dataclass
class NodeStatus:
    capacity: Dict[str, Quantity] = field(default_factory=dict)
    allocatable: Dict[str, Quantity] = field(default_factory=dict)
    phase: str = ""
    conditions: List[NodeCondition] = field(default_factory=list)
    addresses: List[NodeAddress] = field(default_factory=list)
    daemon_endpoints: NodeDaemonEndpoints = field(
        default_factory=NodeDaemonEndpoints)
    node_info: NodeSystemInfo = field(default_factory=NodeSystemInfo)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)


# ---------------------------------------------------------------- services

@dataclass
class ServicePort:
    name: str = ""
    protocol: str = "TCP"
    port: int = 0
    target_port: Any = None
    node_port: int = 0


@dataclass
class ServiceSpec:
    ports: List[ServicePort] = field(default_factory=list)
    selector: Dict[str, str] = field(default_factory=dict)
    cluster_ip: str = ""
    type: str = "ClusterIP"
    session_affinity: str = "None"
    # addresses outside the service range that also route to the
    # endpoints (ref: pkg/api/v1/types.go:1585 ExternalIPs; the wire
    # accepts the deprecatedPublicIPs alias — serde WIRE_ALIASES)
    external_ips: List[str] = field(default_factory=list)
    # requested address for a type=LoadBalancer service (ref:
    # pkg/api/v1/types.go:1606 — honored by providers that support
    # address reservation, best-effort elsewhere)
    load_balancer_ip: str = ""


@dataclass
class ServiceStatus:
    # external IPs assigned by the cloud LB controller (the reference
    # nests these under status.loadBalancer.ingress[].ip)
    load_balancer_ingress: List[str] = field(default_factory=list)


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: ServiceStatus = field(default_factory=ServiceStatus)


@dataclass
class EndpointAddress:
    ip: str = ""
    target_ref: Optional[ObjectReference] = None


@dataclass
class EndpointPort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class EndpointSubset:
    addresses: List[EndpointAddress] = field(default_factory=list)
    not_ready_addresses: List[EndpointAddress] = field(default_factory=list)
    ports: List[EndpointPort] = field(default_factory=list)


@dataclass
class Endpoints:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subsets: List[EndpointSubset] = field(default_factory=list)


# ------------------------------------------------- replication controllers

@dataclass
class ReplicationControllerSpec:
    replicas: int = 1
    selector: Dict[str, str] = field(default_factory=dict)
    template: Optional[PodTemplateSpec] = None


@dataclass
class ReplicationControllerStatus:
    replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicationController:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicationControllerSpec = field(default_factory=ReplicationControllerSpec)
    status: ReplicationControllerStatus = field(default_factory=ReplicationControllerStatus)


# ---------------------------------------------------------------- binding

@dataclass
class Binding:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    target: ObjectReference = field(default_factory=ObjectReference)


# ----------------------------------------------------------------- leases

@dataclass
class LeaseSpec:
    """coordination.k8s.io Lease spec, forward-ported from the later
    reference (the v1.1 reference elects its master through a raw etcd
    CAS seam; the typed Lease is what that seam became). The *Time
    fields are wall-clock and informational — election liveness runs
    on each elector's LOCAL monotonic clock (utils/leaderelection.py),
    so a wall-clock jump can neither drop nor extend leadership."""
    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: str = ""
    renew_time: str = ""
    #: fencing term: increments on every holder CHANGE, never on a
    #: renewal — at most one holder exists per term (CAS-enforced)
    lease_transitions: int = 0


@dataclass
class Lease:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)


@dataclass
class Preconditions:
    """Delete preconditions (ref: pkg/api/types.go Preconditions) —
    the delete aborts with Conflict unless the target carries this
    uid. The kubelet's graceful-deletion confirm uses it so a pod
    recreated under the same name mid-drain is never collateral."""
    uid: str = ""


@dataclass
class DeleteOptions:
    """DELETE request options (ref: pkg/api/types.go DeleteOptions) —
    gracePeriodSeconds rides the DELETE body; None means "use the
    pod's own spec.terminationGracePeriodSeconds"."""
    grace_period_seconds: Optional[int] = None
    preconditions: Optional[Preconditions] = None


# ---------------------------------------------------------------- events

@dataclass
class EventSource:
    component: str = ""
    host: str = ""


@dataclass
class Event:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    source: EventSource = field(default_factory=EventSource)
    first_timestamp: str = ""
    last_timestamp: str = ""
    count: int = 0
    type: str = ""


# ---------------------------------------------------------------- namespaces

@dataclass
class NamespaceSpec:
    finalizers: List[str] = field(default_factory=list)


@dataclass
class NamespaceStatus:
    phase: str = "Active"


@dataclass
class Namespace:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NamespaceSpec = field(default_factory=NamespaceSpec)
    status: NamespaceStatus = field(default_factory=NamespaceStatus)


# ------------------------------------------------------- config resources

@dataclass
class Secret:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)
    type: str = "Opaque"


@dataclass
class ConfigEntry:  # helper for LimitRange items
    type: str = ""
    max: Dict[str, Quantity] = field(default_factory=dict)
    min: Dict[str, Quantity] = field(default_factory=dict)
    default: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class LimitRangeSpec:
    limits: List[ConfigEntry] = field(default_factory=list)


@dataclass
class LimitRange:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LimitRangeSpec = field(default_factory=LimitRangeSpec)


@dataclass
class ResourceQuotaSpec:
    hard: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class ResourceQuotaStatus:
    hard: Dict[str, Quantity] = field(default_factory=dict)
    used: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class ResourceQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)


@dataclass
class ServiceAccount:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: List[ObjectReference] = field(default_factory=list)


# ------------------------------------------------- extensions/v1beta1 group
# (ref: pkg/apis/extensions/types.go; mounted by pkg/master/master.go
#  :1049-1091 — HPA, jobs, deployments, daemonsets, ingress)

DEPLOYMENT_POD_TEMPLATE_HASH_KEY = "deployment.kubernetes.io/podTemplateHash"


@dataclass
class JobSpec:
    parallelism: Optional[int] = None   # nil -> defaulted to 1
    completions: Optional[int] = None   # nil -> any single success completes
    selector: Dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class JobCondition:
    type: str = ""        # "Complete"
    status: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class JobStatus:
    conditions: List[JobCondition] = field(default_factory=list)
    start_time: Optional[str] = None
    completion_time: Optional[str] = None
    active: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)


@dataclass
class ScaleSpec:
    replicas: int = 0


@dataclass
class ScaleStatus:
    replicas: int = 0
    selector: Dict[str, str] = field(default_factory=dict)


@dataclass
class Scale:
    """The scale subresource (ref: pkg/apis/extensions/types.go:38-63
    Scale/ScaleSpec/ScaleStatus) — a scaling request detached from the
    scaled object's full schema, served at .../{name}/scale for
    replicationcontrollers (registry/experimental/controller/etcd) and
    deployments (registry/deployment/etcd); the HPA writes through it."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ScaleSpec = field(default_factory=ScaleSpec)
    status: ScaleStatus = field(default_factory=ScaleStatus)


@dataclass
class RollingUpdateDeployment:
    # IntOrString: an absolute count or a "25%"-style percentage of
    # spec.replicas (ref: pkg/apis/extensions/types.go:267,279
    # intstr.IntOrString; resolved by controllers/deployment.py
    # resolve_int_or_percent with the reference's ceil rounding)
    max_unavailable: Any = 1
    max_surge: Any = 1


@dataclass
class DeploymentStrategy:
    type: str = "RollingUpdate"   # or "Recreate"
    rolling_update: RollingUpdateDeployment = field(
        default_factory=RollingUpdateDeployment)


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: Dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: DeploymentStrategy = field(default_factory=DeploymentStrategy)
    unique_label_key: str = DEPLOYMENT_POD_TEMPLATE_HASH_KEY


@dataclass
class DeploymentStatus:
    replicas: int = 0
    updated_replicas: int = 0
    # availability means READY pods (deployment/deployment.go
    # GetAvailablePodsForRCs); unavailable counts the gap to the larger
    # of spec.replicas and the current total — during a surge the extra
    # unready pods are unavailable too
    available_replicas: int = 0
    unavailable_replicas: int = 0
    observed_generation: int = 0


@dataclass
class Deployment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)


@dataclass
class DaemonSetSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class DaemonSetStatus:
    current_number_scheduled: int = 0
    number_misscheduled: int = 0
    desired_number_scheduled: int = 0


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)


@dataclass
class SubresourceReference:
    kind: str = ""
    name: str = ""
    namespace: str = ""
    subresource: str = ""


@dataclass
class HorizontalPodAutoscalerSpec:
    scale_ref: SubresourceReference = field(
        default_factory=SubresourceReference)
    min_replicas: int = 1
    max_replicas: int = 1
    cpu_utilization_target_percentage: Optional[int] = None


@dataclass
class HorizontalPodAutoscalerStatus:
    observed_generation: int = 0
    last_scale_time: Optional[str] = None
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percentage: Optional[int] = None


@dataclass
class HorizontalPodAutoscaler:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: HorizontalPodAutoscalerSpec = field(
        default_factory=HorizontalPodAutoscalerSpec)
    status: HorizontalPodAutoscalerStatus = field(
        default_factory=HorizontalPodAutoscalerStatus)


@dataclass
class IngressBackend:
    service_name: str = ""
    service_port: Any = None


@dataclass
class HTTPIngressPath:
    path: str = ""
    backend: IngressBackend = field(default_factory=IngressBackend)


@dataclass
class HTTPIngressRuleValue:
    paths: List[HTTPIngressPath] = field(default_factory=list)


@dataclass
class IngressRule:
    host: str = ""
    http: Optional[HTTPIngressRuleValue] = None


@dataclass
class IngressSpec:
    backend: Optional[IngressBackend] = None
    rules: List[IngressRule] = field(default_factory=list)


@dataclass
class IngressStatus:
    load_balancer_ingress: List[str] = field(default_factory=list)


@dataclass
class Ingress:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: IngressSpec = field(default_factory=IngressSpec)
    status: IngressStatus = field(default_factory=IngressStatus)


@dataclass
class PodTemplate:
    """(ref: pkg/api/types.go:1121 PodTemplate)"""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class ComponentCondition:
    """(ref: pkg/api/types.go ComponentCondition)"""
    type: str = "Healthy"
    status: str = ""
    message: str = ""
    error: str = ""


@dataclass
class ComponentStatus:
    """(ref: pkg/api/types.go:2086 ComponentStatus — the health of
    scheduler/controller-manager/etcd as seen by the apiserver)"""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    conditions: List[ComponentCondition] = field(default_factory=list)


@dataclass
class APIVersionEntry:
    """(ref: pkg/apis/extensions/types.go APIVersion)"""
    name: str = ""


@dataclass
class ThirdPartyResource:
    """Dynamic API registration — the CRD ancestor (ref:
    pkg/apis/extensions/types.go:145; name `<kind>.<domain>...` mounts
    /apis/<domain>/<version>/<kind>s, master.go:972
    InstallThirdPartyResource)."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    description: str = ""
    versions: List[APIVersionEntry] = field(default_factory=list)


@dataclass
class ThirdPartyResourceData:
    """One custom object: standard metadata + the raw custom fields
    (ref: pkg/registry/thirdpartyresourcedata — the reference stores the
    whole JSON document; `data` carries everything that isn't
    kind/apiVersion/metadata)."""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, Any] = field(default_factory=dict)


# ------------------------------------------------------ persistent volumes

VOLUME_AVAILABLE = "Available"
VOLUME_BOUND = "Bound"
VOLUME_RELEASED = "Released"
CLAIM_PENDING = "Pending"
CLAIM_BOUND = "Bound"


@dataclass
class PersistentVolumeSpec:
    """(ref: pkg/api/types.go PersistentVolumeSpec: capacity, one volume
    source, accessModes, claimRef, reclaim policy)"""
    capacity: Dict[str, Quantity] = field(default_factory=dict)
    access_modes: List[str] = field(default_factory=list)
    claim_ref: Optional[ObjectReference] = None
    persistent_volume_reclaim_policy: str = "Retain"
    host_path: Optional[HostPathVolumeSource] = None
    nfs: Optional[NFSVolumeSource] = None
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    aws_elastic_block_store: Optional[AWSElasticBlockStoreVolumeSource] = None
    cinder: Optional[CinderVolumeSource] = None
    fc: Optional[FCVolumeSource] = None
    flocker: Optional[FlockerVolumeSource] = None


@dataclass
class PersistentVolumeStatus:
    phase: str = ""
    message: str = ""


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    status: PersistentVolumeStatus = field(
        default_factory=PersistentVolumeStatus)


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: List[str] = field(default_factory=list)
    resources: ResourceRequirements = field(
        default_factory=ResourceRequirements)
    volume_name: str = ""


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = ""
    access_modes: List[str] = field(default_factory=list)
    capacity: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(
        default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus)


# ---------------------------------------------------------------- helpers

# Deprecated v1 wire alias: `serviceAccount` mirrors
# `serviceAccountName` on encode and fills it on decode when the
# canonical key is empty (pkg/api/v1/types.go
# PodSpec.DeprecatedServiceAccount, defaults.go, conversion.go).
from . import serde as _serde  # noqa: E402  (needs PodSpec defined)

_serde.WIRE_ALIASES[PodSpec] = {"serviceAccount": "service_account_name"}
# `deprecatedPublicIPs` is externalIPs' pre-v1.1 spelling (ref:
# pkg/api/v1/types.go:1587) — accepted on decode when the canonical key
# is empty, mirrored on encode like the reference's conversion
_serde.WIRE_ALIASES[ServiceSpec] = {"deprecatedPublicIPs": "external_ips"}


def pod_resource_fields(pod: Pod) -> Dict[str, str]:
    """Flat field map for field selectors (ref: pkg/registry/pod PodToSelectableFields)."""
    return {
        "metadata.name": pod.metadata.name,
        "metadata.namespace": pod.metadata.namespace,
        "spec.nodeName": pod.spec.node_name,
        "status.phase": pod.status.phase,
    }


def node_resource_fields(node: Node) -> Dict[str, str]:
    return {
        "metadata.name": node.metadata.name,
        "spec.unschedulable": "true" if node.spec.unschedulable else "false",
    }


def event_resource_fields(ev: Event) -> Dict[str, str]:
    """Selectable fields for events (ref: pkg/registry/event/strategy.go
    getAttrs:88-99 — involvedObject.* plus reason/source/type, merged
    with the ObjectMeta set). kubectl describe's related-events lookup
    and the reference client's Events.Search filter on these
    server-side (pkg/client/unversioned/events.go GetFieldSelector)."""
    o = ev.involved_object
    return {
        "metadata.name": ev.metadata.name,
        "metadata.namespace": ev.metadata.namespace,
        "involvedObject.kind": o.kind,
        "involvedObject.namespace": o.namespace,
        "involvedObject.name": o.name,
        "involvedObject.uid": o.uid,
        "involvedObject.apiVersion": o.api_version,
        "involvedObject.resourceVersion": o.resource_version,
        "involvedObject.fieldPath": o.field_path,
        "reason": ev.reason,
        "source": ev.source.component,
        "type": ev.type,
    }


def generic_resource_fields(obj: Any) -> Dict[str, str]:
    meta = getattr(obj, "metadata", None)
    if meta is None:
        return {}
    return {"metadata.name": meta.name, "metadata.namespace": meta.namespace}


# Per-key getters mirroring the dict builders above. Field selectors
# whose terms all resolve here compile to direct attribute checks — the
# watch fan-out and filtered LISTs otherwise build one throwaway field
# map per object-version (the load-bearing selectors, the scheduler's
# spec.nodeName= / != pair, pay it on every event of a 30k-pod tile).
POD_FIELD_GETTERS: Dict[str, Any] = {
    "metadata.name": lambda o: o.metadata.name,
    "metadata.namespace": lambda o: o.metadata.namespace,
    "spec.nodeName": lambda o: o.spec.node_name,
    "status.phase": lambda o: o.status.phase,
}

EVENT_FIELD_GETTERS: Dict[str, Any] = {
    "metadata.name": lambda o: o.metadata.name,
    "metadata.namespace": lambda o: o.metadata.namespace,
    "involvedObject.kind": lambda o: o.involved_object.kind,
    "involvedObject.namespace": lambda o: o.involved_object.namespace,
    "involvedObject.name": lambda o: o.involved_object.name,
    "involvedObject.uid": lambda o: o.involved_object.uid,
    "involvedObject.apiVersion": lambda o: o.involved_object.api_version,
    "involvedObject.resourceVersion":
        lambda o: o.involved_object.resource_version,
    "involvedObject.fieldPath": lambda o: o.involved_object.field_path,
    "reason": lambda o: o.reason,
    "source": lambda o: o.source.component,
    "type": lambda o: o.type,
}

NODE_FIELD_GETTERS: Dict[str, Any] = {
    "metadata.name": lambda o: o.metadata.name,
    "spec.unschedulable": lambda o: ("true" if o.spec.unschedulable
                                     else "false"),
}

GENERIC_FIELD_GETTERS: Dict[str, Any] = {
    # mirror generic_resource_fields' metadata-is-None guard (it
    # returns {}, whose missing keys read as "" through the dict
    # path's .get default)
    "metadata.name": lambda o: (
        m.name if (m := getattr(o, "metadata", None)) is not None else ""),
    "metadata.namespace": lambda o: (
        m.namespace if (m := getattr(o, "metadata", None)) is not None
        else ""),
}
