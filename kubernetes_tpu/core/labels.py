"""Label sets and selectors.

Reference: pkg/labels (Set, Selector, Parse). Supports the v1.1 selector
grammar: equality ops (=, ==, !=), set ops (in, notin), existence (key, !key),
comma-joined requirements. `SelectorFromSet` builds the conjunction of
equality requirements used by services/RCs (pkg/labels/selector.go).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

EQUALS = "="
DOUBLE_EQUALS = "=="
NOT_EQUALS = "!="
IN = "in"
NOT_IN = "notin"
EXISTS = "exists"
DOES_NOT_EXIST = "!"


@dataclass(frozen=True)
class Requirement:
    key: str
    op: str
    values: Tuple[str, ...] = ()

    def matches(self, labels: Dict[str, str]) -> bool:
        if self.op in (EQUALS, DOUBLE_EQUALS, IN):
            return self.key in labels and labels[self.key] in self.values
        if self.op in (NOT_EQUALS, NOT_IN):
            # Reference semantics: absent key satisfies != / notin.
            return self.key not in labels or labels[self.key] not in self.values
        if self.op == EXISTS:
            return self.key in labels
        if self.op == DOES_NOT_EXIST:
            return self.key not in labels
        raise ValueError(f"unknown operator {self.op!r}")

    def __str__(self) -> str:
        if self.op == EXISTS:
            return self.key
        if self.op == DOES_NOT_EXIST:
            return f"!{self.key}"
        if self.op in (IN, NOT_IN):
            return f"{self.key} {self.op} ({','.join(sorted(self.values))})"
        return f"{self.key}{self.op}{self.values[0]}"


@dataclass(frozen=True)
class Selector:
    requirements: Tuple[Requirement, ...] = ()

    def matches(self, labels: Optional[Dict[str, str]]) -> bool:
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    def empty(self) -> bool:
        return not self.requirements

    def __str__(self) -> str:
        return ",".join(str(r) for r in self.requirements)


def everything() -> Selector:
    return Selector()


def selector_from_set(labels: Optional[Dict[str, str]]) -> Selector:
    """Conjunction of equality requirements; empty set selects everything."""
    reqs = tuple(
        Requirement(k, EQUALS, (v,)) for k, v in sorted((labels or {}).items())
    )
    return Selector(reqs)


_TOKEN_RE = re.compile(
    r"\s*(?:"
    r"(?P<op>==|=|!=)|"
    r"(?P<comma>,)|"
    r"(?P<lparen>\()|(?P<rparen>\))|"
    r"(?P<bang>!)|"
    r"(?P<word>[A-Za-z0-9_./-]+)"
    r")\s*"
)


def parse(s: str) -> Selector:
    """Parse the selector grammar, e.g. "a=b,env in (prod,dev),!beta"."""
    s = s.strip()
    if not s:
        return Selector()
    tokens: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m or m.end() == pos:
            raise ValueError(f"invalid selector {s!r} at {pos}")
        pos = m.end()
        for name, val in m.groupdict().items():
            if val is not None:
                tokens.append((name, val))
    reqs: List[Requirement] = []
    i = 0

    def peek(k: int = 0):
        return tokens[i + k] if i + k < len(tokens) else (None, None)

    while i < len(tokens):
        kind, val = tokens[i]
        if kind == "comma":
            i += 1
            continue
        if kind == "bang":
            nk, nv = peek(1)
            if nk != "word":
                raise ValueError(f"expected key after ! in {s!r}")
            reqs.append(Requirement(nv, DOES_NOT_EXIST))
            i += 2
            continue
        if kind != "word":
            raise ValueError(f"unexpected token {val!r} in {s!r}")
        key = val
        nk, nv = peek(1)
        if nk == "op":
            vk, vv = peek(2)
            if vk != "word":
                raise ValueError(f"expected value after {nv} in {s!r}")
            op = EQUALS if nv in ("=", "==") else NOT_EQUALS
            reqs.append(Requirement(key, op, (vv,)))
            i += 3
        elif nk == "word" and nv in (IN, NOT_IN):
            # key in (a,b,c)
            if peek(2)[0] != "lparen":
                raise ValueError(f"expected ( after {nv} in {s!r}")
            j = i + 3
            vals: List[str] = []
            while j < len(tokens) and tokens[j][0] != "rparen":
                if tokens[j][0] == "word":
                    vals.append(tokens[j][1])
                elif tokens[j][0] != "comma":
                    raise ValueError(f"unexpected token in value list of {s!r}")
                j += 1
            if j >= len(tokens):
                raise ValueError(f"unclosed ( in {s!r}")
            reqs.append(Requirement(key, nv, tuple(vals)))
            i = j + 1
        else:
            reqs.append(Requirement(key, EXISTS))
            i += 1
    return Selector(tuple(reqs))
