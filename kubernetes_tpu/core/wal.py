"""Write-ahead log + snapshot recovery for the control-plane store.

Reference: etcd's raft-backed WAL + snapshot files are what make the
reference's control plane survive an apiserver (or etcd) process death
(pkg/storage/etcd sits on etcd's wal/ and snap/ directories). In this
single-process reproduction the Store IS etcd, so durability lives here:
the two-phase commit already produces a totally-ordered ledger stream,
and this module appends one record per committed revision — TTL
expiries included, since the store emits those as first-class DELETED
ledger events — to a segmented, checksummed log with periodic snapshot
compaction. `Store.recover(dir)` / `NativeStore.recover(dir)` replay
snapshot + tail back into a live store, bit-identically to the
pre-crash ledger prefix (a torn final record is truncated, not fatal).

Divergence (DIVERGENCES.md #24): etcd's log is raft-REPLICATED; this is
a single-node WAL — durability against process death without
replication. The record/segment/snapshot layout is deliberately
etcd-shaped so a replicated backend can adopt the same format.

On-disk layout (everything under one directory):

  wal-<first_rev:020d>.seg   frames: <u32 len><u32 crc32>payload, where
                             payload is the JSON array
                             [rev, etype, key, expiry|null, obj_wire]
                             or, for a multi-key transaction, one frame
                             [first_rev, "TXN", [records...]] (see TXN)
  snap-<rev:020d>.json       full store state at rev: entries
                             [[key, mod_rev, expiry|null, obj_wire]...]
                             plus the seg_writes / ttl_segs bookkeeping
                             the apiserver's LIST byte caches key on

Segments are named by their first record's revision and opened lazily
(commit() names the file after the first buffered record), so recovery
never leaves an empty or torn-tailed segment behind: the reader
truncates a torn final record in place and the writer always starts a
fresh segment.

fsync_policy: "always" fsyncs every commit (every ledger window pays a
disk flush — the etcd default, A/B'd in bench.py --wal-dir);
"batch" flushes every commit but fsyncs at most every _BATCH_FSYNC_S
seconds (plus on rotate/snapshot/close) — crash-consistent through the
OS page cache, power-loss-consistent only up to the fsync lag.
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..utils.metrics import global_metrics

_FRAME = struct.Struct("<II")          # payload length, crc32(payload)
_SEG_FMT = "wal-%020d.seg"
_SNAP_FMT = "snap-%020d.json"
_BATCH_FSYNC_S = 0.05

# Sentinel in the etype position marking a multi-record transaction
# frame: payload [first_rev, "TXN", [[rev, etype, key, expiry,
# obj_wire], ...]]. One frame is one CRC unit, so a crash mid-write
# tears the WHOLE transaction and _read_segment truncates it
# atomically — a partial txn is never replayable. read_wal expands the
# frame back into flat records, so both recover() loops (Python and
# the kvstore.cc kv_replay ABI) replay txn-bearing logs unchanged.
TXN = "TXN"

FSYNC_POLICIES = ("always", "batch")


class WalError(Exception):
    pass


class WalCorrupt(WalError):
    """A checksum/framing failure NOT attributable to a torn tail."""


def _segments(dirpath: str) -> List[Tuple[int, str]]:
    """Sorted (first_rev, path) of every WAL segment in the directory."""
    out = []
    for name in os.listdir(dirpath):
        if name.startswith("wal-") and name.endswith(".seg"):
            try:
                out.append((int(name[4:-4]), os.path.join(dirpath, name)))
            except ValueError:
                continue
    out.sort()
    return out


def _snapshots(dirpath: str) -> List[Tuple[int, str]]:
    out = []
    for name in os.listdir(dirpath):
        if name.startswith("snap-") and name.endswith(".json"):
            try:
                out.append((int(name[5:-5]), os.path.join(dirpath, name)))
            except ValueError:
                continue
    out.sort()
    return out


def record_payload(rev: int, etype: str, key: str,
                   expiry: Optional[float], obj_wire: Any) -> bytes:
    """Unframed payload bytes of one flat record. The payload/frame
    split is the parity contract with the native appender
    (kvstore.cc kv_commit_txn): Python builds the payload, whichever
    side owns the file adds the <u32 len><u32 crc32> frame — so both
    writers produce byte-identical segments from the same records."""
    return json.dumps([rev, etype, key, expiry, obj_wire],
                      separators=(",", ":")).encode()


def txn_payload(records: List[list]) -> bytes:
    """Unframed payload of a whole multi-key transaction (see TXN)."""
    return json.dumps([records[0][0], TXN, records],
                      separators=(",", ":")).encode()


def frame(payload: bytes) -> bytes:
    """<u32 len><u32 crc32(payload)> + payload — the one on-disk frame
    shape; kvstore.cc reimplements exactly this (same CRC-32/IEEE)."""
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def encode_record(rev: int, etype: str, key: str,
                  expiry: Optional[float], obj_wire: Any) -> bytes:
    return frame(record_payload(rev, etype, key, expiry, obj_wire))


def encode_txn(records: List[list]) -> bytes:
    """One frame for a whole multi-key transaction (see TXN above).
    `records` are ordinary [rev, etype, key, expiry, obj_wire] lists
    with consecutive revisions; the first one names the frame."""
    return frame(txn_payload(records))


def _read_segment(path: str, last: bool) -> Tuple[List[list], bool]:
    """-> (decoded payloads, truncated). A torn or checksum-failing
    record in the LAST segment ends replay (the crash tore the tail —
    the file is truncated to the valid prefix so the writer can resume
    cleanly); the same damage mid-chain is real corruption and raises,
    because every later record would break revision contiguity."""
    records: List[list] = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    valid_to = 0
    torn = False
    while pos < len(data):
        if pos + _FRAME.size > len(data):
            torn = True
            break
        length, crc = _FRAME.unpack_from(data, pos)
        start = pos + _FRAME.size
        end = start + length
        if end > len(data):
            torn = True
            break
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            torn = True
            break
        try:
            rec = json.loads(payload)
        except ValueError:
            torn = True
            break
        records.append(rec)
        pos = end
        valid_to = end
    if torn:
        if not last:
            raise WalCorrupt(
                f"{os.path.basename(path)}: bad record at byte {valid_to} "
                f"in a non-final segment")
        with open(path, "r+b") as f:
            f.truncate(valid_to)
    return records, torn


def read_wal_grouped(dirpath: str
                     ) -> Tuple[Optional[Dict], List[List[list]]]:
    """-> (snapshot state | None, tail record GROUPS strictly after
    it). Each group is the atomic unit one frame carried: a singleton
    for a plain record, the whole window for a TXN frame. Recovery
    backends that replay transactions as one engine window
    (NativeStore via kv_replay_txn) key off the grouping; read_wal()
    flattens it for callers that replay record-at-a-time.

    Picks the newest parseable snapshot, then replays every segment
    record with rev > snapshot rev, enforcing strict revision order.
    Records at or below the snapshot rev are skipped (a crash between
    snapshot write and segment compaction leaves such overlap behind).
    """
    snap: Optional[Dict] = None
    for rev, path in reversed(_snapshots(dirpath)):
        try:
            with open(path) as f:
                cand = json.load(f)
            if cand.get("rev") == rev:
                snap = cand
                break
        except (OSError, ValueError):
            continue  # half-written snapshot: fall back to an older one
    floor = snap["rev"] if snap else 0
    groups: List[List[list]] = []
    segs = _segments(dirpath)
    last_rev = floor
    for i, (_first, path) in enumerate(segs):
        seg_records, torn = _read_segment(path, last=(i == len(segs) - 1))
        for rec in seg_records:
            if len(rec) > 1 and rec[1] == TXN:
                # expand the txn frame; its CRC already guaranteed
                # all-or-nothing, so only intra-frame contiguity with
                # the declared first_rev is left to enforce.
                first, flat = rec[0], rec[2]
                for j, sub in enumerate(flat):
                    if sub[0] != first + j:
                        raise WalCorrupt(
                            f"txn frame at {first} not contiguous: "
                            f"record {j} has rev {sub[0]} "
                            f"({os.path.basename(path)})")
            else:
                flat = (rec,)
            group = []
            for sub in flat:
                rev = sub[0]
                if rev <= floor:
                    continue
                if rev != last_rev + 1:
                    raise WalCorrupt(
                        f"revision gap: have {last_rev}, next record {rev} "
                        f"({os.path.basename(path)})")
                group.append(sub)
                last_rev = rev
            if group:
                groups.append(group)
        if torn:
            break  # nothing after a torn tail is replayable
    return snap, groups


def read_wal(dirpath: str) -> Tuple[Optional[Dict], List[list]]:
    """Flat view of read_wal_grouped: (snapshot | None, tail records)."""
    snap, groups = read_wal_grouped(dirpath)
    return snap, [rec for group in groups for rec in group]


class WalWriter:
    """Append side of the log. NOT thread-safe on its own: the store
    calls append/commit under its ledger lock, which is exactly the
    serialization that makes append order equal revision order."""

    def __init__(self, dirpath: str, fsync_policy: str = "batch",
                 segment_records: int = 10_000,
                 snapshot_records: int = 50_000):
        if fsync_policy not in FSYNC_POLICIES:
            raise WalError(f"fsync_policy must be one of {FSYNC_POLICIES}, "
                           f"got {fsync_policy!r}")
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self.fsync_policy = fsync_policy
        self.segment_records = segment_records
        self.snapshot_records = snapshot_records
        self._buf: List[bytes] = []
        self._buf_records = 0            # logical records (txn-expanded)
        self._buf_first_rev = 0
        self._f = None                   # current segment file object
        self._seg_count = 0              # records in the current segment
        self._since_snapshot = 0
        self._last_fsync = 0.0
        self._closed = False

    # ------------------------------------------------------------ append

    def append(self, rev: int, etype: str, key: str,
               expiry: Optional[float], obj_wire: Any) -> None:
        if not self._buf:
            self._buf_first_rev = rev
        self._buf.append(encode_record(rev, etype, key, expiry, obj_wire))
        self._buf_records += 1

    def append_txn(self, records: List[list]) -> None:
        """Buffer a whole multi-key transaction as ONE frame. The
        records are [rev, etype, key, expiry, obj_wire] lists with
        consecutive revisions (the store's commit_txn window)."""
        if not records:
            return
        if not self._buf:
            self._buf_first_rev = records[0][0]
        self._buf.append(encode_txn(records))
        self._buf_records += len(records)

    def commit(self) -> int:
        """Write every buffered frame in one os.write and flush; fsync
        per policy. Returns the number of records committed."""
        if not self._buf:
            return 0
        if self._closed:
            raise WalError("WAL is closed")
        if self._f is None:
            self._f = open(os.path.join(
                self.dir, _SEG_FMT % self._buf_first_rev), "ab")
        n = self._buf_records
        self._f.write(b"".join(self._buf))
        self._f.flush()
        self._buf.clear()
        self._buf_records = 0
        self._seg_count += n
        self._since_snapshot += n
        now = time.monotonic()
        if (self.fsync_policy == "always"
                or now - self._last_fsync >= _BATCH_FSYNC_S):
            os.fsync(self._f.fileno())
            self._last_fsync = now
        global_metrics.inc("wal_records_total", by=n)
        if self._seg_count >= self.segment_records:
            self._rotate()
        return n

    def _rotate(self) -> None:
        if self._f is not None:
            os.fsync(self._f.fileno())
            self._f.close()
            self._f = None
        self._seg_count = 0

    # ---------------------------------------------------------- snapshot

    @property
    def should_snapshot(self) -> bool:
        return (self.snapshot_records > 0
                and self._since_snapshot >= self.snapshot_records)

    def write_snapshot(self, state: Dict) -> None:
        """Durably write a full-state snapshot at state['rev'], then
        compact: every closed segment's records are <= that rev, so
        they (and older snapshots) are deleted. The current segment is
        rotated first so the invariant holds."""
        rev = state["rev"]
        self._rotate()
        tmp = os.path.join(self.dir, f".snap-{rev}.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        final = os.path.join(self.dir, _SNAP_FMT % rev)
        os.replace(tmp, final)
        for srev, path in _snapshots(self.dir):
            if srev < rev:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        for _first, path in _segments(self.dir):
            try:
                os.unlink(path)
            except OSError:
                pass
        self._since_snapshot = 0
        global_metrics.inc("wal_snapshots_total")

    def close(self) -> None:
        if self._closed:
            return
        self.commit()
        self._rotate()
        self._closed = True
