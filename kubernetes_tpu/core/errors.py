"""API errors with HTTP status semantics.

Mirrors the reference's pkg/api/errors (StatusError carrying a Status object
with reason/code) in a minimal Python form; these surface both through the
in-process client and as HTTP status codes from the REST server.
"""

from __future__ import annotations


class ApiError(Exception):
    code = 500
    reason = "InternalError"
    #: seconds the server asked the client to wait before retrying
    #: (header-borne — a 429's Retry-After; None when the server sent
    #: none). Consumed by api.retry.RetryPolicy.
    retry_after = None

    def __init__(self, message: str = "", kind: str = "", name: str = ""):
        self.kind = kind
        self.name = name
        if not message and (kind or name):
            message = f'{self.reason}: {kind or "object"} "{name}"'
        super().__init__(message or self.reason)

    def status(self) -> dict:
        return {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Failure",
            "message": str(self),
            "reason": self.reason,
            "code": self.code,
            "details": {"kind": self.kind, "name": self.name},
        }


class NotFound(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExists(ApiError):
    code = 409
    reason = "AlreadyExists"


class Conflict(ApiError):
    code = 409
    reason = "Conflict"


class Invalid(ApiError):
    code = 422
    reason = "Invalid"


class BadRequest(ApiError):
    code = 400
    reason = "BadRequest"


class MethodNotSupported(ApiError):
    code = 405
    reason = "MethodNotSupported"


class Unauthorized(ApiError):
    code = 401
    reason = "Unauthorized"


class Forbidden(ApiError):
    code = 403
    reason = "Forbidden"


class TooManyRequests(ApiError):
    code = 429
    reason = "TooManyRequests"


class Expired(ApiError):
    """Watch window no longer contains the requested revision (410 Gone);
    the client must re-list (ref: pkg/storage/cacher.go 'too old resource
    version')."""
    code = 410
    reason = "Expired"


class BadGateway(ApiError):
    """An upstream the apiserver relays to (a node's kubelet) failed."""
    code = 502
    reason = "BadGateway"


class ServiceUnavailable(ApiError):
    """No backend can take the proxied request (ref:
    errors.NewServiceUnavailable, pkg/registry/service/rest.go:320)."""
    code = 503
    reason = "ServiceUnavailable"


def from_status(status: dict) -> ApiError:
    reason = status.get("reason", "")
    for cls in (NotFound, AlreadyExists, Conflict, Invalid, BadRequest,
                MethodNotSupported, Unauthorized, Forbidden, TooManyRequests,
                Expired, BadGateway, ServiceUnavailable):
        if cls.reason == reason:
            err = cls(status.get("message", ""))
            details = status.get("details") or {}
            err.kind = details.get("kind", "")
            err.name = details.get("name", "")
            return err
    return ApiError(status.get("message", "unknown error"))
