"""ctypes binding for the native C++ store (native/kvstore.cc).

Drop-in replacement for core.store.Store: same verbs, same CAS/TTL/watch
semantics, same exceptions. Objects are serialized through the Scheme
codec at the boundary — exactly the role runtime.Codec plays between the
reference's registry and etcd (etcd_helper.go stores JSON); the stored
resourceVersion is stamped from the native revision on the way out, and
a bounded (key, rev) decode cache plays the watch cache's decoded-object
role so repeated reads don't re-parse.

When to use which backend: the pure-Python Store keeps live objects and
skips serialization entirely, so for a single-process control plane it
is the faster default. NativeStore is the etcd-analogue backend — state
lives outside the Python heap behind a serialization boundary (the cost
profile the reference's apiserver actually has), store operations run
GIL-free under a native mutex, and kv_wait parks watcher threads in
native code. Pick it when fidelity to the external-store architecture
matters more than in-proc throughput, or as the base for a future
multi-process / shared-memory deployment.

The shared library is compiled on first use (g++ -O2 -shared) and cached
next to the source; a missing toolchain raises ImportError so callers
can fall back to the pure-Python Store (native_available() probes).
"""

from __future__ import annotations

import ctypes
import json as _json
import os
import struct
import subprocess
import threading
import time as _time
from typing import Any, Callable, Iterable, List, Optional, Tuple

from . import watch as watchpkg
from .errors import AlreadyExists, Conflict, Expired, NotFound
from .scheme import Scheme, default_scheme
from .wal import record_payload, txn_payload

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "kvstore.cc")
_LIB = os.path.join(_NATIVE_DIR, "libkvstore.so")

ERR_NOT_FOUND = -1
ERR_EXISTS = -2
ERR_CONFLICT = -3
ERR_TOO_SMALL = -4
ERR_EXPIRED = -5
# kv_commit_txn only: the pre-assigned revision window raced another
# writer — restage and retry. Distinct from ERR_CONFLICT so a genuine
# CAS failure still surfaces as Conflict to the caller.
ERR_RACED = -6
# Buffer size hints come back as -(required + SIZE_HINT_BASE): a range
# disjoint from the error codes so a tiny required size can't alias them
# (kvstore.cc SIZE_HINT_BASE).
SIZE_HINT_BASE = 64
_RETRY_SLACK = 64  # extra bytes on retry; unrelated to SIZE_HINT_BASE


def _size_hint(n: int) -> Optional[int]:
    """Decode a kv_* return: buffer size to retry with, or None."""
    if n <= -SIZE_HINT_BASE:
        return (-n - SIZE_HINT_BASE) + _RETRY_SLACK
    return None

_EVENT_TYPES = {0: watchpkg.ADDED, 1: watchpkg.MODIFIED, 2: watchpkg.DELETED}

_build_lock = threading.Lock()
_lib = None


def _load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        from ..native.build import build_native
        built = build_native(
            _SRC, _LIB,
            [["g++", "-O2", "-std=c++17", "-shared", "-fPIC"]])
        if built is None:
            raise ImportError("cannot build native store (no toolchain "
                              "or unwritable native/ directory)")
        lib = ctypes.CDLL(_LIB)
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_open.argtypes = [ctypes.c_uint64]
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.kv_current_rev.restype = ctypes.c_uint64
        lib.kv_current_rev.argtypes = [ctypes.c_void_p]
        lib.kv_oldest_rev.restype = ctypes.c_uint64
        lib.kv_oldest_rev.argtypes = [ctypes.c_void_p]
        for fn in (lib.kv_create, lib.kv_set):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                           ctypes.c_char_p, ctypes.c_uint64,
                           ctypes.c_double]
        lib.kv_update.restype = ctypes.c_int64
        lib.kv_update.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_uint64]
        lib.kv_delete.restype = ctypes.c_int64
        lib.kv_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint64]
        lib.kv_get.restype = ctypes.c_int64
        lib.kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_char_p, ctypes.c_int64,
                               ctypes.POINTER(ctypes.c_uint64)]
        lib.kv_list.restype = ctypes.c_int64
        lib.kv_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_int64]
        lib.kv_batch.restype = ctypes.c_int64
        lib.kv_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.kv_create_batch.restype = ctypes.c_int64
        lib.kv_create_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_double)]
        lib.kv_events.restype = ctypes.c_int64
        lib.kv_events.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                  ctypes.c_char_p, ctypes.c_char_p,
                                  ctypes.c_int64]
        lib.kv_wait.restype = ctypes.c_uint64
        lib.kv_wait.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                ctypes.c_double]
        # WAL recovery surface (kvstore.cc kv_restore/kv_restore_seal/
        # kv_replay); absent only in a stale prebuilt library, in which
        # case recover() refuses rather than replaying wrong
        try:
            lib.kv_restore.restype = ctypes.c_int64
            lib.kv_restore.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_uint64, ctypes.c_double]
            lib.kv_restore_seal.restype = None
            lib.kv_restore_seal.argtypes = [ctypes.c_void_p,
                                            ctypes.c_uint64]
            lib.kv_replay.restype = ctypes.c_int64
            lib.kv_replay.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                      ctypes.c_uint8, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_uint64, ctypes.c_double]
            lib.has_recovery = True
        except AttributeError:
            lib.has_recovery = False
        # txn-window replay (kv_replay_txn): a TXN frame recovers as
        # one engine lock window, mirroring the atomic unit it was on
        # disk. Absent in a stale prebuilt library — recover() then
        # falls back to per-record kv_replay (bit-identical result).
        try:
            lib.kv_replay_txn.restype = ctypes.c_int64
            lib.kv_replay_txn.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_double)]
            lib.has_txn_replay = True
        except AttributeError:
            lib.has_txn_replay = False
        # Native commit path (kv_commit_txn + publish ring + WAL
        # appender, ISSUE 17). Absent only in a stale prebuilt library
        # — NativeStore then falls back to the kv_batch delegate and
        # refuses wal_dir (the fallback README documents).
        try:
            lib.kv_commit_txn.restype = ctypes.c_int64
            lib.kv_commit_txn.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_uint64)]
            lib.kv_publish_start.restype = ctypes.c_int64
            lib.kv_publish_start.argtypes = [ctypes.c_void_p]
            lib.kv_publish_flush.restype = ctypes.c_uint64
            lib.kv_publish_flush.argtypes = [ctypes.c_void_p,
                                             ctypes.c_double]
            lib.kv_shutdown.restype = None
            lib.kv_shutdown.argtypes = [ctypes.c_void_p]
            lib.kv_wal_attach.restype = ctypes.c_int64
            lib.kv_wal_attach.argtypes = [ctypes.c_void_p,
                                          ctypes.c_char_p, ctypes.c_int,
                                          ctypes.c_uint64]
            lib.kv_get_ex.restype = ctypes.c_int64
            lib.kv_get_ex.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_int64,
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.POINTER(ctypes.c_double)]
            lib.kv_stats.restype = None
            lib.kv_stats.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint64)]
            lib.has_commit_path = True
        except AttributeError:
            lib.has_commit_path = False
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        _load_library()
        return True
    except ImportError:
        return False


class NativeStore:
    """core.store.Store API over the C++ engine."""

    def __init__(self, window: int = 100_000,
                 scheme: Scheme = default_scheme,
                 decode_cache_size: int = 200_000,
                 native_publish: bool = True,
                 wal_dir: Optional[str] = None,
                 fsync_policy: str = "batch",
                 segment_records: int = 10_000):
        self._lib = _load_library()
        self._h = self._lib.kv_open(window)
        self.scheme = scheme
        self._watch_threads: List[threading.Thread] = []
        self._watchers: List[Any] = []
        # worker fan-out shards (attach_fanout_shard); copy-on-write
        self._shards: List["_NativeShard"] = []
        self._shards_lock = threading.Lock()
        self._closed = False
        # native commit path: ring publisher + pre-assigned-window
        # commits (kv_commit_txn). native_publish=False is the control
        # arm (mirrors Store(publish_inline=True) / Registry(
        # txn_commit=False)): commit_txn falls back to the kv_batch
        # delegate and events publish inline under the engine mutex.
        # A stale prebuilt .so without the ABI degrades the same way.
        self._native_publish = (native_publish
                                and getattr(self._lib, "has_commit_path",
                                            False))
        self._wal_on = False
        if self._native_publish:
            self._lib.kv_publish_start(self._h)
        if wal_dir is not None:
            from .wal import FSYNC_POLICIES, WalError
            if not self._native_publish:
                raise WalError(
                    "NativeStore(wal_dir=...) requires the native "
                    "commit path (native_publish=True and a current "
                    "libkvstore build): journaling routes every write "
                    "through kv_commit_txn")
            if fsync_policy not in FSYNC_POLICIES:
                raise WalError(
                    f"fsync_policy must be one of {FSYNC_POLICIES}, "
                    f"got {fsync_policy!r}")
            os.makedirs(wal_dir, exist_ok=True)
            self._lib.kv_wal_attach(
                self._h, wal_dir.encode(),
                1 if fsync_policy == "always" else 0, segment_records)
            self._wal_on = True
        # (key, rev) -> decoded object. Plays the watch cache's decoded-
        # object role in front of "etcd" (cacher.go): objects are frozen
        # by the store contract, so sharing decoded instances is safe —
        # and writers hand their object over, so writes cache without
        # ever decoding.
        from collections import OrderedDict
        self._decoded: "OrderedDict[Tuple[str, int], Any]" = OrderedDict()
        self._decoded_cap = decode_cache_size
        self._cache_lock = threading.Lock()

    def __del__(self):
        try:
            if self._h:
                h, self._h = self._h, None
                self._closed = True
                self._lib.kv_close(h)
        except Exception:
            pass

    # -------------------------------------------------------- lifecycle

    def close(self, timeout: float = 2.0) -> None:
        """Stop the store the way a process kill would look to its
        clients: wake every watcher thread parked in kv_wait
        (kv_shutdown drains the publish ring, seals the WAL and breaks
        the native wait), stop the delivered watchers so consumers
        blocked in next() return, and join the pump threads. The
        engine handle stays alive until __del__ so a straggler pump
        can never touch freed memory."""
        if self._closed:
            return
        self._closed = True
        if getattr(self._lib, "has_commit_path", False):
            self._lib.kv_shutdown(self._h)
        for sh in list(self._shards):
            try:
                sh.stop(timeout=timeout)
            except Exception:
                pass
        for w in self._watchers:
            try:
                w.stop()
            except Exception:
                pass
        for t in self._watch_threads:
            t.join(timeout=timeout)

    # the apiserver restart path calls stop() on whatever store it has
    stop = close

    # --------------------------------------------------------- serde

    def _encode(self, obj: Any) -> bytes:
        return self.scheme.encode(obj).encode()

    def _stamp(self, obj: Any, rev: int) -> Any:
        from dataclasses import replace
        return replace(obj, metadata=replace(obj.metadata,
                                             resource_version=str(rev)))

    def _cache_put(self, key: str, rev: int, obj: Any) -> None:
        with self._cache_lock:
            self._decoded[(key, rev)] = obj
            while len(self._decoded) > self._decoded_cap:
                self._decoded.popitem(last=False)

    def _decode(self, raw: bytes, rev: int, key: str = "") -> Any:
        if key:
            with self._cache_lock:
                hit = self._decoded.get((key, rev))
            if hit is not None:
                return hit
        obj = self._stamp(self.scheme.decode(raw.decode()), rev)
        if key:
            self._cache_put(key, rev, obj)
        return obj

    @staticmethod
    def _key_name(key: str) -> Tuple[str, str]:
        kind = key.split("/")[2] if key.count("/") >= 2 else ""
        return kind, key.rsplit("/", 1)[-1]

    # --------------------------------------------------------- verbs

    @property
    def current_revision(self) -> int:
        return int(self._lib.kv_current_rev(self._h))

    # ------------------------------------------- native commit path

    def _kv_commit(self, first_rev: int, staged: List[tuple],
                   payloads: List[bytes]) -> int:
        """One kv_commit_txn call. staged entries are
        (type_code, key, val_bytes, expect_rev, expiry_abs); payloads
        are unframed WAL payload bytes (the engine frames them)."""
        n = len(staged)
        types = (ctypes.c_uint8 * n)(*[s[0] for s in staged])
        keys = (ctypes.c_char_p * n)(*[s[1].encode() for s in staged])
        vals = (ctypes.c_char_p * n)(*[s[2] for s in staged])
        val_lens = (ctypes.c_uint64 * n)(*[len(s[2]) for s in staged])
        expects = (ctypes.c_uint64 * n)(*[s[3] for s in staged])
        expiries = (ctypes.c_double * n)(
            *[float(s[4] or 0.0) for s in staged])
        nf = len(payloads)
        frames = (ctypes.c_char_p * nf)(*payloads) if nf else None
        frame_lens = ((ctypes.c_uint64 * nf)(*[len(p) for p in payloads])
                      if nf else None)
        return int(self._lib.kv_commit_txn(
            self._h, n, first_rev, types, keys, vals, val_lens,
            expects, expiries, nf, frames, frame_lens))

    def _txn_commit_native(self, ops, flat: bool) -> List[Any]:
        """Shared staging loop for commit_txn (one TXN frame) and the
        journaled batch() (flat frames): pre-assign the revision
        window, run the update fns against it, stamp + encode once,
        build the WAL payload(s) through core/wal.py's codec, and
        commit through kv_commit_txn — ledger mutation, WAL framing
        and the publish handoff all native. ERR_RACED (another writer
        claimed the window) and ERR_CONFLICT (a staged key moved)
        restage the whole tile, mirroring batch()'s retry contract."""
        if not ops:
            return []
        modified = watchpkg.MODIFIED
        for _ in range(10):
            first = self.current_revision + 1
            rev = first - 1
            staged: List[tuple] = []
            records: List[list] = []
            outs: List[Tuple[str, Any]] = []
            for key, fn in ops:
                raw, mod_rev, expiry = self._get_raw_ex(key)
                rev += 1
                cur = self._decode(raw, mod_rev, key)
                if getattr(fn, "wants_rv", False):
                    new_obj = fn(cur, str(rev))
                else:
                    new_obj = self._stamp(fn(cur), rev)
                wire = self.scheme.encode_dict(new_obj)
                val = _json.dumps(wire).encode()
                staged.append((1, key, val, mod_rev, expiry))
                if self._wal_on:
                    records.append([rev, modified, key,
                                    expiry if expiry else None, wire])
                outs.append((key, new_obj))
            if self._wal_on:
                payloads = ([record_payload(*r) for r in records]
                            if flat else [txn_payload(records)])
            else:
                payloads = []
            r = self._kv_commit(first, staged, payloads)
            if r in (ERR_RACED, ERR_CONFLICT, ERR_NOT_FOUND):
                # raced (window claimed / key moved / key vanished):
                # restage — a vanished key raises NotFound with its
                # precise name from the next _get_raw_ex probe
                continue
            out = []
            for i, (key, obj) in enumerate(outs):
                self._cache_put(key, first + i, obj)
                out.append(obj)
            return out
        raise Conflict("commit_txn: too many retries")

    def publish_stats(self) -> dict:
        """Engine-side ledger/publish counters (kv_stats): the native
        commit-path split the Python sampler cannot observe."""
        if not getattr(self._lib, "has_commit_path", False):
            return {}
        out = (ctypes.c_uint64 * 8)()
        self._lib.kv_stats(self._h, out)
        return {"commits": int(out[0]), "ledger_ns": int(out[1]),
                "published_batches": int(out[2]),
                "publish_ns": int(out[3]), "wal_frames": int(out[4]),
                "wal_bytes": int(out[5]), "revision": int(out[6]),
                "published_rev": int(out[7])}

    def publish_flush(self, timeout: float = 5.0) -> int:
        """Block until the native publisher has drained the ring (the
        committer's drain barrier: 'drained' must keep meaning
        'visible to watchers'). Returns the watch-visible revision."""
        if not getattr(self._lib, "has_commit_path", False):
            return self.current_revision
        return int(self._lib.kv_publish_flush(self._h, float(timeout)))

    # ----------------------------------------------------- durability

    @classmethod
    def recover(cls, wal_dir: str, window: int = 100_000,
                scheme: Scheme = default_scheme) -> "NativeStore":
        """Rebuild a NativeStore from a WAL directory (core/wal.py
        layout, as written by the Python Store's ledger hook): snapshot
        entries restore with their original mod_revs and absolute
        expiries (kv_restore, no history), the revision counter seals
        at the snapshot point (kv_restore_seal — revisions at or below
        it are not replayable, the watch-window contract), and the
        record tail replays at its exact revisions (kv_replay). Same
        recovered-prefix contract as Store.recover: same revision,
        same live object set, expired keys never resurrected. This is
        also the migration path from the in-proc ledger onto the
        native engine: capture with one backend, recover into the
        other."""
        import time as _time

        from ..utils.metrics import global_metrics
        from .wal import WalError, read_wal_grouped

        t0 = _time.monotonic()
        lib = _load_library()
        if not getattr(lib, "has_recovery", False):
            raise WalError("native library predates the recovery ABI; "
                           "rebuild kvstore.cc")
        snap, groups = read_wal_grouped(wal_dir)
        st = cls(window=window, scheme=scheme)
        etype_code = {v: k for k, v in _EVENT_TYPES.items()}
        if snap is not None:
            for key, mod_rev, expiry, wire in snap["entries"]:
                raw = _json.dumps(wire).encode()
                lib.kv_restore(st._h, key.encode(), raw, len(raw),
                               int(mod_rev), float(expiry or 0))
            lib.kv_restore_seal(st._h, int(snap["rev"]))
        txn_ok = getattr(lib, "has_txn_replay", False)
        n_records = 0
        for group in groups:
            n_records += len(group)
            if len(group) > 1 and txn_ok:
                # a TXN frame replays as ONE engine lock window — the
                # same atomic unit it was on disk and at commit time
                n = len(group)
                prepared = []
                for rev, etype, key, expiry, wire in group:
                    raw = _json.dumps(wire).encode()
                    obj_rev = int((wire.get("metadata") or {})
                                  .get("resourceVersion") or rev)
                    prepared.append((rev, etype_code[etype], key.encode(),
                                     raw, obj_rev, float(expiry or 0)))
                revs = (ctypes.c_uint64 * n)(*[p[0] for p in prepared])
                types = (ctypes.c_uint8 * n)(*[p[1] for p in prepared])
                keys = (ctypes.c_char_p * n)(*[p[2] for p in prepared])
                vals = (ctypes.c_char_p * n)(*[p[3] for p in prepared])
                val_lens = (ctypes.c_uint64 * n)(
                    *[len(p[3]) for p in prepared])
                obj_revs = (ctypes.c_uint64 * n)(
                    *[p[4] for p in prepared])
                expiries = (ctypes.c_double * n)(
                    *[p[5] for p in prepared])
                last = group[-1][0]
                if lib.kv_replay_txn(st._h, n, revs, types, keys, vals,
                                     val_lens, obj_revs,
                                     expiries) != last:
                    raise WalError(
                        f"txn replay of revisions "
                        f"{group[0][0]}..{last} rejected "
                        f"(engine at {st.current_revision})")
                continue
            for rev, etype, key, expiry, wire in group:
                raw = _json.dumps(wire).encode()
                obj_rev = int((wire.get("metadata") or {})
                              .get("resourceVersion") or rev)
                if lib.kv_replay(st._h, rev, etype_code[etype],
                                 key.encode(), raw, len(raw), obj_rev,
                                 float(expiry or 0)) != rev:
                    raise WalError(f"replay of revision {rev} rejected "
                                   f"(engine at {st.current_revision})")
        global_metrics.inc("wal_recoveries_total")
        st.recovery_stats = {
            "snapshot_rev": snap["rev"] if snap is not None else 0,
            "replayed_records": n_records,
            "recovered_revision": st.current_revision,
            "seconds": round(_time.monotonic() - t0, 6),
        }
        return st

    def create(self, key: str, obj: Any, ttl: Optional[float] = None) -> Any:
        if self._wal_on:
            for _ in range(16):
                rev = self.current_revision + 1
                expiry = (_time.time() + ttl) if ttl else None
                stamped = self._stamp(obj, rev)
                wire = self.scheme.encode_dict(stamped)
                val = _json.dumps(wire).encode()
                r = self._kv_commit(
                    rev, [(0, key, val, 0, expiry)],
                    [record_payload(rev, watchpkg.ADDED, key, expiry,
                                    wire)])
                if r == ERR_RACED:
                    continue
                if r == ERR_EXISTS:
                    kind, name = self._key_name(key)
                    raise AlreadyExists(kind=kind, name=name)
                self._cache_put(key, rev, stamped)
                return stamped
            raise Conflict(f"create {key}: revision window kept racing")
        raw = self._encode(obj)
        rev = self._lib.kv_create(self._h, key.encode(), raw, len(raw),
                                  float(ttl or 0))
        if rev == ERR_EXISTS:
            kind, name = self._key_name(key)
            raise AlreadyExists(kind=kind, name=name)
        out = self._stamp(obj, rev)
        self._cache_put(key, rev, out)
        return out

    def create_batch(self, entries: List[Tuple[str, Any, Optional[float]]],
                     owned_meta: bool = False) -> List[Any]:
        """Batched create in ONE engine pass (kv_create_batch):
        all-or-nothing exactly like the in-memory Store.create_batch —
        any pre-existing or intra-batch duplicate key fails the whole
        batch before anything commits — with one lock window and
        consecutive revisions C-side. owned_meta as in
        Store.create_batch: stamp the fresh caller-owned metadata in
        place instead of a replace-clone pair per object."""
        if not entries:
            return []
        if self._wal_on:
            return self._create_batch_walled(entries, owned_meta)
        encoded = [(k, self._encode(o), ttl) for k, o, ttl in entries]
        n = len(encoded)
        keys = (ctypes.c_char_p * n)(*[k.encode() for k, _v, _t in encoded])
        vals = (ctypes.c_char_p * n)(*[v for _k, v, _t in encoded])
        val_lens = (ctypes.c_uint64 * n)(
            *[len(v) for _k, v, _t in encoded])
        ttls = (ctypes.c_double * n)(
            *[float(t or 0) for _k, _v, t in encoded])
        first = self._lib.kv_create_batch(self._h, n, keys, vals,
                                          val_lens, ttls)
        if first == ERR_EXISTS:
            # re-raise with the precise key for the caller's message
            for k, _v, _t in encoded:
                try:
                    self._get_raw(k)
                except NotFound:
                    continue
                kind, name = self._key_name(k)
                raise AlreadyExists(kind=kind, name=name)
            kind, name = self._key_name(encoded[0][0])
            raise AlreadyExists(kind=kind, name=name)
        out = []
        for i, (key, obj, _ttl) in enumerate(entries):
            if owned_meta:
                obj.metadata.resource_version = str(first + i)
                stamped = obj
            else:
                stamped = self._stamp(obj, first + i)
            self._cache_put(key, first + i, stamped)
            out.append(stamped)
        return out

    def _create_batch_walled(self, entries, owned_meta: bool) -> List[Any]:
        """create_batch through the native commit path: one
        kv_commit_txn window, n flat ADDED records journaled — the
        same per-record framing Store.create_batch writes."""
        for _ in range(10):
            first = self.current_revision + 1
            now = _time.time()
            staged: List[tuple] = []
            payloads: List[bytes] = []
            outs: List[Tuple[str, Any]] = []
            for i, (key, obj, ttl) in enumerate(entries):
                rev = first + i
                expiry = (now + ttl) if ttl else None
                if owned_meta:
                    obj.metadata.resource_version = str(rev)
                    stamped = obj
                else:
                    stamped = self._stamp(obj, rev)
                wire = self.scheme.encode_dict(stamped)
                val = _json.dumps(wire).encode()
                staged.append((0, key, val, 0, expiry))
                payloads.append(record_payload(rev, watchpkg.ADDED, key,
                                               expiry, wire))
                outs.append((key, stamped))
            r = self._kv_commit(first, staged, payloads)
            if r == ERR_RACED:
                continue
            if r == ERR_EXISTS:
                for key, _obj, _ttl in entries:
                    try:
                        self._get_raw(key)
                    except NotFound:
                        continue
                    kind, name = self._key_name(key)
                    raise AlreadyExists(kind=kind, name=name)
                kind, name = self._key_name(entries[0][0])
                raise AlreadyExists(kind=kind, name=name)
            out = []
            for i, (key, obj) in enumerate(outs):
                self._cache_put(key, first + i, obj)
                out.append(obj)
            return out
        raise Conflict("create_batch: revision window kept racing")

    def set(self, key: str, obj: Any, ttl: Optional[float] = None) -> Any:
        if self._wal_on:
            for _ in range(16):
                rev = self.current_revision + 1
                try:
                    self._get_raw_ex(key)
                    existed = True
                except NotFound:
                    existed = False
                expiry = (_time.time() + ttl) if ttl else None
                stamped = self._stamp(obj, rev)
                wire = self.scheme.encode_dict(stamped)
                val = _json.dumps(wire).encode()
                etype = (watchpkg.MODIFIED if existed
                         else watchpkg.ADDED)
                r = self._kv_commit(
                    rev, [(1 if existed else 0, key, val, 0, expiry)],
                    [record_payload(rev, etype, key, expiry, wire)])
                if r in (ERR_RACED, ERR_EXISTS, ERR_NOT_FOUND):
                    continue  # raced, or existence flipped: re-probe
                self._cache_put(key, rev, stamped)
                return stamped
            raise Conflict(f"set {key}: revision window kept racing")
        raw = self._encode(obj)
        rev = self._lib.kv_set(self._h, key.encode(), raw, len(raw),
                               float(ttl or 0))
        out = self._stamp(obj, rev)
        self._cache_put(key, rev, out)
        return out

    def update(self, key: str, obj: Any) -> Any:
        rv = obj.metadata.resource_version
        expect = int(rv) if rv else 0
        if self._wal_on:
            for _ in range(16):
                _old, mod_rev, expiry = self._get_raw_ex(key)
                rev = self.current_revision + 1
                stamped = self._stamp(obj, rev)
                wire = self.scheme.encode_dict(stamped)
                val = _json.dumps(wire).encode()
                r = self._kv_commit(
                    rev,
                    # TTL carries over, like kv_update / Store.update
                    [(1, key, val, expect or mod_rev, expiry)],
                    [record_payload(rev, watchpkg.MODIFIED, key,
                                    expiry if expiry else None, wire)])
                if r == ERR_RACED:
                    continue
                if r == ERR_CONFLICT:
                    if expect:
                        raise Conflict(f"operation on {key} failed: "
                                       f"object was modified")
                    continue  # raced an unconditional update: re-read
                if r == ERR_NOT_FOUND:
                    raise NotFound(name=key)
                self._cache_put(key, rev, stamped)
                return stamped
            raise Conflict(f"update {key}: revision window kept racing")
        raw = self._encode(obj)
        rev = self._lib.kv_update(self._h, key.encode(), raw, len(raw),
                                  expect)
        if rev == ERR_NOT_FOUND:
            raise NotFound(name=key)
        if rev == ERR_CONFLICT:
            raise Conflict(
                f"operation on {key} failed: object was modified")
        out = self._stamp(obj, rev)
        self._cache_put(key, rev, out)
        return out

    def guaranteed_update(self, key: str, fn: Callable[[Any], Any],
                          retries: int = 10) -> Any:
        if self._wal_on:
            return self._txn_commit_native([(key, fn)], flat=True)[0]
        for _ in range(retries):
            raw, mod_rev = self._get_raw(key)
            new_obj = fn(self._decode(raw, mod_rev, key))
            new_raw = self._encode(new_obj)
            rev = self._lib.kv_update(self._h, key.encode(), new_raw,
                                      len(new_raw), mod_rev)
            if rev == ERR_CONFLICT:
                continue  # raced: re-read and retry (etcd_helper.go:449)
            if rev == ERR_NOT_FOUND:
                raise NotFound(name=key)
            out = self._stamp(new_obj, rev)
            self._cache_put(key, rev, out)
            return out
        raise Conflict(f"guaranteed_update on {key}: too many retries")

    def delete(self, key: str, expect_rv: Optional[str] = None) -> Any:
        while True:
            raw, mod_rev = self._get_raw(key)
            if expect_rv and int(expect_rv) != mod_rev:
                raise Conflict(f"delete {key}: revision mismatch")
            if self._wal_on:
                rev = self.current_revision + 1
                wire = _json.loads(raw)
                r = self._kv_commit(
                    rev,
                    [(2, key, raw,
                      mod_rev if not expect_rv else int(expect_rv),
                      0.0)],
                    [record_payload(rev, watchpkg.DELETED, key, None,
                                    wire)])
                if r == ERR_RACED:
                    continue
                if r == ERR_NOT_FOUND:
                    raise NotFound(name=key)
                if r == ERR_CONFLICT:
                    if expect_rv:
                        raise Conflict(f"delete {key}: revision mismatch")
                    continue  # raced an unconditional delete: re-read
                return self._decode(raw, mod_rev, key)
            rev = self._lib.kv_delete(self._h, key.encode(),
                                      mod_rev if not expect_rv
                                      else int(expect_rv))
            if rev == ERR_NOT_FOUND:
                raise NotFound(name=key)
            if rev == ERR_CONFLICT:
                continue  # raced an unconditional delete: re-read
            return self._decode(raw, mod_rev, key)

    def batch(self, ops: Iterable[Tuple[str, Callable[[Any], Any]]]
              ) -> List[Any]:
        ops = list(ops)
        if self._wal_on:
            # journaled stores route the chunked control arm through
            # the commit path too (flat frames, exactly the per-record
            # framing Store.batch journals) — kv_batch has no WAL hook
            return self._txn_commit_native(ops, flat=True)
        for _ in range(10):
            staged: List[Tuple[str, Any, bytes, int]] = []
            for key, fn in ops:
                raw, mod_rev = self._get_raw(key)
                new_obj = fn(self._decode(raw, mod_rev, key))
                staged.append((key, new_obj, self._encode(new_obj),
                               mod_rev))
            n = len(staged)
            keys = (ctypes.c_char_p * n)(
                *[s[0].encode() for s in staged])
            vals = (ctypes.c_char_p * n)(*[s[2] for s in staged])
            val_lens = (ctypes.c_uint64 * n)(
                *[len(s[2]) for s in staged])
            expects = (ctypes.c_uint64 * n)(*[s[3] for s in staged])
            first = self._lib.kv_batch(self._h, n, keys, vals, val_lens,
                                       expects)
            if first == ERR_CONFLICT:
                continue  # some key raced: restage the whole tile
            if first == ERR_NOT_FOUND:
                raise NotFound(name="batch")
            out = []
            for i, (key, new_obj, _raw, _mr) in enumerate(staged):
                stamped = self._stamp(new_obj, first + i)
                self._cache_put(key, first + i, stamped)
                out.append(stamped)
            return out
        raise Conflict("batch: too many retries")

    def commit_txn(self, ops: Iterable[Tuple[str, Callable[[Any], Any]]]
                   ) -> List[Any]:
        """Multi-key transaction through the native commit path
        (kv_commit_txn): Python pre-assigns the revision window and
        stages the encoded batch; the engine validates the window,
        applies the whole op list under one mutex window with
        consecutive revisions (all-or-nothing CAS), appends the WAL
        TXN frame when journaling, and hands the ordered event batch
        to the native publisher ring — ledger + publish off the GIL.

        native_publish=False (or a stale prebuilt library) is the
        control arm: kv_batch delegate, inline publish under the
        engine mutex — the same events, on the caller's thread."""
        if not self._native_publish:
            return self.batch(ops)
        return self._txn_commit_native(list(ops), flat=False)

    # --------------------------------------------------------- reads

    def _get_raw(self, key: str, initial: int = 1 << 16) -> Tuple[bytes, int]:
        size = initial
        while True:
            buf = ctypes.create_string_buffer(size)
            mod_rev = ctypes.c_uint64()
            n = self._lib.kv_get(self._h, key.encode(), buf, size,
                                 ctypes.byref(mod_rev))
            if n == ERR_NOT_FOUND:
                raise NotFound(name=key)
            if n == ERR_TOO_SMALL:
                size *= 4
                continue
            return buf.raw[:n], int(mod_rev.value)

    def _get_raw_ex(self, key: str, initial: int = 1 << 16
                    ) -> Tuple[bytes, int, float]:
        """_get_raw plus the entry's absolute expiry (kv_get_ex), so
        the commit path can carry TTLs over exactly like Store.update.
        Stale prebuilt library: degrade to (raw, mod_rev, 0.0)."""
        if not getattr(self._lib, "has_commit_path", False):
            raw, mod_rev = self._get_raw(key, initial)
            return raw, mod_rev, 0.0
        size = initial
        while True:
            buf = ctypes.create_string_buffer(size)
            mod_rev = ctypes.c_uint64()
            expiry = ctypes.c_double()
            n = self._lib.kv_get_ex(self._h, key.encode(), buf, size,
                                    ctypes.byref(mod_rev),
                                    ctypes.byref(expiry))
            if n == ERR_NOT_FOUND:
                raise NotFound(name=key)
            if n == ERR_TOO_SMALL:
                size *= 4
                continue
            return buf.raw[:n], int(mod_rev.value), float(expiry.value)

    def get(self, key: str) -> Any:
        raw, mod_rev = self._get_raw(key)
        return self._decode(raw, mod_rev, key)

    def list(self, prefix: str,
             predicate: Optional[Callable[[Any], bool]] = None
             ) -> Tuple[List[Any], int]:
        size = 1 << 20
        while True:
            buf = ctypes.create_string_buffer(size)
            n = self._lib.kv_list(self._h, prefix.encode(), buf, size)
            hint = _size_hint(n)
            if hint is not None:
                size = hint
                continue
            if n < 0:
                raise RuntimeError(f"kv_list failed: {n}")
            break
        data = buf.raw[:n]
        store_rev, count = struct.unpack_from("<QI", data, 0)
        pos = 12
        items = []
        for _ in range(count):
            (obj_rev,) = struct.unpack_from("<Q", data, pos)
            pos += 8
            (klen,) = struct.unpack_from("<I", data, pos)
            pos += 4 + klen
            k = data[pos - klen:pos].decode()
            (vlen,) = struct.unpack_from("<I", data, pos)
            pos += 4
            items.append(self._decode(data[pos:pos + vlen], obj_rev, k))
            pos += vlen
        if predicate is not None:
            items = [o for o in items if predicate(o)]
        items.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return items, int(store_rev)

    # --------------------------------------------------------- watch

    def _events_since(self, since_rev: int, prefix: str
                      ) -> List[Tuple[int, str, str, Any]]:
        size = 1 << 20
        while True:
            buf = ctypes.create_string_buffer(size)
            n = self._lib.kv_events(self._h, since_rev, prefix.encode(),
                                    buf, size)
            if n == ERR_EXPIRED:
                raise Expired(
                    f"resourceVersion {since_rev} is too old")
            hint = _size_hint(n)
            if hint is not None:
                size = hint
                continue
            if n < 0:
                raise RuntimeError(f"kv_events failed: {n}")
            break
        data = buf.raw[:n]
        (count,) = struct.unpack_from("<I", data, 0)
        pos = 4
        out = []
        for _ in range(count):
            (rev,) = struct.unpack_from("<Q", data, pos)
            pos += 8
            etype = _EVENT_TYPES[data[pos]]
            pos += 1
            (klen,) = struct.unpack_from("<I", data, pos)
            pos += 4 + klen
            k = data[pos - klen:pos].decode()
            (obj_rev,) = struct.unpack_from("<Q", data, pos)
            pos += 8
            (vlen,) = struct.unpack_from("<I", data, pos)
            pos += 4
            out.append((rev, etype, k,
                        self._decode(data[pos:pos + vlen], obj_rev, k)))
            pos += vlen
        return out

    def watch(self, prefix: str, since_rev: Optional[int] = None,
              capacity: int = 100_000,
              predicate=None,
              shard: Optional["_NativeShard"] = None) -> watchpkg.Watcher:
        if shard is not None:
            return self._watch_on_shard(prefix, since_rev, capacity,
                                        predicate, shard)
        start_rev = (self.current_revision if since_rev is None
                     else since_rev)
        # Membership snapshot for the filter seed, taken BEFORE the
        # replay read: any write landing between the two shows up in
        # replay (or the pump), so its key is excluded from the seed and
        # tracked from its events instead — never seeded stale.
        snapshot = (self.list(prefix)[0] if predicate is not None else [])
        replay = self._events_since(start_rev, prefix)  # raises Expired
        w = watchpkg.Watcher(max(capacity, len(replay) + 16))

        # Filtered-watch transition semantics (Store._filtered_event's
        # contract) without prev objects on the wire: track each key's
        # last predicate result — entering the selector surfaces as
        # ADDED, leaving it as DELETED with the current object. Keys
        # untouched by the replay are seeded exactly from the snapshot
        # (their objects haven't changed since start_rev); keys first
        # seen mid-stream with no seed resolve conservatively: a
        # leave-event delivers DELETED (suppressing it would strand
        # stale cache entries) and a matching MODIFIED delivers ADDED —
        # both are the duplicate-tolerant direction for reflectors, the
        # same bias the reference's watch cache has when it replays its
        # window as init ADDED events (pkg/storage/cacher.go).
        known: dict = {}
        if predicate is not None:
            touched = {(o.metadata.namespace, o.metadata.name)
                       for _rev, _etype, _k, o in replay}
            for obj in snapshot:
                k = (obj.metadata.namespace, obj.metadata.name)
                if k not in touched:
                    known[k] = predicate(obj)

        def mapped(etype: str, obj) -> Optional[watchpkg.Event]:
            if predicate is None:
                return watchpkg.Event(etype, obj)
            key = (obj.metadata.namespace, obj.metadata.name)
            was = known.get(key)          # True / False / None (unknown)
            if etype == watchpkg.DELETED:
                known.pop(key, None)
                return None if was is False else watchpkg.Event(etype, obj)
            match_new = predicate(obj)
            known[key] = match_new
            if match_new:
                if was is True and etype != watchpkg.ADDED:
                    return watchpkg.Event(watchpkg.MODIFIED, obj)
                return watchpkg.Event(watchpkg.ADDED, obj)
            if was is False:
                return None
            if was is None and etype == watchpkg.ADDED:
                return None               # created non-matching: never seen
            return watchpkg.Event(watchpkg.DELETED, obj)

        last = start_rev
        for rev, etype, _k, obj in replay:
            ev = mapped(etype, obj)
            if ev is not None:
                w.send(ev)
            last = rev

        def pump(last_rev: int) -> None:
            while not w.stopped:
                # kv_wait parks in native code (GIL released)
                self._lib.kv_wait(self._h, last_rev, 0.5)
                if w.stopped or self._closed:
                    return
                try:
                    events = self._events_since(last_rev, prefix)
                except Expired:
                    w.fail(Expired("watch window overrun"))
                    return
                for rev, etype, _k, obj in events:
                    ev = mapped(etype, obj)
                    if ev is not None and not w.send(ev):
                        w.fail(Expired(
                            f"watch delivery queue overrun (capacity "
                            f"{w.capacity}); re-list and re-watch"))
                        return
                    last_rev = rev

        t = threading.Thread(target=pump, args=(last,), daemon=True,
                             name="native-store-watch")
        t.start()
        self._watch_threads.append(t)
        self._watchers.append(w)
        return w

    # --------------------------------------------- worker fan-out shards

    def _build_filter(self, prefix: str, predicate):
        """Per-watcher event filter for shard delivery: the same
        filtered-watch transition closure the dedicated-pump path
        builds, seeded from a membership snapshot. Returns
        mapped(etype, obj) -> Optional[Event]. Caller must hold the
        shard lock from before the snapshot until the watcher is
        registered (the closure's `known` dict is pump-thread-only
        after that)."""
        if predicate is None:
            return lambda etype, obj: watchpkg.Event(etype, obj)
        known: dict = {}
        for obj in self.list(prefix)[0]:
            known[(obj.metadata.namespace, obj.metadata.name)] = \
                predicate(obj)

        def mapped(etype: str, obj):
            key = (obj.metadata.namespace, obj.metadata.name)
            was = known.get(key)
            if etype == watchpkg.DELETED:
                known.pop(key, None)
                return None if was is False else watchpkg.Event(etype, obj)
            match_new = predicate(obj)
            known[key] = match_new
            if match_new:
                if was is True and etype != watchpkg.ADDED:
                    return watchpkg.Event(watchpkg.MODIFIED, obj)
                return watchpkg.Event(watchpkg.ADDED, obj)
            if was is False:
                return None
            if was is None and etype == watchpkg.ADDED:
                return None
            return watchpkg.Event(watchpkg.DELETED, obj)

        return mapped

    def _watch_on_shard(self, prefix: str, since_rev: Optional[int],
                        capacity: int, predicate,
                        shard: "_NativeShard") -> watchpkg.Watcher:
        """Register a watcher on a worker shard. Under the shard lock
        its cursor is frozen (the pump advances it only while holding
        the lock), so replay-up-to-cursor + floor = max(since, cursor)
        is exactly-once across the replay->live handoff: events at
        rev <= cursor come from history now, events above arrive on
        the shard pump. Predicate watchers are duplicate-tolerant in
        one direction: a key committed after the membership snapshot
        but before the cursor advances may surface once in the seed
        AND once as a live ADDED (reflector-safe; the reference's
        watch cache has the same bias replaying its window as init
        ADDED events)."""
        with shard.lock:
            cursor = shard.cursor_rev
            start_rev = cursor if since_rev is None else since_rev
            mapped = self._build_filter(prefix, predicate)
            replay = [e for e in self._events_since(start_rev, prefix)
                      if e[0] <= cursor]           # raises Expired
            w = watchpkg.Watcher(max(capacity, len(replay) + 16))
            for _rev, etype, _k, obj in replay:
                ev = mapped(etype, obj)
                if ev is not None:
                    w.send(ev)
            floor = max(start_rev, cursor)
            shard.watchers.append((prefix, mapped, w, floor))
        return w

    def attach_fanout_shard(self, name: str = "") -> "_NativeShard":
        """Create a worker delivery shard (one pump thread fanning the
        native event log out to that worker's watchers). Caller starts
        it (shard.start()) and must stop() it on teardown; close()
        sweeps stragglers."""
        sh = _NativeShard(self, name or f"shard-{len(self._shards)}")
        with self._shards_lock:
            self._shards = self._shards + [sh]
        return sh

    def detach_fanout_shard(self, shard: "_NativeShard") -> None:
        with self._shards_lock:
            self._shards = [s for s in self._shards if s is not shard]
        shard.detached = True

    def fanout_shards(self) -> List["_NativeShard"]:
        return list(self._shards)


class _NativeShard:
    """One apiserver worker's delivery partition over the native event
    log: a cursor revision plus the watchers registered through that
    worker, drained by ONE pump thread parked in kv_wait. Where the
    dedicated-pump watch() path spends a thread per watcher, a shard
    spends one thread per WORKER — the shape the 10k-watcher plane
    needs — at the cost of serializing that worker's fan-out (which is
    the point: delivery parallelism comes from adding workers).

    Lock contract: `lock` freezes (cursor_rev, watchers) for
    registration; the pump holds it across consume+fanout of a batch,
    so a watcher registering mid-batch either replays those events
    from history (cursor not yet advanced) or receives them live
    (already in the watcher list) — never both, never neither."""

    def __init__(self, store: "NativeStore", name: str):
        self._store = store
        self.name = name
        self.lock = threading.Lock()
        self.cursor_rev = store.current_revision
        # entries: (prefix, mapped, watcher, floor_rev)
        self.watchers: List[tuple] = []
        self.delivered_events = 0
        self.delivered_batches = 0
        self.detached = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def pending(self) -> int:
        return max(0, self._store.current_revision - self.cursor_rev)

    def drain(self) -> int:
        """Advance the cursor over newly-committed events and fan them
        out to this shard's watchers (prefix + floor + filter closure
        per watcher). Returns the number of events delivered. A
        watcher that can't absorb the batch takes the 410 path
        (Watcher.fail) and is dropped; a window overrun fails the
        whole shard's watchers the same way and jumps the cursor to
        head (everything between is unrecoverable from the log)."""
        delivered = 0
        with self.lock:
            try:
                events = self._store._events_since(self.cursor_rev, "")
            except Expired:
                for _p, _m, w, _f in self.watchers:
                    w.fail(Expired(
                        "watch window overrun; re-list and re-watch"))
                self.watchers = []
                self.cursor_rev = self._store.current_revision
                return 0
            if not events:
                return 0
            self.cursor_rev = events[-1][0]
            alive = []
            for prefix, mapped, w, floor in self.watchers:
                if w.stopped:
                    continue
                ok = True
                for rev, etype, key, obj in events:
                    if rev <= floor or not key.startswith(prefix):
                        continue
                    ev = mapped(etype, obj)
                    if ev is None:
                        continue
                    if not w.send(ev):
                        w.fail(Expired(
                            f"watch delivery queue overrun (capacity "
                            f"{w.capacity}); re-list and re-watch"))
                        ok = False
                        break
                    delivered += 1
                if ok:
                    alive.append((prefix, mapped, w, floor))
            self.watchers = alive
            self.delivered_batches += 1
            self.delivered_events += delivered
        return delivered

    def start(self) -> "_NativeShard":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"native-fanout-{self.name}")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            # parks in native code (GIL released); kv_shutdown breaks it
            self._store._lib.kv_wait(
                self._store._h, self.cursor_rev, 0.5)
            if self._stop.is_set() or self._store._closed:
                return
            self.drain()

    def stop(self, timeout: float = 5.0) -> None:
        """Join the pump, 410 any still-registered watchers (a worker
        going away mid-stream must be visible — clients re-list
        against a surviving worker), detach from the store."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None
        with self.lock:
            for _p, _m, w, _f in self.watchers:
                w.fail(Expired(
                    "apiserver worker shutting down; "
                    "re-list and re-watch"))
            self.watchers = []
        self._store.detach_fanout_shard(self)
