"""Revisioned KV store with CAS and windowed watch — the cluster's etcd.

Reference mapping:
  - storage.Interface (pkg/storage/interfaces.go:74): Create / Set / Delete /
    Get / List / GuaranteedUpdate / Watch / WatchList — all here.
  - etcd CAS semantics (pkg/storage/etcd/etcd_helper.go:449 GuaranteedUpdate):
    optimistic-concurrency via resourceVersion; `guaranteed_update` retries
    the caller's update function on conflict.
  - watch cache (pkg/storage/cacher.go:109): a sliding in-memory window of
    (revision, event) so watchers can resume from any recent resourceVersion
    without replaying from scratch; too-old versions raise Expired (the
    HTTP layer maps this to 410 Gone, prompting a client re-list).

Being in-process (etcd is an external process in the reference), storage and
watch cache collapse into one component. Concurrency contract: stored objects
are logically FROZEN — readers get the stored object without copying
(list/watch fan-out to thousands of agents must not deep-copy per reader);
writers hand ownership of the written object to the store and must not mutate
it afterwards. Updates build new objects (dataclasses.replace or codec
round-trip), never mutate in place. This is the same contract Go client
caches impose informally.

A single global revision counter doubles as resourceVersion (stringified),
exactly like etcd's modifiedIndex in the reference.

Every commit runs in three phases (the decomposition the 5k-node profile
demanded — roughly half of each ledger-lock hold was watch fan-out, and
three committers serialize on this lock at full load):

  stage   — object construction and conflict checks; as much as the verb's
            semantics allow runs before the lock (the registry's
            _prepare_create does the heavy cloning outside it entirely)
  ledger  — revision bump + _data/_seg_keys/history/list-cache mutation;
            the ONLY phase that holds self._lock
  publish — predicate mapping (_filtered_event) + Watcher.send/send_many,
            run by the ordered publisher AFTER the ledger lock is released

The publisher is a FIFO of committed batches fed under the ledger lock (so
queue order IS revision order) and drained under a dedicated _pub_lock by
whichever committer gets there first: watchers observe events in strict
revision order no matter which thread fans them out. A watcher registering
mid-flight replays the history window only up to the last PUBLISHED
revision and carries a per-watcher floor for live delivery, so the
replay->live handoff has no duplicates and no gaps (see watch()).

Fleet serving (the multi-consumer ring): the publish queue is a RING of
sequence-numbered batches that more than one delivery shard may consume.
The default shard (shard 0) is the classic committer-drained path above —
its lock IS _pub_lock, its high-water mark IS _published_rev, byte-for-byte
the old behavior when no worker shards exist. `attach_fanout_shard()` adds
an independent consumer: its own watcher partition, its own delivery
cursor over the ring, its own pump thread — so N apiserver workers fan out
in parallel instead of queuing behind one publisher. A batch is retained
until EVERY shard's cursor passes it (trim at min-cursor); per-shard
registration freezes that shard's published_rev under its shard lock, so
the exactly-once replay->live handoff holds per worker.
"""

from __future__ import annotations

import fnmatch
import heapq
import threading
from collections import OrderedDict, deque
from dataclasses import replace
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .. import obs
from ..utils.clock import REAL, Clock
from ..utils.metrics import (FANOUT_QUEUE_DEPTH_GAUGE, WATCH_LAG_HISTOGRAM,
                             MetricsRegistry, global_metrics)
from . import watch as watchpkg
from .errors import AlreadyExists, Conflict, Expired, NotFound
from .types import fast_replace


def _with_rv(obj: Any, rev: int) -> Any:
    meta = fast_replace(obj.metadata, resource_version=str(rev))
    return fast_replace(obj, metadata=meta)


class _DrainOverlap:
    """Witness of concurrent ring drains. On a 1-core box the 1->N
    worker wall-clock win can vanish under the GIL while the
    architecture is still correct; this counts how often two or more
    shards were mid-fanout at once, which is the gate the fan-out
    bench falls back to (PROFILE-style honesty, see ISSUE 18)."""

    __slots__ = ("_mu", "_active", "max_concurrent", "entries",
                 "overlapped")

    def __init__(self):
        self._mu = threading.Lock()
        self._active = 0
        self.max_concurrent = 0
        self.entries = 0        # batches drained, all shards
        self.overlapped = 0     # drains entered while another ran

    def enter(self) -> None:
        with self._mu:
            self._active += 1
            self.entries += 1
            if self._active > 1:
                self.overlapped += 1
            if self._active > self.max_concurrent:
                self.max_concurrent = self._active

    def exit(self) -> None:
        with self._mu:
            self._active -= 1

    def snapshot(self) -> dict:
        with self._mu:
            return {"entries": self.entries,
                    "overlapped": self.overlapped,
                    "max_concurrent": self.max_concurrent,
                    "overlap_frac": (round(self.overlapped
                                           / self.entries, 4)
                                     if self.entries else 0.0)}


class FanoutShard:
    """One delivery partition of the store's publish ring.

    A shard owns a slice of the watcher population, a cursor over the
    shared ring, and (once start()ed) the pump thread that drains it —
    the unit an apiserver worker holds so N workers deliver watch
    events in parallel instead of queuing behind one publisher
    (reference: one cacher per apiserver process over one etcd,
    pkg/storage/cacher.go; ours shares one ledger in-proc).

    Locking: `lock` freezes this shard's (cursor, published_rev,
    watchers) — registration takes it, then the ledger lock, mirroring
    Store._watch_register's publish->ledger order. The pump holds it
    across consuming ONE ring entry and fans out under it, so delivery
    order per shard is revision order and a mid-flight registration's
    floor filters exactly the batches it already replayed."""

    def __init__(self, store: "Store", name: str):
        self._store = store
        self.name = name
        self.lock = threading.Lock()
        self.watchers: List[Tuple[str, Optional[Callable[[Any], bool]],
                                  "watchpkg.Watcher", int]] = []
        self.published_rev = 0   # set at attach, under the ledger lock
        self.cursor = 0          # next ring seq this shard consumes
        self.wake = threading.Event()
        self.delivered_batches = 0
        self.delivered_events = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.detached = False

    # ------------------------------------------------------- delivery

    def drain(self, max_batches: Optional[int] = None) -> int:
        """Consume ring entries at this shard's cursor; returns batches
        delivered. Runs on the pump thread (or inline from tests)."""
        store = self._store
        n = 0
        while max_batches is None or n < max_batches:
            with self.lock:
                entry = store._ring_next(self.cursor)
                if entry is None:
                    break
                seq, t_enq, items = entry
                # pending depth BEFORE consuming this entry: the
                # backlog a stalled worker shows on dashboards
                store._metrics.set_gauge(
                    FANOUT_QUEUE_DEPTH_GAUGE,
                    float(store._pub_seq - self.cursor),
                    {"shard": self.name})
                store._metrics.observe(
                    WATCH_LAG_HISTOGRAM,
                    store._clock.monotonic() - t_enq,
                    {"shard": self.name})
                store._drain_overlap.enter()
                try:
                    store._fanout(items, self.watchers)
                finally:
                    store._drain_overlap.exit()
                self.published_rev = items[-1][0]
                self.cursor = seq + 1
                self.delivered_batches += 1
                self.delivered_events += len(items)
            n += 1
        if n:
            store._ring_trim()
        return n

    def pending(self) -> int:
        """Ring batches staged but not yet delivered by this shard."""
        return max(0, self._store._pub_seq - self.cursor)

    # ------------------------------------------------------ lifecycle

    def start(self) -> "FanoutShard":
        if self._thread is not None:
            return self
        self._stop.clear()

        def pump() -> None:
            while not self._stop.is_set():
                self.wake.wait(0.2)
                self.wake.clear()   # before drain: a set during the
                self.drain()        # drain forces one more pass
            self.drain()            # deliver anything staged pre-stop

        self._thread = threading.Thread(
            target=pump, daemon=True, name=f"fanout-{self.name}")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Join the pump, fail remaining watchers (they must re-list —
        their worker is gone), and detach from the ring so a dead
        cursor can't pin retention."""
        self._stop.set()
        self.wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None
        with self.lock:
            doomed = self.watchers
            self.watchers = []
        for _prefix, _pred, w, _floor in doomed:
            if not w.stopped:
                w.fail(Expired("apiserver worker shutting down; "
                               "re-list and re-watch"))
        self._store.detach_fanout_shard(self)


class Store:
    def __init__(self, window: int = 100_000, publish_inline: bool = False,
                 wal_dir: Optional[str] = None,
                 fsync_policy: str = "batch",
                 wal_segment_records: int = 10_000,
                 wal_snapshot_records: int = 50_000,
                 clock: Optional[Clock] = None,
                 metrics: Optional[MetricsRegistry] = None):
        # TTL deadlines are wall-clock (they stamp API objects and ride
        # the WAL as absolute expiries); the clock is injectable so
        # expiry behavior is testable without sleeping and so the lint
        # determinism rule has a sanctioned time source to point at
        self._clock = clock or REAL
        # the LEDGER lock: guards _rev/_data/_seg_keys/_history/list
        # caches — and nothing else. Watch fan-out runs outside it.
        self._lock = threading.RLock()
        self._rev = 0
        # key -> (object, mod_rev, expiry_ts|None); insertion-ordered so list
        # output is stable (etcd returns key order; we sort on list anyway).
        self._data: Dict[str, Tuple[Any, int, Optional[float]]] = {}
        # sliding watch window: deque of (rev, event_type, key, obj, prev_obj)
        self._history: deque = deque(maxlen=window)
        self._oldest_rev = 0  # smallest rev still replayable + its predecessor
        # (prefix, server-side predicate | None, watcher, floor): floor
        # is the registration-time delivery cutoff — the publisher skips
        # events with rev <= floor (they were replayed from history, or
        # predate a from-now watch). Guarded by _pub_lock, NOT the
        # ledger lock: only the publish phase touches watchers.
        self._watchers: List[Tuple[str, Optional[Callable[[Any], bool]],
                                   "watchpkg.Watcher", int]] = []
        # publish pipeline: a multi-consumer RING of (seq,
        # enqueue_monotonic, batch) triples — batches of (rev, key,
        # event, prev) — appended under the ledger lock (FIFO order =
        # revision order, seq contiguous) and consumed per delivery
        # shard: shard 0 is drained by committers under _pub_lock
        # exactly as before; worker shards (attach_fanout_shard) drain
        # on their own pump threads at their own cursors. An entry is
        # retained until every cursor has passed it. The enqueue stamp
        # feeds the watch publish->deliver lag histogram: how long a
        # committed event sat queued before watcher fan-out began.
        self._pub_queue: deque = deque()
        self._pub_lock = threading.Lock()
        # leaf lock guarding ring mutation vs cursor-indexed reads
        # (append runs under the ledger lock, trim/reads run under a
        # shard lock; neither store lock is ever taken under it)
        self._ring_lock = threading.Lock()
        self._pub_seq = 0      # next ring sequence number to assign
        self._pub_cursor = 0   # shard 0's cursor (next seq to consume)
        # worker delivery shards (copy-on-write list: _stage_publish
        # iterates it without a lock to wake pumps)
        self._shards: List["FanoutShard"] = []
        self._shards_lock = threading.Lock()
        # multi-consumer overlap witness: how parallel delivery
        # actually ran (the honest readout a 1-core box gates on when
        # wall-clock scaling can't show)
        self._drain_overlap = _DrainOverlap()
        self._metrics = metrics or global_metrics
        # highest revision whose events have been handed to watchers;
        # watch() replays history only up to here (the rest arrives live)
        self._published_rev = 0
        # A/B switch (bench.py --store-ab): publish while still holding
        # the ledger lock — the pre-split serialization, kept so the
        # two-phase win stays measurable end-to-end
        self._publish_inline = publish_inline
        # min-heap of (expiry, key) for TTL'd entries only, so GC cost is
        # O(expired) per write instead of a full-store scan (only events
        # carry TTLs; pods/nodes must not pay for them)
        self._expiry_heap: List[Tuple[float, str]] = []
        # list-snapshot cache (the watch cache's LIST half,
        # cacher.go:214): selector-free list() scans EVERY store entry
        # and re-sorts per call — a 5k-node LIST against a 35k-entry
        # store was most of that endpoint's warm latency. Cached sorted
        # snapshots are invalidated per write via a resource-segment
        # bucket (O(1) per write, no prefix scan).
        self._list_cache: Dict[str, List[Any]] = {}
        self._list_cache_seg: Dict[str, set] = {}
        # per-cached-prefix key -> position map: a MODIFIED write (same
        # key set, same (ns, name) sort order) PATCHES the snapshot
        # element in place instead of dropping the whole snapshot — a
        # 5k-node heartbeat sweep otherwise forces every subsequent
        # LIST into a full bucket re-scan + re-sort for a minute
        # (DENSITY.json 5000x30's GET-nodes tail)
        self._list_cache_idx: Dict[str, Dict[str, int]] = {}
        # resources that ever stored a TTL'd entry (events): their
        # lists are never cached — expiry is passive, so a snapshot
        # could serve an expired object with no write to invalidate it
        self._ttl_segs: set = set()
        # per-segment key index: list(prefix) iterates ONE resource's
        # keys instead of scanning the whole store — at north-star
        # density a nodes LIST would otherwise walk 150k pod keys per
        # call (DENSITY.json 5000x30's GET-nodes whale). dict used as
        # an ordered set; maintained at every key add/remove under the
        # store lock.
        self._seg_keys: Dict[str, Dict[str, None]] = {}
        # per-segment write counter: a LIST response is reusable
        # verbatim while its resource segment has seen no writes, even
        # as OTHER resources advance the global revision (the apiserver
        # keys whole-response byte caches on this; serving the older
        # embedded resourceVersion stays sound because no events exist
        # for this segment between the two revisions)
        self._seg_writes: Dict[str, int] = {}
        # durability (opt-in): a segmented, checksummed write-ahead log
        # hooked at the ledger stage — one record per committed
        # revision, appended under the ledger lock so append order IS
        # revision order (core/wal.py; recovery is Store.recover).
        # wal_dir=None keeps every hot path byte-identical to before.
        self._wal = None
        self._wal_scheme = None
        if wal_dir is not None:
            import os
            from .wal import WalError, WalWriter, _segments, _snapshots
            if os.path.isdir(wal_dir) and (_segments(wal_dir)
                                           or _snapshots(wal_dir)):
                raise WalError(
                    f"{wal_dir} already holds a WAL — a fresh Store "
                    f"would fork its history; use Store.recover()")
            self._wal = WalWriter(wal_dir, fsync_policy=fsync_policy,
                                  segment_records=wal_segment_records,
                                  snapshot_records=wal_snapshot_records)
            from .scheme import default_scheme
            self._wal_scheme = default_scheme

    # ------------------------------------------------------------- helpers

    @property
    def current_revision(self) -> int:
        # lock-free: a single int read is atomic under the GIL, and any
        # torn ordering a caller could observe is indistinguishable from
        # sampling a moment earlier — revision reads must not queue
        # behind a committer's ledger window
        return self._rev

    def _bump(self) -> int:
        self._rev += 1
        return self._rev

    def _expired(self, entry, now: float) -> bool:
        return entry[2] is not None and entry[2] <= now

    @staticmethod
    def _seg(key: str) -> str:
        """'/registry/<resource>/' segment of a key — the invalidation
        bucket for cached list snapshots."""
        i = key.find("/", 10)  # first slash after "/registry/"
        return key[:i + 1] if i > 0 else key

    def _index_add(self, key: str) -> None:
        self._seg_keys.setdefault(self._seg(key), {})[key] = None

    def _index_del(self, key: str) -> None:
        seg = self._seg_keys.get(self._seg(key))
        if seg is not None:
            seg.pop(key, None)

    def _invalidate_lists(self, key: str) -> None:
        """Drop cached list snapshots for the written key's resource
        (caller holds the lock)."""
        if not self._list_cache:
            return
        for p in self._list_cache_seg.pop(self._seg(key), ()):
            self._list_cache.pop(p, None)
            self._list_cache_idx.pop(p, None)

    def _patch_lists(self, key: str, obj: Any) -> None:
        """A value-only write (MODIFIED: key set and sort order
        unchanged) swaps the object into any cached snapshot covering
        it; snapshots that predate the key fall back to invalidation.
        Safe against outstanding readers: list() hands out COPIES, so
        an in-place element swap never mutates a caller's list."""
        if not self._list_cache:
            return
        seg = self._seg(key)
        prefixes = self._list_cache_seg.get(seg)
        if not prefixes:
            return
        drop = []
        for p in prefixes:
            if not key.startswith(p):
                continue
            pos = self._list_cache_idx.get(p, {}).get(key)
            if pos is None:
                drop.append(p)
                continue
            self._list_cache[p][pos] = obj
        for p in drop:
            self._list_cache.pop(p, None)
            self._list_cache_idx.pop(p, None)
            prefixes.discard(p)

    def write_version(self, prefix: str) -> int:
        """Writes ever committed under the prefix's resource segment —
        the validity token for cached LIST response bytes. Lock-free:
        one GIL-atomic dict read, so the apiserver's byte-cache hit
        path (the DENSITY GET-/nodes whale) never queues behind a
        committer. A racing write can only make the read conservative
        (the caller rebuilds a response it could have reused)."""
        return self._seg_writes.get(self._seg(prefix), 0)

    def watch_floor(self) -> int:
        """Smallest resourceVersion a watch can still start from without
        410 Expired. Cached LIST bytes embedding an older rev must be
        rebuilt, or a write-quiet resource's list->watch loop livelocks
        once busier segments roll the shared history window past it.
        Lock-free for the same reason as write_version: the int only
        grows, so a stale read is again the conservative direction."""
        return self._oldest_rev

    def _record(self, rev: int, etype: str, key: str, obj: Any,
                prev: Any) -> watchpkg.Event:
        """History-window bookkeeping for one committed write."""
        seg = self._seg(key)
        self._seg_writes[seg] = self._seg_writes.get(seg, 0) + 1
        if etype == watchpkg.MODIFIED:
            self._patch_lists(key, obj)
        else:
            self._invalidate_lists(key)
        if len(self._history) == self._history.maxlen:
            self._oldest_rev = self._history[0][0]
        self._history.append((rev, etype, key, obj, prev))
        if self._wal is not None:
            self._wal_append(rev, etype, key, obj)
        return watchpkg.Event(etype, obj)

    def _wal_append(self, rev: int, etype: str, key: str, obj: Any) -> None:
        """Buffer one ledger record (caller holds the ledger lock).
        The entry's absolute expiry rides along so recovery restores
        TTL deadlines instead of resurrecting expired keys; for a
        DELETED record the entry is already gone and expiry is moot."""
        entry = self._data.get(key)
        self._wal.append(rev, etype, key,
                         entry[2] if entry is not None else None,
                         self._wal_scheme.encode_dict(obj))

    def _wal_sync(self) -> None:
        """Flush buffered WAL records for the commit that just ran
        (caller still holds the ledger lock — append order stays
        revision order) and compact when the snapshot interval is due.
        The snapshot runs under the lock too: commits stall for its
        duration, which is the price of a consistent cut."""
        w = self._wal
        if w is None:
            return
        w.commit()
        if w.should_snapshot:
            w.write_snapshot(self._snapshot_state())

    def _snapshot_state(self) -> dict:
        """Full store state for a WAL snapshot (caller holds the ledger
        lock): the live entries plus the bookkeeping recovery must
        rebuild bit-identically — per-segment write counters (the LIST
        byte-cache validity tokens) and the TTL'd-segment set."""
        enc = self._wal_scheme.encode_dict
        return {
            "rev": self._rev,
            "entries": [[k, mod_rev, expiry, enc(obj)]
                        for k, (obj, mod_rev, expiry) in self._data.items()],
            "seg_writes": dict(self._seg_writes),
            "ttl_segs": sorted(self._ttl_segs),
        }

    @staticmethod
    def _filtered_event(ev: watchpkg.Event, prev: Any,
                        pred: Callable[[Any], bool]
                        ) -> Optional[watchpkg.Event]:
        """Map one committed event through a watch predicate with the
        reference's filtered-watch transition semantics
        (pkg/storage/etcd/etcd_watcher.go sendModify): an object
        entering the selector surfaces as ADDED, one leaving it as
        DELETED (carrying the current object), and non-matching events
        are suppressed entirely — the watcher's queue never sees them."""
        if ev.type != watchpkg.MODIFIED:
            return ev if pred(ev.object) else None
        match_new = pred(ev.object)
        match_old = prev is not None and pred(prev)
        if match_new:
            return ev if match_old else watchpkg.Event(watchpkg.ADDED,
                                                       ev.object)
        if match_old:
            return watchpkg.Event(watchpkg.DELETED, ev.object)
        return None

    def _fanout(self, items: List[Tuple[int, str, watchpkg.Event, Any]],
                watchers: Optional[list] = None) -> None:
        """Publish phase: deliver one committed batch to watchers — one
        send per watcher when the batch has more than one event — and
        sweep the dead. Runs under the owning shard's lock (never the
        ledger lock): default shard 0 passes _watchers under _pub_lock,
        a worker FanoutShard passes its own partition under its own
        lock — in both cases that lock's holder is the only
        reader/writer of the list, which is what keeps delivery in
        revision order per shard across committer threads.

        Per-watcher floors: an event with rev <= floor was already
        replayed to that watcher from history at registration time (or
        predates a from-now watch) and must not be delivered again.

        For multi-event batches, items is the OUTER loop: every
        watcher's predicate sees one object back-to-back, so the
        registry's (id, rv)-keyed fields memo hits across watchers
        (three pod watchers used to recompute the fields map 3x per
        event on a 30k-binding tile)."""
        if not items:
            return
        if watchers is None:
            watchers = self._watchers
        dead = []
        if len(items) == 1:
            rev, key, ev, prev = items[0]
            for i, (prefix, pred, w, floor) in enumerate(watchers):
                if w.stopped:
                    dead.append(i)
                    continue
                if rev <= floor or not key.startswith(prefix):
                    continue
                mapped = (ev if pred is None
                          else self._filtered_event(ev, prev, pred))
                if mapped is None:
                    continue
                if not w.send(mapped):
                    w.fail(Expired("watch delivery queue overrun "
                                   f"(capacity {w.capacity}); re-list "
                                   "and re-watch"))
                    dead.append(i)
        else:
            per_w: List[Optional[list]] = [None] * len(watchers)
            for i, (_prefix, _pred, w, _floor) in enumerate(watchers):
                if w.stopped:
                    dead.append(i)
                else:
                    per_w[i] = []
            # a commit batch is almost always one resource segment
            # (a bind tile, a status tile, a create storm): resolve the
            # watcher set ONCE against the shared segment instead of
            # testing every watcher's prefix against every key — the
            # per-(event x watcher) startswith was ~a third of fan-out
            # at 30k-pod tiles
            seg0 = self._seg(items[0][1])
            if all(k.startswith(seg0) for _r, k, _e, _p in items):
                active = [(i, prefix, pred, floor)
                          for i, (prefix, pred, _w, floor)
                          in enumerate(watchers)
                          if per_w[i] is not None
                          and (prefix.startswith(seg0)
                               or seg0.startswith(prefix))]
                for rev, key, ev, prev in items:
                    for i, prefix, pred, floor in active:
                        if rev <= floor:
                            continue
                        if len(prefix) > len(seg0) \
                                and not key.startswith(prefix):
                            continue
                        if pred is None:
                            per_w[i].append(ev)
                        else:
                            mapped = self._filtered_event(ev, prev, pred)
                            if mapped is not None:
                                per_w[i].append(mapped)
            else:
                for rev, key, ev, prev in items:
                    for i, (prefix, pred, _w, floor) in enumerate(watchers):
                        evs = per_w[i]
                        if evs is None or rev <= floor \
                                or not key.startswith(prefix):
                            continue
                        if pred is None:
                            evs.append(ev)
                        else:
                            mapped = self._filtered_event(ev, prev, pred)
                            if mapped is not None:
                                evs.append(mapped)
            for i, (_prefix, _pred, w, _floor) in enumerate(watchers):
                evs = per_w[i]
                if not evs:
                    continue
                ok = (w.send(evs[0]) if len(evs) == 1
                      else w.send_many(evs, owned=True))
                if not ok:
                    # the laggard path: a silent stop() here is
                    # indistinguishable from a clean close, so the
                    # client would never re-list — fail() delivers the
                    # cacher's 410-Gone ERROR past the bound instead
                    w.fail(Expired("watch delivery queue overrun "
                                   f"(capacity {w.capacity}); re-list "
                                   "and re-watch"))
                    dead.append(i)
        # dead may interleave stopped-sweep and failed-send indices:
        # delete in strictly descending order
        for i in sorted(dead, reverse=True):
            del watchers[i]

    def _stage_publish(self, items: List[Tuple[int, str, watchpkg.Event,
                                               Any]]) -> None:
        """Hand one committed batch to the ring (caller holds the
        ledger lock, so append order is revision order) — the caller
        MUST call _drain_publish() after releasing the lock. Worker
        shard pumps are woken here; they drain at their own cursors."""
        if items:
            with self._ring_lock:
                self._pub_queue.append(
                    (self._pub_seq, self._clock.monotonic(), items))
                self._pub_seq += 1
            for sh in self._shards:
                sh.wake.set()

    def _emit(self, rev: int, etype: str, key: str, obj: Any,
              prev: Any) -> None:
        """Ledger bookkeeping + publisher handoff for one write (caller
        holds the ledger lock and drains after releasing it)."""
        self._stage_publish(
            [(rev, key, self._record(rev, etype, key, obj, prev), prev)])

    def _ring_next(self, cursor: int) -> Optional[tuple]:
        """(seq, t_enq, items) at seq == cursor, or None when the ring
        holds nothing at or past it. Seqs are contiguous, so the entry
        sits at a computed offset from the ring head; the ring lock
        pins the head against a concurrent append/trim for the read."""
        with self._ring_lock:
            q = self._pub_queue
            if not q:
                return None
            idx = cursor - q[0][0]
            if idx >= len(q):
                return None
            return q[idx]

    def _ring_trim(self) -> None:
        """Drop ring entries every consumer has passed (min-cursor).
        Cursors only grow, so a racy read of another shard's cursor is
        conservative — an entry lives at most one round longer."""
        with self._ring_lock:
            q = self._pub_queue
            if not q:
                return
            low = self._pub_cursor
            for sh in self._shards:
                if sh.cursor < low:
                    low = sh.cursor
            while q and q[0][0] < low:
                q.popleft()

    def _drain_publish(self) -> None:
        """Publish every staged batch to the DEFAULT shard, in order,
        outside the ledger lock. The non-blocking acquire hands a busy
        publisher the work instead of parking this committer behind
        another thread's fan-out; the outer re-check after release
        closes the stage-after-empty window (a batch staged while the
        previous drainer was exiting is picked up here, never
        stranded). Worker shards consume the same ring on their own
        pump threads — this path neither waits for nor wakes them."""
        while self._pub_seq > self._pub_cursor:
            if not self._pub_lock.acquire(blocking=False):
                return  # the live publisher drains our batch in order
            try:
                while True:
                    entry = self._ring_next(self._pub_cursor)
                    if entry is None:
                        break
                    seq, t_enq, items = entry
                    # publish->deliver lag, observed OUTSIDE the ledger
                    # lock (metrics take their own registry lock; the
                    # histogram dual-lands via the pinned boundaries)
                    self._metrics.observe(
                        WATCH_LAG_HISTOGRAM,
                        self._clock.monotonic() - t_enq)
                    self._drain_overlap.enter()
                    try:
                        self._fanout(items)
                    finally:
                        self._drain_overlap.exit()
                    self._published_rev = items[-1][0]
                    self._pub_cursor = seq + 1
            finally:
                self._pub_lock.release()
            self._ring_trim()

    def _gc_expired(self, now: Optional[float] = None) -> None:
        """Lazily delete TTL-expired entries (reference: etcd event TTL)."""
        if not self._expiry_heap:
            return
        now = self._clock.now() if now is None else now
        while self._expiry_heap and self._expiry_heap[0][0] <= now:
            expiry, k = heapq.heappop(self._expiry_heap)
            entry = self._data.get(k)
            if entry is None or entry[2] != expiry:
                continue  # stale heap entry: key deleted or re-written
            obj, _, _ = self._data.pop(k)
            self._index_del(k)
            self._emit(self._bump(), watchpkg.DELETED, k, obj, obj)

    # ------------------------------------------------------------ writes

    def create(self, key: str, obj: Any, ttl: Optional[float] = None) -> Any:
        # every write verb shares this shape: ledger phase under the
        # lock, then the publish drain in the finally — which also
        # flushes expiry events _gc_expired queued even when the verb
        # itself raises before committing anything
        try:
            with self._lock:
                self._gc_expired()
                if key in self._data:
                    raise AlreadyExists(kind=key.split("/")[2] if key.count("/") >= 2 else "",
                                        name=key.rsplit("/", 1)[-1])
                rev = self._bump()
                obj = _with_rv(obj, rev)
                expiry = self._clock.now() + ttl if ttl else None
                self._data[key] = (obj, rev, expiry)
                self._index_add(key)
                if expiry is not None:
                    heapq.heappush(self._expiry_heap, (expiry, key))
                    self._ttl_segs.add(self._seg(key))
                self._emit(rev, watchpkg.ADDED, key, obj, None)
                self._wal_sync()
                if self._publish_inline:
                    self._drain_publish()
                return obj
        finally:
            self._drain_publish()

    def create_batch(self, entries: List[Tuple[str, Any, Optional[float]]],
                     owned_meta: bool = False) -> List[Any]:
        """Create many keys under ONE lock acquisition with one watch
        fan-out flush — the write-side analogue of batch() (the 30k-pod
        create storm was paying one lock + one per-watcher send per pod;
        ref: GuaranteedUpdate batching rationale, etcd_helper.go:449).
        All-or-nothing: any pre-existing key fails the whole batch
        before anything commits, so callers can retry object-by-object
        to surface the precise conflict.

        owned_meta=True: the caller guarantees every object AND its
        .metadata were freshly allocated for this call and no other
        reference sees them (the registry's _prepare_create contract) —
        the revision is then stamped in place instead of through two
        clone passes per object, which is most of what the create storm
        used to do under the store lock (PROFILE_e2e.md)."""
        tr = obs.tracer()
        t0 = tr.clock.monotonic() if tr.enabled else 0.0
        t1 = None
        try:
            with self._lock:
                self._gc_expired()
                now = self._clock.now()
                seen = set()
                for key, _obj, _ttl in entries:
                    if key in self._data or key in seen:
                        raise AlreadyExists(
                            kind=key.split("/")[2] if key.count("/") >= 2 else "",
                            name=key.rsplit("/", 1)[-1])
                    seen.add(key)
                out = []
                batch_events: List[Tuple[int, str, watchpkg.Event, Any]] = []
                for key, obj, ttl in entries:
                    rev = self._bump()
                    if owned_meta:
                        obj.metadata.resource_version = str(rev)
                    else:
                        obj = _with_rv(obj, rev)
                    expiry = now + ttl if ttl else None
                    self._data[key] = (obj, rev, expiry)
                    self._index_add(key)
                    if expiry is not None:
                        heapq.heappush(self._expiry_heap, (expiry, key))
                        self._ttl_segs.add(self._seg(key))
                    batch_events.append(
                        (rev, key,
                         self._record(rev, watchpkg.ADDED, key, obj, None),
                         None))
                    out.append(obj)
                self._stage_publish(batch_events)
                self._wal_sync()
                if self._publish_inline:
                    self._drain_publish()
            if tr.enabled:
                t1 = tr.clock.monotonic()
        finally:
            self._drain_publish()
            if t1 is not None:
                ctx = obs.current()
                t2 = tr.clock.monotonic()
                tr.record("store.create_batch.ledger", t0, t1, parent=ctx,
                          attrs={"ops": len(out)})
                tr.record("store.create_batch.publish", t1, t2, parent=ctx,
                          stage="publish", attrs={"ops": len(out)})
        return out

    def set(self, key: str, obj: Any, ttl: Optional[float] = None) -> Any:
        """Unconditional write (ref: etcd_helper Set)."""
        try:
            with self._lock:
                self._gc_expired()
                rev = self._bump()
                obj = _with_rv(obj, rev)
                expiry = self._clock.now() + ttl if ttl else None
                prev = self._data.get(key)
                self._data[key] = (obj, rev, expiry)
                if prev is None:
                    self._index_add(key)
                if expiry is not None:
                    heapq.heappush(self._expiry_heap, (expiry, key))
                    self._ttl_segs.add(self._seg(key))
                etype = watchpkg.MODIFIED if prev else watchpkg.ADDED
                self._emit(rev, etype, key, obj, prev[0] if prev else None)
                self._wal_sync()
                if self._publish_inline:
                    self._drain_publish()
                return obj
        finally:
            self._drain_publish()

    def update(self, key: str, obj: Any) -> Any:
        """Conditional write: obj.metadata.resource_version must match the
        stored revision (the optimistic-concurrency check every PUT gets,
        ref: pkg/registry/generic/etcd/etcd.go:270-316)."""
        try:
            with self._lock:
                self._gc_expired()
                entry = self._data.get(key)
                if entry is None:
                    raise NotFound(name=key)
                stored, mod_rev, expiry = entry
                rv = obj.metadata.resource_version
                if rv and int(rv) != mod_rev:
                    raise Conflict(
                        f"operation on {key} failed: object was modified "
                        f"(have {rv}, current {mod_rev})")
                rev = self._bump()
                obj = _with_rv(obj, rev)
                self._data[key] = (obj, rev, expiry)
                self._emit(rev, watchpkg.MODIFIED, key, obj, stored)
                self._wal_sync()
                if self._publish_inline:
                    self._drain_publish()
                return obj
        finally:
            self._drain_publish()

    def guaranteed_update(self, key: str, fn: Callable[[Any], Any],
                          retries: int = 10,
                          ttl: Optional[float] = None) -> Any:
        """Read-modify-write loop with CAS semantics
        (ref: etcd_helper.go:449). `fn` receives the current object and
        returns the new one (never mutate the input). In-process the lock
        makes one pass sufficient, but the retry structure is kept so `fn`
        may be called outside the lock in future remote-store backends.
        ttl, when given, REFRESHES the entry's expiry (the rv-less PUT
        path for TTL'd resources extends the deadline on every write,
        matching the old get+set behavior)."""
        try:
            for _ in range(retries):
                with self._lock:
                    self._gc_expired()
                    entry = self._data.get(key)
                    if entry is None:
                        raise NotFound(name=key)
                    stored, mod_rev, expiry = entry
                    new_obj = fn(stored)
                    if self._data.get(key, (None, -1, None))[1] != mod_rev:
                        continue  # concurrent write between read and write
                    rev = self._bump()
                    new_obj = _with_rv(new_obj, rev)
                    if ttl is not None:
                        expiry = self._clock.now() + ttl
                        heapq.heappush(self._expiry_heap, (expiry, key))
                        self._ttl_segs.add(self._seg(key))
                    self._data[key] = (new_obj, rev, expiry)
                    self._emit(rev, watchpkg.MODIFIED, key, new_obj, stored)
                    self._wal_sync()
                    if self._publish_inline:
                        self._drain_publish()
                    return new_obj
            raise Conflict(f"guaranteed_update on {key}: too many retries")
        finally:
            self._drain_publish()

    def delete(self, key: str, expect_rv: Optional[str] = None) -> Any:
        try:
            with self._lock:
                self._gc_expired()
                entry = self._data.get(key)
                if entry is None:
                    raise NotFound(name=key)
                stored, mod_rev, _ = entry
                if expect_rv and int(expect_rv) != mod_rev:
                    raise Conflict(f"delete {key}: revision mismatch")
                del self._data[key]
                self._index_del(key)
                rev = self._bump()
                self._emit(rev, watchpkg.DELETED, key, stored, stored)
                self._wal_sync()
                if self._publish_inline:
                    self._drain_publish()
                return stored
        finally:
            self._drain_publish()

    def batch(self, ops: Iterable[Tuple[str, Callable[[Any], Any]]]) -> List[Any]:
        """Apply many guaranteed-updates under ONE lock acquisition with one
        revision bump per object. This is the binding-commit fast path the
        north star needs (30k CAS writes in <1s; see SURVEY.md section 7 hard
        part 2): same per-key conflict semantics as guaranteed_update, but the
        scheduler commits a whole tile of bindings per call.

        The body is deliberately flat: every per-op attribute lookup is
        hoisted and the history/list-cache bookkeeping runs batched
        (one segment invalidation, direct deque appends) — at 30k ops
        per drain this loop IS the host-side commit cost
        (PROFILE_e2e.md's bind/status whales)."""
        out = []
        tr = obs.tracer()
        t0 = tr.clock.monotonic() if tr.enabled else 0.0
        t1 = None
        try:
            with self._lock:
                self._gc_expired()
                # Two-phase: run every update function first, then commit.
                # A mid-batch failure therefore commits nothing
                # (all-or-nothing), so the scheduler always knows whether
                # a tile of bindings is durable.
                # Revisions are pre-assigned during staging (we hold the
                # lock, so rev0+1..rev0+n are ours): an update fn marked
                # `wants_rv` receives the final resourceVersion and builds
                # the stamped object in ONE construction pass instead of
                # fn's clone + a second _with_rv clone — the 30k-binding
                # tile pays 4 object clones per pod otherwise.
                rev = self._rev
                staged = []
                stage = staged.append
                data_get = self._data.get
                for key, fn in ops:
                    entry = data_get(key)
                    if entry is None:
                        raise NotFound(name=key)
                    stored, _mod_rev, expiry = entry
                    rev += 1
                    if getattr(fn, "wants_rv", False):
                        new_obj = fn(stored, str(rev))
                    else:
                        new_obj = _with_rv(fn(stored), rev)
                    stage((key, new_obj, stored, expiry, rev))
                batch_events: List[Tuple[int, str, watchpkg.Event,
                                         Any]] = []
                ev_append = batch_events.append
                out_append = out.append
                data = self._data
                hist = self._history
                hist_append = hist.append
                hist_max = hist.maxlen
                seg_of = self._seg
                seg_writes = self._seg_writes
                seg_writes_get = seg_writes.get
                modified = watchpkg.MODIFIED
                event = watchpkg.Event
                for key, new_obj, stored, expiry, rev in staged:
                    data[key] = (new_obj, rev, expiry)
                    # per-RECORD write token (not per batch): WAL replay
                    # rebuilds these counters one record at a time, and
                    # the recovered token must equal the live one
                    seg = seg_of(key)
                    seg_writes[seg] = seg_writes_get(seg, 0) + 1
                    if len(hist) == hist_max:
                        self._oldest_rev = hist[0][0]
                    hist_append((rev, modified, key, new_obj, stored))
                    ev_append((rev, key, event(modified, new_obj), stored))
                    out_append(new_obj)
                if staged:
                    self._rev = staged[-1][4]
                    if self._list_cache:
                        # all batch events are MODIFIED: patch snapshots
                        # in place (key set and sort order unchanged)
                        for key, new_obj, _stored, _exp, _rev in staged:
                            self._patch_lists(key, new_obj)
                    if self._wal is not None:
                        # outside the hot loop: the common case has no
                        # WAL, and with one the encode pass batches
                        enc = self._wal_scheme.encode_dict
                        for key, new_obj, _stored, expiry, rev in staged:
                            self._wal.append(rev, modified, key, expiry,
                                             enc(new_obj))
                # one send per watcher for the whole tile, not per
                # object — and the whole fan-out runs AFTER this lock
                # releases (the fan-out was ~half the measured in-lock
                # binding commit cost)
                self._stage_publish(batch_events)
                self._wal_sync()
                if self._publish_inline:
                    self._drain_publish()
            if tr.enabled:
                t1 = tr.clock.monotonic()
        finally:
            self._drain_publish()
            if t1 is not None:
                ctx = obs.current()
                t2 = tr.clock.monotonic()
                tr.record("store.batch.ledger", t0, t1, parent=ctx,
                          attrs={"ops": len(out)})
                tr.record("store.batch.publish", t1, t2, parent=ctx,
                          stage="publish", attrs={"ops": len(out)})
        return out

    def commit_txn(self, ops: Iterable[Tuple[str, Callable[[Any], Any]]]
                   ) -> List[Any]:
        """Multi-key ledger TRANSACTION: apply a whole bind/status tile
        in ONE revision window — one ledger-lock acquisition covering
        one pre-assigned _bump range, ONE WAL frame (a TXN record whose
        per-frame CRC makes a torn tail truncate the whole txn
        atomically in recover()), and one ordered publish batch through
        _pub_queue, so _published_rev jumps the entire window at once
        and a mid-txn watch() registration replays up to the previous
        batch and takes this one live — exactly-once either way.

        Same op interface and all-or-nothing NotFound/Conflict
        semantics as batch(); the difference is the caller no longer
        chunks (the per-1024-op batch() loops in the binder and the
        status pump were paying a lock acquisition, a WAL commit and a
        publish handoff per chunk — PROFILE_e2e.md round-6's 70%
        in-lock binder). batch() is kept verbatim as the A/B control
        arm (bench.py --txn-ab)."""
        out = []
        tr = obs.tracer()
        t0 = tr.clock.monotonic() if tr.enabled else 0.0
        t1 = None
        try:
            with self._lock:
                self._gc_expired()
                # staging phase: identical to batch() — run every
                # update fn first against pre-assigned revisions, so a
                # mid-txn failure commits nothing
                rev = self._rev
                staged = []
                stage = staged.append
                data_get = self._data.get
                for key, fn in ops:
                    entry = data_get(key)
                    if entry is None:
                        raise NotFound(name=key)
                    stored, _mod_rev, expiry = entry
                    rev += 1
                    if getattr(fn, "wants_rv", False):
                        new_obj = fn(stored, str(rev))
                    else:
                        new_obj = _with_rv(fn(stored), rev)
                    stage((key, new_obj, stored, expiry, rev))
                batch_events: List[Tuple[int, str, watchpkg.Event,
                                         Any]] = []
                ev_append = batch_events.append
                out_append = out.append
                data = self._data
                hist = self._history
                hist_append = hist.append
                hist_max = hist.maxlen
                seg_of = self._seg
                seg_writes = self._seg_writes
                seg_writes_get = seg_writes.get
                modified = watchpkg.MODIFIED
                event = watchpkg.Event
                for key, new_obj, stored, expiry, rev in staged:
                    data[key] = (new_obj, rev, expiry)
                    seg = seg_of(key)
                    seg_writes[seg] = seg_writes_get(seg, 0) + 1
                    if len(hist) == hist_max:
                        self._oldest_rev = hist[0][0]
                    hist_append((rev, modified, key, new_obj, stored))
                    ev_append((rev, key, event(modified, new_obj), stored))
                    out_append(new_obj)
                if staged:
                    self._rev = staged[-1][4]
                    if self._list_cache:
                        for key, new_obj, _stored, _exp, _rev in staged:
                            self._patch_lists(key, new_obj)
                    if self._wal is not None:
                        # the one framing difference from batch(): the
                        # whole window is ONE TXN frame — one CRC unit,
                        # torn-tail truncation is all-or-nothing
                        enc = self._wal_scheme.encode_dict
                        self._wal.append_txn(
                            [[rev, modified, key, expiry, enc(new_obj)]
                             for key, new_obj, _stored, expiry, rev
                             in staged])
                self._stage_publish(batch_events)
                self._wal_sync()
                if self._publish_inline:
                    self._drain_publish()
            if tr.enabled:
                t1 = tr.clock.monotonic()
        finally:
            self._drain_publish()
            if t1 is not None:
                # span bookkeeping stays outside self._lock (the
                # lock-witness lint); under publish_inline the fan-out
                # ran inside the window, so the ledger span absorbs it
                ctx = obs.current()
                t2 = tr.clock.monotonic()
                tr.record("store.txn.ledger", t0, t1, parent=ctx,
                          attrs={"ops": len(out)})
                tr.record("store.txn.publish", t1, t2, parent=ctx,
                          stage="publish", attrs={"ops": len(out)})
        return out

    # ------------------------------------------------------------- reads

    def get(self, key: str) -> Any:
        # Lock-free point read: _data maps keys to IMMUTABLE tuples that
        # writers swap atomically under the GIL, so a dict .get observes
        # either the pre- or post-commit entry — both valid snapshots —
        # and never a torn one. GETs therefore no longer queue behind a
        # committer's ledger window (the DENSITY.json GET-/nodes p99
        # whale was reads parked on this lock during the create storm).
        entry = self._data.get(key)
        if entry is None:
            raise NotFound(name=key)
        if self._expired(entry, self._clock.now()):
            # first-class expiry: the key's death is COMMITTED to the
            # ledger (revision, DELETED event, WAL record) the moment a
            # reader observes it, not deferred to the next write — so
            # revision history, watch streams, and recovery agree on
            # when it died. Only actually-expired reads pay the lock.
            self._reap_expired()
            raise NotFound(name=key)
        return entry[0]

    def _reap_expired(self) -> None:
        """Commit pending TTL expiries from a read path: ledger phase
        under the lock, publish drain after release, WAL flush — the
        same shape as every write verb."""
        try:
            with self._lock:
                self._gc_expired()
                self._wal_sync()
        finally:
            self._drain_publish()

    def list(self, prefix: str,
             predicate: Optional[Callable[[Any], bool]] = None
             ) -> Tuple[List[Any], int]:
        """All live objects under prefix, with the store revision at read
        time (the List + resourceVersion pair reflectors rely on,
        ref: pkg/client/cache/reflector.go:225). Selector-free lists of
        resource-or-deeper prefixes serve from the snapshot cache; a
        hit is consistent at the CURRENT revision because any write
        under the prefix would have invalidated it (_record).

        Pending TTL expiries are committed first (first-class expiry:
        ledger, watch streams, and WAL record a key's death when a
        reader observes it, not at the next unrelated write); the
        lock-free heap peek keeps the no-TTL hot path unchanged."""
        heap = self._expiry_heap
        if heap and heap[0][0] <= self._clock.now():
            self._reap_expired()
        with self._lock:
            cacheable = (predicate is None and prefix.count("/") >= 3
                         and self._seg(prefix) not in self._ttl_segs)
            if cacheable:
                cached = self._list_cache.get(prefix)
                if cached is not None:
                    # copy: callers filter/mutate their result lists
                    return list(cached), self._rev
            now = self._clock.now()
            # iterate only the prefix's resource segment (the key
            # index): a nodes LIST must not walk 150k pod keys. The
            # index is sound only for resource-or-deeper /registry/
            # prefixes (every matching key then shares the prefix's
            # segment); coarser or foreign prefixes take the full scan.
            seg = self._seg(prefix)
            if prefix.startswith("/registry/") and prefix.count("/") >= 3:
                bucket = self._seg_keys.get(seg) or ()
                keys: Iterable[str] = (
                    bucket if prefix == seg
                    else [k for k in bucket if k.startswith(prefix)])
            else:
                keys = [k for k in self._data if k.startswith(prefix)]
            data = self._data
            if cacheable:
                # (key, obj) pairs survive the sort so the snapshot's
                # key->position index can be built for in-place
                # MODIFIED patching; uncacheable paths (predicates,
                # coarse prefixes, TTL segs) skip the pair overhead
                pairs = []
                for k in keys:
                    e = data[k]
                    if not self._expired(e, now):
                        pairs.append((k, e[0]))
                pairs.sort(key=lambda ko: (ko[1].metadata.namespace,
                                           ko[1].metadata.name))
                items = [o for _k, o in pairs]
                if len(self._list_cache) >= 64:
                    self._list_cache.clear()
                    self._list_cache_seg.clear()
                    self._list_cache_idx.clear()
                self._list_cache[prefix] = items
                self._list_cache_idx[prefix] = {
                    k: i for i, (k, _o) in enumerate(pairs)}
                self._list_cache_seg.setdefault(self._seg(prefix),
                                                set()).add(prefix)
                return list(items), self._rev
            items = []
            for k in keys:
                e = data[k]
                if not self._expired(e, now):
                    items.append(e[0])
            if predicate is not None:
                items = [o for o in items if predicate(o)]
            items.sort(key=lambda o: (o.metadata.namespace,
                                      o.metadata.name))
            return items, self._rev

    # ------------------------------------------------------------- watch

    def watch(self, prefix: str, since_rev: Optional[int] = None,
              capacity: int = 100_000,
              predicate: Optional[Callable[[Any], bool]] = None,
              shard: Optional["FanoutShard"] = None
              ) -> watchpkg.Watcher:
        """Stream events for keys under prefix with rev > since_rev.

        since_rev=None means "from now" (no replay). Any integer — including
        0, the revision an empty store reports to list() — replays from the
        watch window, so the list-then-watch sequence is race-free from the
        very first write. If the window no longer covers since_rev, Expired
        is raised and the client must re-list (ref: cacher.go 'too old
        resource version').

        predicate: server-side selector filter (the apiserver filters
        watches before they reach the wire; filtering here keeps
        non-matching events out of the watcher queue entirely). Events
        are mapped through the reference's filtered-watch transition
        semantics — see _filtered_event.

        shard: a FanoutShard from attach_fanout_shard() — the watcher
        joins that worker's partition and its events arrive on the
        worker's pump thread. None = the default committer-drained
        shard (every pre-existing caller).

        Mid-flight registration (commits in their publish phase): under
        the shard's lock its publisher is quiescent and its
        published_rev frozen. History is replayed only up to that
        published_rev; anything already committed to the ledger but not
        yet fanned out is delivered by the shard's drain, because this
        watcher registers (with floor = max(since_rev, published_rev))
        before the shard lock is released. Exactly-once across the
        replay->live handoff, in revision order — per shard.
        """
        try:
            return self._watch_register(prefix, since_rev, capacity,
                                        predicate, shard)
        finally:
            # batches committed while registration held the default
            # shard's lock skipped their drain (non-blocking acquire):
            # flush them even when registration raises Expired
            self._drain_publish()

    def _watch_register(self, prefix: str, since_rev: Optional[int],
                        capacity: int,
                        predicate: Optional[Callable[[Any], bool]],
                        shard: Optional["FanoutShard"] = None
                        ) -> watchpkg.Watcher:
        lock = self._pub_lock if shard is None else shard.lock
        with lock:
            with self._lock:
                replay = []
                if since_rev is None:
                    # "from now": everything already committed — even if
                    # its publish is still queued — predates this watch
                    floor = self._rev
                else:
                    if since_rev < self._oldest_rev:
                        raise Expired(
                            f"resourceVersion {since_rev} is too old "
                            f"(oldest available {self._oldest_rev})")
                    published = (self._published_rev if shard is None
                                 else shard.published_rev)
                    floor = max(since_rev, published)
                    for rev, etype, key, obj, prev in self._history:
                        if rev <= since_rev or rev > published \
                                or not key.startswith(prefix):
                            continue
                        ev = watchpkg.Event(etype, obj)
                        if predicate is not None:
                            ev = self._filtered_event(ev, prev, predicate)
                            if ev is None:
                                continue
                        replay.append(ev)
            # Size the queue to hold the whole replay: a blocking send
            # here would deadlock the store (no consumer can run until
            # we return). One send_many = one queue slot for the whole
            # replay (send_many admits an oversized batch into an empty
            # watcher).
            w = watchpkg.Watcher(max(capacity, len(replay) + 16))
            if replay:
                w.send_many(replay, owned=True)
            (self._watchers if shard is None
             else shard.watchers).append((prefix, predicate, w, floor))
        return w

    def attach_fanout_shard(self, name: str = "") -> FanoutShard:
        """Create a worker delivery shard over the publish ring. Its
        cursor starts at the ring's END and its published_rev at the
        ledger head — both snapshotted under the ledger lock, so a
        watcher registering on the fresh shard replays history up to
        exactly the point live delivery takes over (pending ring
        entries it skips are inside its replay window). Caller starts
        the pump (shard.start()) and must stop() it on teardown."""
        with self._shards_lock:
            sh = FanoutShard(self, name or f"shard-{len(self._shards)}")
            with self._lock:
                sh.cursor = self._pub_seq
                sh.published_rev = self._rev
                # copy-on-write: _stage_publish iterates lock-free
                self._shards = self._shards + [sh]
        return sh

    def detach_fanout_shard(self, shard: "FanoutShard") -> None:
        """Remove a shard from ring retention (idempotent; called by
        FanoutShard.stop)."""
        with self._shards_lock:
            self._shards = [s for s in self._shards if s is not shard]
        shard.detached = True
        self._ring_trim()

    def fanout_shards(self) -> List["FanoutShard"]:
        return list(self._shards)

    def drain_overlap(self) -> dict:
        """The multi-consumer concurrency witness (see _DrainOverlap)."""
        return self._drain_overlap.snapshot()

    def watcher_count(self) -> int:
        with self._pub_lock:
            self._watchers = [e for e in self._watchers
                              if not e[2].stopped]
            n = len(self._watchers)
        self._drain_publish()  # flush batches parked while we held the lock
        for sh in self._shards:
            with sh.lock:
                sh.watchers = [e for e in sh.watchers
                               if not e[2].stopped]
                n += len(sh.watchers)
        return n

    # -------------------------------------------------------- durability

    def wal_close(self) -> None:
        """Flush and close the WAL (clean shutdown). A crashed process
        never calls this — recovery handles the torn tail."""
        if self._wal is not None:
            with self._lock:
                self._wal.close()

    @classmethod
    def recover(cls, wal_dir: str, window: int = 100_000,
                publish_inline: bool = False,
                fsync_policy: str = "batch",
                wal_segment_records: int = 10_000,
                wal_snapshot_records: int = 50_000) -> "Store":
        """Rebuild a Store from its WAL directory: newest snapshot,
        then the record tail, applied in strict revision order — the
        pre-crash ledger prefix, bit-identically: same revision
        counter, same live entries (insertion order preserved through
        the snapshot), same history tail, same per-segment write
        tokens and key index. Expired keys are not resurrected: every
        record carries its absolute expiry, and expiries the old
        process committed are first-class DELETED records. A torn
        final record is truncated, not fatal (core/wal.py).

        The returned store has the WAL re-attached and keeps
        journaling; `recovery_stats` records what the replay did.
        """
        import time as _time
        from ..utils.metrics import global_metrics
        from .scheme import default_scheme
        from .wal import WalWriter, read_wal

        t0 = _time.monotonic()
        snap, records = read_wal(wal_dir)
        st = cls(window=window, publish_inline=publish_inline)
        decode = default_scheme.decode_dict
        if snap is not None:
            st._rev = snap["rev"]
            # revisions at or below the snapshot are no longer
            # replayable from history (same meaning as a rolled window)
            st._oldest_rev = snap["rev"]
            st._seg_writes = {k: int(v)
                              for k, v in snap["seg_writes"].items()}
            st._ttl_segs = set(snap["ttl_segs"])
            for key, mod_rev, expiry, wire in snap["entries"]:
                obj = decode(wire)
                st._data[key] = (obj, int(mod_rev), expiry)
                st._index_add(key)
                if expiry is not None:
                    heapq.heappush(st._expiry_heap, (expiry, key))
        hist = st._history
        for rev, etype, key, expiry, wire in records:
            obj = decode(wire)
            prev_entry = st._data.get(key)
            if etype == watchpkg.DELETED:
                # the record's object IS the pre-delete stored object;
                # the live _record path emits (obj=stored, prev=stored)
                if prev_entry is not None:
                    del st._data[key]
                    st._index_del(key)
                prev = obj
            else:
                st._data[key] = (obj, rev, expiry)
                st._index_add(key)
                if expiry is not None:
                    heapq.heappush(st._expiry_heap, (expiry, key))
                    st._ttl_segs.add(st._seg(key))
                prev = prev_entry[0] if prev_entry is not None else None
            seg = st._seg(key)
            st._seg_writes[seg] = st._seg_writes.get(seg, 0) + 1
            if len(hist) == hist.maxlen:
                st._oldest_rev = hist[0][0]
            hist.append((rev, etype, key, obj, prev))
            st._rev = rev
        st._published_rev = st._rev  # nothing is pending fan-out
        w = WalWriter(wal_dir, fsync_policy=fsync_policy,
                      segment_records=wal_segment_records,
                      snapshot_records=wal_snapshot_records)
        w._since_snapshot = len(records)  # resume the compaction cadence
        st._wal = w
        st._wal_scheme = default_scheme
        global_metrics.inc("wal_recoveries_total")
        st.recovery_stats = {
            "snapshot_rev": snap["rev"] if snap is not None else 0,
            "replayed_records": len(records),
            "recovered_revision": st._rev,
            "seconds": round(_time.monotonic() - t0, 6),
        }
        return st
