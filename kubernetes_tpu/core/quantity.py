"""Resource quantities.

The reference models resource amounts as `resource.Quantity` strings
("100m" CPU, "32Gi" memory) and the scheduler immediately reduces them to
integer milli-CPU and bytes (reference: plugin/pkg/scheduler/algorithm/
predicates/predicates.go:140-146 getResourceRequest, pkg/api/resource).
We normalise at parse time: a Quantity is an exact integer in a canonical
unit (milliunits for CPU-like values, plain units for everything else),
remembering the original string for round-tripping.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from fractions import Fraction

_BIN_SUFFIX = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_QTY_RE = re.compile(r"^([+-]?[0-9]+(?:\.[0-9]+)?)(Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE]?)$")


@dataclass(frozen=True, eq=False)
class Quantity:
    """An exact resource amount.

    `milli` is the value in thousandths (so "100m" -> 100, "2" -> 2000);
    `value` rounds up to whole units the way the reference's Quantity.Value()
    does (ceil), which predicates use for memory/pod counts.

    Equality/hash are by `milli` only — `text` is presentational, so
    "1000m" == "1" and arithmetic-derived quantities compare equal to
    parsed ones (controllers rely on old == new to suppress writes).
    """

    milli: int
    text: str = ""

    def __eq__(self, other: object) -> bool:
        if isinstance(other, Quantity):
            return self.milli == other.milli
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self.milli)

    @property
    def value(self) -> int:
        # ceil division, matching resource.Quantity.Value() rounding up.
        return -((-self.milli) // 1000)

    def __str__(self) -> str:
        return self.text or format_quantity(self)

    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.milli + other.milli)

    def __bool__(self) -> bool:
        return self.milli != 0


def parse_quantity(s) -> Quantity:
    if isinstance(s, Quantity):
        return s
    if isinstance(s, (int, float)):
        return Quantity(int(round(float(s) * 1000)), str(s))
    s = s.strip()
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity {s!r}")
    num, suffix = m.groups()
    # Exact integer arithmetic via Fraction (floats corrupt values >= 2^53,
    # e.g. large byte counts with Ei suffixes).
    if suffix in _BIN_SUFFIX:
        factor = Fraction(_BIN_SUFFIX[suffix])
    else:
        factor = Fraction(10) ** {"n": -9, "u": -6, "m": -3, "": 0, "k": 3,
                                  "M": 6, "G": 9, "T": 12, "P": 15, "E": 18}[suffix]
    milli = int(Fraction(num) * factor * 1000)
    return Quantity(milli, s)


def format_quantity(q: Quantity) -> str:
    if q.milli % 1000 == 0:
        return str(q.milli // 1000)
    return f"{q.milli}m"
