"""Output printers: human tables, json, yaml-ish, name, jsonpath-lite.

Reference: pkg/kubectl/resource_printer.go — HumanReadablePrinter column
sets per kind, JSONPath/template printers, `-o name`.
"""

from __future__ import annotations

import json
from datetime import datetime, timezone
from typing import Any, Callable, Dict, List

from ..core import types as api


def translate_timestamp(ts: str) -> str:
    """Humanized age (ref: resource_printer.go translateTimestamp)."""
    if not ts:
        return "<unknown>"
    try:
        then = datetime.fromisoformat(ts.replace("Z", "+00:00"))
    except ValueError:
        return "<unknown>"
    seconds = int((datetime.now(timezone.utc) - then).total_seconds())
    if seconds < 0:
        return "0s"
    if seconds < 90:
        return f"{seconds}s"
    minutes = seconds // 60
    if minutes < 90:
        return f"{minutes}m"
    hours = seconds // 3600
    if hours < 36:
        return f"{hours}h"
    return f"{seconds // 86400}d"


def _pod_row(p: api.Pod) -> List[str]:
    ready = sum(1 for s in p.status.container_statuses if s.ready)
    total = len(p.spec.containers)
    restarts = sum(s.restart_count for s in p.status.container_statuses)
    return [p.metadata.name, f"{ready}/{total}", p.status.phase or "Unknown",
            str(restarts), translate_timestamp(p.metadata.creation_timestamp)]


def _node_row(n: api.Node) -> List[str]:
    status = "Unknown"
    for cond in n.status.conditions:
        if cond.type == "Ready":
            status = "Ready" if cond.status == "True" else "NotReady"
    if n.spec.unschedulable:
        status += ",SchedulingDisabled"
    labels = ",".join(f"{k}={v}" for k, v in sorted(n.metadata.labels.items())) or "<none>"
    return [n.metadata.name, labels, status,
            translate_timestamp(n.metadata.creation_timestamp)]


def _svc_row(s: api.Service) -> List[str]:
    ports = ",".join(f"{p.port}/{p.protocol}" for p in s.spec.ports) or "<none>"
    selector = ",".join(f"{k}={v}" for k, v in sorted(s.spec.selector.items())) or "<none>"
    # EXTERNAL-IP: the LB ingress joined with explicit externalIPs
    # (ref: resource_printer.go getServiceExternalIP shows both for
    # LoadBalancer services)
    external = ",".join(list(s.status.load_balancer_ingress)
                        + list(s.spec.external_ips)) or "<none>"
    return [s.metadata.name, s.spec.cluster_ip or "<none>", external,
            ports, selector,
            translate_timestamp(s.metadata.creation_timestamp)]


def _rc_row(rc: api.ReplicationController) -> List[str]:
    tpl = rc.spec.template
    containers = ",".join(c.name for c in tpl.spec.containers) if tpl else ""
    images = ",".join(c.image for c in tpl.spec.containers) if tpl else ""
    selector = ",".join(f"{k}={v}" for k, v in sorted(rc.spec.selector.items()))
    return [rc.metadata.name, containers, images, selector,
            str(rc.spec.replicas),
            translate_timestamp(rc.metadata.creation_timestamp)]


def _event_row(e: api.Event) -> List[str]:
    obj = e.involved_object
    return [translate_timestamp(e.last_timestamp or e.first_timestamp),
            str(e.count), obj.kind, obj.name, e.type, e.reason, e.message]


def _deployment_row(d: api.Deployment) -> List[str]:
    return [d.metadata.name, str(d.spec.replicas),
            str(d.status.updated_replicas), str(d.status.replicas),
            translate_timestamp(d.metadata.creation_timestamp)]


def _job_row(j: api.Job) -> List[str]:
    completions = j.spec.completions if j.spec.completions is not None else "<none>"
    return [j.metadata.name, str(completions), str(j.status.succeeded),
            translate_timestamp(j.metadata.creation_timestamp)]


def _ns_row(ns: api.Namespace) -> List[str]:
    return [ns.metadata.name, ns.status.phase,
            translate_timestamp(ns.metadata.creation_timestamp)]


# kind -> (headers, row fn); layouts follow resource_printer.go's
# printPod/printNode/printService/printReplicationController/...
COLUMNS: Dict[str, Any] = {
    "Pod": (["NAME", "READY", "STATUS", "RESTARTS", "AGE"], _pod_row),
    "Node": (["NAME", "LABELS", "STATUS", "AGE"], _node_row),
    "Service": (["NAME", "CLUSTER_IP", "EXTERNAL_IP", "PORT(S)",
                 "SELECTOR", "AGE"], _svc_row),
    "ReplicationController": (
        ["CONTROLLER", "CONTAINER(S)", "IMAGE(S)", "SELECTOR", "REPLICAS",
         "AGE"], _rc_row),
    "Event": (["AGE", "COUNT", "KIND", "NAME", "TYPE", "REASON", "MESSAGE"],
              _event_row),
    "Deployment": (["NAME", "DESIRED", "UPDATED", "TOTAL", "AGE"],
                   _deployment_row),
    "Job": (["NAME", "COMPLETIONS", "SUCCESSFUL", "AGE"], _job_row),
    "Namespace": (["NAME", "STATUS", "AGE"], _ns_row),
    "ComponentStatus": (["NAME", "STATUS", "MESSAGE", "ERROR"],
                        lambda cs: [
                            cs.metadata.name,
                            ("Healthy" if cs.conditions
                             and cs.conditions[0].status == "True"
                             else "Unhealthy"),
                            cs.conditions[0].message if cs.conditions
                            else "",
                            cs.conditions[0].error if cs.conditions
                            else ""]),
}


def _generic_row(obj: Any) -> List[str]:
    return [obj.metadata.name,
            translate_timestamp(obj.metadata.creation_timestamp)]


def print_table(objs: List[Any], scheme, out,
                with_namespace=False, wide=False) -> None:
    """One table section per kind, kinds in first-seen order (kubectl
    prints `get pods,svc` as stacked per-kind tables)."""
    groups: Dict[str, List[Any]] = {}
    order: List[str] = []
    for obj in objs:
        kind = scheme.kind_for(obj)
        if kind not in groups:
            groups[kind] = []
            order.append(kind)
        groups[kind].append(obj)
    for i, kind in enumerate(order):
        if i:
            out.write("\n")
        _print_kind_table(kind, groups[kind], out, with_namespace, wide)


# -o wide extras per kind (resource_printer.go's wide columns)
WIDE_COLUMNS = {
    "Pod": (["IP", "NODE"],
            lambda p: [p.status.pod_ip or "<none>",
                       p.spec.node_name or "<none>"]),
    "Node": (["ADDRESSES", "VERSION"],
             lambda n: [",".join(a.address for a in n.status.addresses)
                        or "<none>",
                        n.status.node_info.kubelet_version or "<none>"]),
}


def _print_kind_table(kind: str, objs: List[Any], out,
                      with_namespace: bool, wide: bool = False) -> None:
    headers, fn = COLUMNS.get(kind, (["NAME", "AGE"], _generic_row))
    wide_headers, wide_fn = (WIDE_COLUMNS.get(kind, ([], None))
                             if wide else ([], None))
    headers = list(headers) + wide_headers
    if with_namespace:
        headers = ["NAMESPACE"] + headers
    rows = []
    for obj in objs:
        row = fn(obj)
        if wide_fn is not None:
            row = row + wide_fn(obj)
        if with_namespace:
            row = [obj.metadata.namespace] + row
        rows.append(row)
    emit_table(headers, rows, out)


def emit_table(headers: List[str], rows: List[List[str]], out) -> None:
    """The one aligned-columns renderer (kind tables and
    custom-columns both use it)."""
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows
              else len(h)
              for i, h in enumerate(headers)]
    out.write("   ".join(h.ljust(widths[i])
                         for i, h in enumerate(headers)).rstrip() + "\n")
    for r in rows:
        out.write("   ".join(v.ljust(widths[i])
                             for i, v in enumerate(r)).rstrip() + "\n")


def _to_yamlish(data: Any, indent: int = 0) -> str:
    """Minimal YAML emitter for JSON-able structures (no pyyaml dep)."""
    pad = "  " * indent
    if isinstance(data, dict):
        if not data:
            return pad + "{}"
        lines = []
        for k, v in data.items():
            if isinstance(v, (dict, list)) and v:
                lines.append(f"{pad}{k}:")
                lines.append(_to_yamlish(v, indent + 1))
            else:
                lines.append(f"{pad}{k}: {json.dumps(v)}")
        return "\n".join(lines)
    if isinstance(data, list):
        lines = []
        for v in data:
            if isinstance(v, (dict, list)) and v:
                body = _to_yamlish(v, indent + 1)
                first, _, rest = body.partition("\n")
                lines.append(f"{pad}- {first.strip()}")
                if rest:
                    lines.append(rest)
            else:
                lines.append(f"{pad}- {json.dumps(v)}")
        return "\n".join(lines)
    return pad + json.dumps(data)


def jsonpath_get(data: Any, path: str) -> Any:
    """jsonpath-lite: {.a.b[0].c} (ref: pkg/util/jsonpath, subset)."""
    expr = path.strip()
    if expr.startswith("{") and expr.endswith("}"):
        expr = expr[1:-1]
    cur = data
    for part in expr.lstrip(".").replace("]", "").split("."):
        if not part:
            continue
        name, _, idx = part.partition("[")
        if name:
            cur = cur[name] if isinstance(cur, dict) else None
        if idx != "":
            cur = cur[int(idx)] if isinstance(cur, list) else None
        if cur is None:
            return None
    return cur


def print_objects(objs: List[Any], output: str, scheme, out,
                  resource_names=None, with_namespace=False) -> None:
    """output: '' (table) | wide | json | yaml | name |
    jsonpath=<expr> | custom-columns=<spec>"""
    if output == "json":
        if len(objs) == 1:
            payload = scheme.encode_dict(objs[0])
        else:
            payload = {"kind": "List", "apiVersion": "v1",
                       "items": [scheme.encode_dict(o) for o in objs]}
        out.write(json.dumps(payload, indent=2) + "\n")
    elif output == "yaml":
        for i, obj in enumerate(objs):
            if i:
                out.write("---\n")
            out.write(_to_yamlish(scheme.encode_dict(obj)) + "\n")
    elif output == "name":
        for obj, rname in zip(objs, resource_names or
                              [""] * len(objs)):
            prefix = f"{rname}/" if rname else ""
            out.write(f"{prefix}{obj.metadata.name}\n")
    elif output.startswith("jsonpath="):
        expr = output[len("jsonpath="):]
        for obj in objs:
            value = jsonpath_get(scheme.encode_dict(obj), expr)
            out.write((json.dumps(value)
                       if isinstance(value, (dict, list))
                       else str(value)) + "\n")
    elif output.startswith("custom-columns="):
        print_custom_columns(objs, output[len("custom-columns="):],
                             scheme, out)
    else:
        print_table(objs, scheme, out, with_namespace=with_namespace,
                    wide=(output == "wide"))


def print_custom_columns(objs: List[Any], spec: str, scheme,
                         out) -> None:
    """-o custom-columns=NAME:.metadata.name,PHASE:.status.phase
    (ref: pkg/kubectl/custom_column_printer.go — header row, one
    jsonpath-addressed cell per column, '<none>' for misses)."""
    columns = []
    for part in spec.split(","):
        header, _, expr = part.partition(":")
        if not header or not expr:
            raise ValueError(
                f"custom-columns: bad column spec {part!r} "
                "(want HEADER:.json.path)")
        columns.append((header, expr))
    rows = []
    for obj in objs:
        data = scheme.encode_dict(obj)
        row = []
        for _header, expr in columns:
            try:
                value = jsonpath_get(data, expr)
            except (KeyError, IndexError, TypeError,
                    ValueError):
                value = None  # absent path -> <none>, not an error
            if value is None:
                row.append("<none>")  # custom_column_printer.go miss
            elif isinstance(value, (dict, list)):
                row.append(json.dumps(value))
            else:
                row.append(str(value))
        rows.append(row)
    emit_table([h for h, _ in columns], rows, out)
