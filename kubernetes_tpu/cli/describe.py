"""kubectl describe: per-kind detail blocks + related events.

Reference: pkg/kubectl/describe.go (PodDescriber, NodeDescriber, ...).
"""

from __future__ import annotations

from typing import Any, List

from ..core import types as api
from .printers import translate_timestamp


def _kv(out: List[str], key: str, value) -> None:
    out.append(f"{key}:\t{value}")


def _labels(labels) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items())) or "<none>"


def describe_pod(pod: api.Pod, events) -> str:
    out: List[str] = []
    _kv(out, "Name", pod.metadata.name)
    _kv(out, "Namespace", pod.metadata.namespace)
    _kv(out, "Node", pod.spec.node_name or "<none>")
    _kv(out, "Labels", _labels(pod.metadata.labels))
    _kv(out, "Status", pod.status.phase)
    _kv(out, "IP", pod.status.pod_ip or "<none>")
    out.append("Containers:")
    statuses = {cs.name: cs for cs in pod.status.container_statuses}
    for c in pod.spec.containers:
        out.append(f"  {c.name}:")
        out.append(f"    Image:\t{c.image}")
        req = c.resources.requests
        if req:
            out.append("    Requests:")
            for r, q in sorted(req.items()):
                out.append(f"      {r}:\t{q}")
        cs = statuses.get(c.name)
        if cs is not None:
            # the state block the reference describer prints, incl.
            # the termination message and exit code
            if cs.state.running is not None:
                out.append("    State:\tRunning")
                if cs.state.running.started_at:
                    out.append(f"      Started:\t"
                               f"{cs.state.running.started_at}")
            elif cs.state.terminated is not None:
                t = cs.state.terminated
                out.append("    State:\tTerminated")
                out.append(f"      Exit Code:\t{t.exit_code}")
                if t.started_at:
                    out.append(f"      Started:\t{t.started_at}")
                if t.finished_at:
                    out.append(f"      Finished:\t{t.finished_at}")
                if t.reason:
                    out.append(f"      Reason:\t{t.reason}")
                if t.message:
                    out.append(f"      Message:\t{t.message}")
            elif cs.state.waiting is not None:
                out.append("    State:\tWaiting")
                if cs.state.waiting.reason:
                    out.append(f"      Reason:\t"
                               f"{cs.state.waiting.reason}")
            out.append(f"    Ready:\t{cs.ready}")
            out.append(f"    Restart Count:\t{cs.restart_count}")
    _append_events(out, events)
    return "\n".join(out)


def describe_node(node: api.Node, pods, events) -> str:
    out: List[str] = []
    _kv(out, "Name", node.metadata.name)
    _kv(out, "Labels", _labels(node.metadata.labels))
    _kv(out, "Unschedulable", str(node.spec.unschedulable).lower())
    out.append("Conditions:")
    for cond in node.status.conditions:
        out.append(f"  {cond.type}\t{cond.status}\t{cond.reason}")
    out.append("Capacity:")
    for r, q in sorted(node.status.capacity.items()):
        out.append(f"  {r}:\t{q}")
    if node.status.allocatable:
        out.append("Allocatable:")
        for r, q in sorted(node.status.allocatable.items()):
            out.append(f"  {r}:\t{q}")
    if node.status.addresses:
        _kv(out, "Addresses", ",".join(
            a.address for a in node.status.addresses))
    if node.status.daemon_endpoints.kubelet_endpoint.port:
        _kv(out, "Kubelet Port",
            str(node.status.daemon_endpoints.kubelet_endpoint.port))
    out.append(f"Pods:\t({len(pods)} in total)")
    for p in pods:
        out.append(f"  {p.metadata.namespace}/{p.metadata.name}")
    _append_events(out, events)
    return "\n".join(out)


def describe_service(svc: api.Service, endpoints, events) -> str:
    out: List[str] = []
    _kv(out, "Name", svc.metadata.name)
    _kv(out, "Namespace", svc.metadata.namespace)
    _kv(out, "Selector", _labels(svc.spec.selector))
    _kv(out, "Type", svc.spec.type)
    _kv(out, "IP", svc.spec.cluster_ip or "<none>")
    for port in svc.spec.ports:
        _kv(out, "Port", f"{port.name or '<unset>'}\t{port.port}/{port.protocol}")
    if endpoints is not None:
        addrs = []
        for subset in endpoints.subsets:
            for addr in subset.addresses:
                for port in subset.ports:
                    addrs.append(f"{addr.ip}:{port.port}")
        _kv(out, "Endpoints", ",".join(addrs) or "<none>")
    _append_events(out, events)
    return "\n".join(out)


def describe_rc(rc: api.ReplicationController, pods, events) -> str:
    out: List[str] = []
    _kv(out, "Name", rc.metadata.name)
    _kv(out, "Namespace", rc.metadata.namespace)
    _kv(out, "Selector", _labels(rc.spec.selector))
    _kv(out, "Replicas",
        f"{rc.status.replicas} current / {rc.spec.replicas} desired")
    phases = {}
    for p in pods:
        phases[p.status.phase] = phases.get(p.status.phase, 0) + 1
    _kv(out, "Pods Status",
        " / ".join(f"{n} {phase}" for phase, n in sorted(phases.items()))
        or "<none>")
    _append_events(out, events)
    return "\n".join(out)


def describe_generic(obj: Any, scheme, events) -> str:
    out: List[str] = []
    _kv(out, "Name", obj.metadata.name)
    if obj.metadata.namespace:
        _kv(out, "Namespace", obj.metadata.namespace)
    _kv(out, "Labels", _labels(obj.metadata.labels))
    _kv(out, "Kind", scheme.kind_for(obj))
    _kv(out, "Created",
        translate_timestamp(obj.metadata.creation_timestamp) + " ago")
    _append_events(out, events)
    return "\n".join(out)


def _append_events(out: List[str], events) -> None:
    if not events:
        return
    out.append("Events:")
    out.append("  AGE\tCOUNT\tTYPE\tREASON\tMESSAGE")
    for e in events:
        out.append("  " + "\t".join([
            translate_timestamp(e.last_timestamp or e.first_timestamp),
            str(e.count), e.type, e.reason, e.message]))


def _events_for(client, namespace: str, kind: str, name: str):
    """Related events via a server-side involvedObject field selector
    (ref: pkg/client/unversioned/events.go GetFieldSelector/Search —
    kubectl describe filters events on the server, not by walking the
    whole namespace client-side). Events recorded without a kind on
    their reference still surface, as before."""
    evs = client.list("events", namespace,
                      field_selector=f"involvedObject.name={name}")[0]
    return [e for e in evs
            if not e.involved_object.kind or e.involved_object.kind == kind]


def describe(client, scheme, resource: str, name: str, namespace: str) -> str:
    from ..api.registry import Registry
    obj = client.get(resource, name, namespace)
    kind = Registry.info(resource).kind
    events = _events_for(client, namespace, kind, name) if namespace else []
    if resource == "pods":
        return describe_pod(obj, events)
    if resource == "nodes":
        pods = [p for p in client.list("pods", "")[0]
                if p.spec.node_name == name]
        node_events = _events_for(client, "default", "Node", name)
        return describe_node(obj, pods, node_events)
    if resource == "services":
        try:
            endpoints = client.get("endpoints", name, namespace)
        except Exception:
            endpoints = None
        return describe_service(obj, endpoints, events)
    if resource == "replicationcontrollers":
        from ..core.labels import selector_from_set
        sel = selector_from_set(obj.spec.selector)
        pods = [p for p in client.list("pods", namespace)[0]
                if sel.matches(p.metadata.labels)]
        return describe_rc(obj, pods, events)
    return describe_generic(obj, scheme, events)
