"""kubectl port-forward: a local TCP listener bridged to a pod port.

Reference: pkg/kubectl/cmd/portforward.go + pkg/client/unversioned/
portforward — there the local listener speaks SPDY to the apiserver,
which relays to the kubelet; here every leg is a websocket carrying raw
TCP bytes as binary frames (utils/wsstream, the documented transport
divergence). The client object decides the route: HttpClient goes
through the apiserver relay, InProcClient dials the kubelet directly.
"""

from __future__ import annotations

import socket
import sys
import threading
from typing import Optional

from ..utils import wsstream


class PortForwarder:
    """Serve local_port -> pod:remote_port until stop()."""

    def __init__(self, client, pod_name: str, namespace: str,
                 local_port: int, remote_port: int,
                 address: str = "127.0.0.1"):
        self.client = client
        self.pod_name = pod_name
        self.namespace = namespace
        self.remote_port = remote_port
        self._listener = socket.create_server((address, local_port))
        self.local_port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> "PortForwarder":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"port-forward-{self.local_port}")
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            ws = self.client.portforward_open(
                self.pod_name, self.namespace, self.remote_port)
        except Exception as e:
            # the reference kubectl logs each failed connection; silence
            # here would look like inexplicable instant disconnects
            print(f"port-forward {self.pod_name}:{self.remote_port}: {e}",
                  file=sys.stderr)
            conn.close()
            return
        try:
            # local TCP <-> websocket; we are the ws client, so frames
            # we send are masked
            wsstream.bridge(ws.recv, ws.sendall, conn, mask=True)
        finally:
            ws.close()
            conn.close()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._accept_thread:
            self._accept_thread.join(timeout=5)
