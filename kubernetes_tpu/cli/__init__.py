"""kubectl-style CLI.

Reference: pkg/kubectl + cmd/kubectl (NewKubectlCommand
pkg/kubectl/cmd/cmd.go:134). Commands: get, describe, create, apply,
delete, scale, label, annotate, logs, expose, rolling-update, autoscale,
run, version, api-versions, cluster-info — over the HTTP client, with
the reference's printer column layouts and resource-name aliases.
"""

from .cmd import main, build_parser

__all__ = ["main", "build_parser"]
