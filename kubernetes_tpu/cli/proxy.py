"""kubectl proxy — a local unauthenticated door to the apiserver.

Reference: pkg/kubectl/proxy.go + cmd/proxy.go: a local HTTP listener
forwards every request to the apiserver, attaching the client's
credentials, so local tools can speak plain HTTP to 127.0.0.1. Watches
stream through (the relay copies chunks as they arrive)."""

from __future__ import annotations

import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

# hop-by-hop headers must not be forwarded verbatim (RFC 7230 §6.1)
_HOP = {"connection", "keep-alive", "transfer-encoding", "upgrade",
        "proxy-authenticate", "proxy-authorization", "te", "trailers",
        "host", "content-length"}


class ApiProxy:
    def __init__(self, client, address: str = "127.0.0.1",
                 port: int = 8001):
        self.client = client
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _relay(self, method):
                proxy._relay(self, method)

            def do_GET(self):
                self._relay("GET")

            def do_POST(self):
                self._relay("POST")

            def do_PUT(self):
                self._relay("PUT")

            def do_DELETE(self):
                self._relay("DELETE")

            def do_PATCH(self):
                self._relay("PATCH")

        self.httpd = ThreadingHTTPServer((address, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _relay(self, h, method: str) -> None:
        url = self.client.base_url + h.path
        length = int(h.headers.get("Content-Length") or 0)
        body = h.rfile.read(length) if length else None
        headers = {k: v for k, v in h.headers.items()
                   if k.lower() not in _HOP}
        headers.update(self.client.headers)  # the credential role
        req = urllib.request.Request(url, data=body, headers=headers,
                                     method=method)
        try:
            resp = urllib.request.urlopen(
                req, timeout=None,
                context=getattr(self.client, "ssl_context", None))
        except urllib.error.HTTPError as e:
            resp = e  # relay the apiserver's status verbatim
        except (urllib.error.URLError, OSError) as e:
            h.send_response(502)
            msg = f"apiserver unreachable: {e}".encode()
            h.send_header("Content-Length", str(len(msg)))
            h.end_headers()
            h.wfile.write(msg)
            return
        try:
            status = getattr(resp, "status", getattr(resp, "code", 200))
            h.send_response(status)
            ctype = resp.headers.get("Content-Type", "application/json")
            h.send_header("Content-Type", ctype)
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()
            while True:
                piece = resp.read1(65536)
                if not piece:
                    break
                h.wfile.write(f"{len(piece):x}\r\n".encode())
                h.wfile.write(piece + b"\r\n")
                h.wfile.flush()
            h.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            h.close_connection = True
        finally:
            resp.close()

    def start(self) -> "ApiProxy":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: float) -> None:
        if self._thread:
            self._thread.join(timeout)

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
