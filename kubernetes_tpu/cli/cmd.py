"""The kubectl command tree.

Reference: pkg/kubectl/cmd/cmd.go:134 NewKubectlCommand and the
subcommand files under pkg/kubectl/cmd/ (get.go, create.go, delete.go,
describe.go, scale.go, label.go, annotate.go, expose.go, run.go,
rollingupdate.go, autoscale.go, logs.go, clusterinfo.go, version.go).
argparse plays cobra's role; `--server` plays kubeconfig.
"""

from __future__ import annotations

import argparse
import sys
import time
from dataclasses import replace
from typing import List, Optional

from ..api.client import HttpClient
from ..core import types as api
from ..core.errors import AlreadyExists, ApiError, NotFound
from ..core.scheme import default_scheme
from .describe import describe
from .printers import jsonpath_get, print_objects
from .resource import (load_manifest, parse_resource_args,
                       resource_for_object)

VERSION = "v1.1.0-tpu"  # capability parity line (pkg/version/base.go)
# the apply ownership record (ref: kubectl apply's annotation protocol)
LAST_APPLIED_ANNOTATION = "kubectl.kubernetes.io/last-applied-configuration"


def _parse_bool(v: str) -> bool:
    """strconv.ParseBool's accepted spellings; anything else errors
    (argparse surfaces the ValueError as a usage error)."""
    low = v.lower()
    if low in ("1", "t", "true"):
        return True
    if low in ("0", "f", "false"):
        return False
    raise ValueError(f"invalid boolean value {v!r}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubectl",
        description="controls the kubernetes_tpu cluster manager")
    p.add_argument("-s", "--server", default="")
    p.add_argument("--token", default="", help="bearer token")
    p.add_argument("--kubeconfig", default="",
                   help="path to a kubeconfig file (default: $KUBECONFIG "
                        "or ~/.kube/config)")
    p.add_argument("--context", default="",
                   help="kubeconfig context to use")
    p.add_argument("-n", "--namespace", default="")
    sub = p.add_subparsers(dest="command")

    g = sub.add_parser("get", help="display one or many resources")
    g.add_argument("args", nargs="+")
    g.add_argument("-o", "--output", default="")
    g.add_argument("-l", "--selector", default="")
    g.add_argument("--field-selector", dest="field_selector", default="")
    g.add_argument("--all-namespaces", action="store_true")
    g.add_argument("-w", "--watch", action="store_true")
    g.add_argument("--sort-by", dest="sort_by", default="",
                   help="jsonpath expression to sort the list by, "
                        "e.g. '{.metadata.name}'")

    d = sub.add_parser("describe", help="show details of a resource")
    d.add_argument("args", nargs="+")

    c = sub.add_parser("create", help="create resources from a file")
    c.add_argument("-f", "--filename", required=True)

    a = sub.add_parser("apply", help="create or update from a file")
    a.add_argument("-f", "--filename", required=True)

    rm = sub.add_parser("delete", help="delete resources")
    rm.add_argument("args", nargs="*", default=[])
    rm.add_argument("-f", "--filename", default="")
    rm.add_argument("-l", "--selector", default="")
    rm.add_argument("--all", action="store_true")
    # ref: pkg/kubectl/cmd/delete.go:98 — negative means "unset"
    # (pods then terminate with their own spec grace period)
    rm.add_argument("--grace-period", type=int, default=-1)
    # ref: delete.go:97 — cascade reaps managed pods first (stop.go
    # ReaperFor); --cascade=false deletes only the object itself.
    # Strict bool parse like strconv.ParseBool: a typo must error, not
    # silently cascade.
    rm.add_argument("--cascade", default=True, type=_parse_bool)

    sc = sub.add_parser("scale", help="set a new size for a controller")
    sc.add_argument("args", nargs="+")
    sc.add_argument("--replicas", type=int, required=True)
    sc.add_argument("--current-replicas", type=int, default=None)

    lb = sub.add_parser("label", help="update labels on a resource")
    lb.add_argument("args", nargs="+")
    lb.add_argument("--overwrite", action="store_true")

    an = sub.add_parser("annotate", help="update annotations on a resource")
    an.add_argument("args", nargs="+")
    an.add_argument("--overwrite", action="store_true")

    ex = sub.add_parser("expose", help="expose a controller as a service")
    ex.add_argument("args", nargs="+")
    ex.add_argument("--port", type=int, required=True)
    ex.add_argument("--target-port", type=int, default=None)
    ex.add_argument("--name", default="")
    ex.add_argument("--type", default="ClusterIP")

    rn = sub.add_parser("run", help="run an image as an RC")
    rn.add_argument("name")
    rn.add_argument("--image", required=True)
    rn.add_argument("-r", "--replicas", type=int, default=1)
    rn.add_argument("-l", "--labels", default="")

    ru = sub.add_parser("rolling-update",
                        help="gradually replace an RC's pods")
    ru.add_argument("old_name")
    ru.add_argument("new_name")
    ru.add_argument("--image", default="")
    ru.add_argument("-f", "--filename", default="")
    ru.add_argument("--update-period", type=float, default=0.0)

    au = sub.add_parser("autoscale", help="create an HPA for a controller")
    au.add_argument("args", nargs="+")
    au.add_argument("--min", type=int, default=1)
    au.add_argument("--max", type=int, required=True)
    au.add_argument("--cpu-percent", type=int, default=80)

    lg = sub.add_parser("logs", help="print container logs")
    lg.add_argument("pod")
    lg.add_argument("container", nargs="?", default="")
    lg.add_argument("-f", "--follow", action="store_true")
    lg.add_argument("-p", "--previous", action="store_true",
                    help="print the logs of the previous terminated "
                         "container instance")

    ex = sub.add_parser("exec", help="execute a command in a container")
    ex.add_argument("pod")
    ex.add_argument("-c", "--container", default="")
    ex.add_argument("-i", "--stdin", action="store_true",
                    help="stream this terminal's stdin to the command "
                         "(interactive exec over the websocket relay)")
    ex.add_argument("-t", "--tty", action="store_true",
                    help="accepted for kubectl parity (no pty is "
                         "allocated; output is the merged stream)")
    ex.add_argument("cmd", nargs="+",
                    help="command and args (use -- before flags)")

    rp = sub.add_parser("replace", help="replace a resource from a file")
    rp.add_argument("-f", "--filename", required=True)
    rp.add_argument("--force", action="store_true",
                    help="delete and re-create instead of updating")

    pt = sub.add_parser("patch",
                        help="update fields with a strategic merge patch")
    pt.add_argument("args", nargs=2, metavar=("TYPE", "NAME"))
    pt.add_argument("-p", "--patch", required=True,
                    help="the patch as a JSON object")

    st = sub.add_parser("stop",
                        help="gracefully shut down a resource "
                             "(scales controllers to 0 first)")
    st.add_argument("args", nargs="*")
    st.add_argument("-f", "--filename", default="")

    ed = sub.add_parser("edit", help="edit a resource in $EDITOR")
    ed.add_argument("args", nargs=2, metavar=("TYPE", "NAME"))

    xp = sub.add_parser("explain",
                        help="documentation for a resource's fields")
    xp.add_argument("path", help="RESOURCE[.field.path], e.g. "
                                 "pods.spec.containers")

    cv = sub.add_parser("convert",
                        help="normalize a manifest to the served version")
    cv.add_argument("-f", "--filename", required=True)

    px = sub.add_parser("proxy", help="run a local proxy to the apiserver")
    px.add_argument("--port", type=int, default=8001)
    px.add_argument("--address", default="127.0.0.1")

    nsd = sub.add_parser("namespace",
                         help="(deprecated) show or set the namespace")
    nsd.add_argument("name", nargs="?")

    cfg = sub.add_parser("config", help="modify kubeconfig files")
    cfg.add_argument("action",
                     choices=["view", "current-context", "use-context",
                              "set-cluster", "set-credentials",
                              "set-context", "get-contexts"])
    cfg.add_argument("name", nargs="?")
    cfg.add_argument("--server", default="")
    cfg.add_argument("--token", default="")
    cfg.add_argument("--username", default="")
    cfg.add_argument("--password", default="")
    cfg.add_argument("--cluster", default="")
    cfg.add_argument("--user", default="")
    cfg.add_argument("--context-namespace", default="",
                     help="namespace for set-context")
    cfg.add_argument("--raw", action="store_true",
                     help="view: print credentials instead of REDACTED")

    at = sub.add_parser("attach", help="attach to a running container")
    at.add_argument("pod")
    at.add_argument("-c", "--container", default="")
    at.add_argument("-i", "--stdin", action="store_true",
                    help="pass this terminal's stdin to the container")

    pf = sub.add_parser("port-forward",
                        help="forward a local port to a pod port")
    pf.add_argument("pod")
    pf.add_argument("mapping",
                    help="LOCAL:REMOTE (or PORT for same-port)")
    pf.add_argument("--address", default="127.0.0.1")

    sub.add_parser("version", help="print version")
    sub.add_parser("api-versions", help="print supported API versions")
    sub.add_parser("cluster-info", help="display cluster info")
    return p


def _split_kv(items: List[str], what: str):
    updates = {}
    removals = []
    for item in items:
        if item.endswith("-") and "=" not in item:
            removals.append(item[:-1])
            continue
        if "=" not in item:
            raise ApiError(f"invalid {what} {item!r} (want key=value)")
        k, _, v = item.partition("=")
        updates[k] = v
    return updates, removals


def _find_kv_split(args: List[str]):
    """TYPE NAME KEY=VAL... -> ((resource, name), kv-args). A trailing
    dash marks a removal; DNS names can't end with '-', so it's
    unambiguous in any position after the first arg."""
    kv_start = next((i for i, a in enumerate(args)
                     if (("=" in a or a.endswith("-")) and i >= 1)),
                    len(args))
    targets = parse_resource_args(args[:kv_start])
    return targets, args[kv_start:]


class Kubectl:
    def __init__(self, client, out=None, err=None,
                 scheme=default_scheme):
        self.client = client
        self.scheme = scheme
        self.out = out or sys.stdout
        self.err = err or sys.stderr

    # ------------------------------------------------------------- verbs

    def get(self, ns, args, output="", selector="", field_selector="",
            all_namespaces=False, watch=False, sort_by="") -> None:
        targets = parse_resource_args(args)
        objs = []
        names: List[str] = []
        list_rev = None
        for resource, name in targets:
            list_ns = "" if all_namespaces else ns
            if name is None:
                items, list_rev = self.client.list(
                    resource, list_ns, selector, field_selector)
                objs.extend(items)
                names.extend([resource] * len(items))
            else:
                objs.append(self.client.get(resource, name, list_ns))
                names.append(resource)
        if sort_by:
            objs, names = self._sort_objects(objs, names, sort_by)
        print_objects(objs, output, self.scheme, self.out,
                      resource_names=names, with_namespace=all_namespaces)
        if watch and len(targets) == 1 and targets[0][1] is None:
            # resume from the list's revision: nothing created between
            # list and watch is lost (the reflector's listwatch contract)
            w = self.client.watch(targets[0][0],
                                  "" if all_namespaces else ns,
                                  since_rev=list_rev)
            try:
                while True:
                    ev = w.next(timeout=1.0)
                    if ev is None:
                        if w.stopped:
                            break
                        continue
                    print_objects([ev.object], output, self.scheme, self.out)
            except KeyboardInterrupt:
                pass
            finally:
                w.stop()

    def _sort_objects(self, objs, names, sort_by):
        """--sort-by='{.field.path}' (ref: pkg/kubectl/sorting_printer.go
        SortingPrinter: a jsonpath field extracted per object keys the
        sort; missing fields sort first, mixed types by type name)."""
        def key(pair):
            try:
                val = jsonpath_get(self.scheme.encode_dict(pair[0]),
                                   sort_by)
            except (KeyError, IndexError, TypeError, ValueError):
                # the wire omits default-valued fields; absent (or an
                # expression this jsonpath subset can't evaluate)
                # sorts first like a zero value
                val = None
            if val is None:
                return (0, "", "")
            if isinstance(val, bool):
                return (1, "bool", str(val))
            if isinstance(val, (int, float)):
                return (1, "number", val)
            return (1, type(val).__name__, str(val))

        order = sorted(zip(objs, names), key=key)
        return [o for o, _ in order], [n for _, n in order]

    def describe(self, ns, args) -> None:
        for resource, name in parse_resource_args(args):
            if name is None:
                items, _ = self.client.list(resource, ns)
                names = [o.metadata.name for o in items]
            else:
                names = [name]
            for n in names:
                self.out.write(describe(self.client, self.scheme, resource,
                                        n, ns) + "\n\n")

    def create(self, ns, filename) -> None:
        for obj in load_manifest(filename, self.scheme):
            resource = resource_for_object(obj, self.scheme)
            created = self.client.create(resource, obj,
                                         obj.metadata.namespace or ns)
            self.out.write(f"{resource}/{created.metadata.name} created\n")

    def apply(self, ns, filename) -> None:
        """Declarative apply with a 3-way strategic merge: last-applied
        annotation + new config + live object, so server-set fields and
        other writers' changes survive a modify-reapply cycle
        (ref: pkg/util/strategicpatch/patch.go; the annotation protocol
        of kubectl apply)."""
        import json as jsonlib

        from ..utils.strategicpatch import three_way_merge
        for obj in load_manifest(filename, self.scheme):
            resource = resource_for_object(obj, self.scheme)
            target_ns = obj.metadata.namespace or ns
            config = self.scheme.encode_dict(obj)
            # the stored config never embeds its own annotation
            anns = config.get("metadata", {}).get("annotations")
            if anns:
                anns.pop(LAST_APPLIED_ANNOTATION, None)
            last_applied = jsonlib.dumps(config, sort_keys=True)
            try:
                live = self.client.get(resource, obj.metadata.name,
                                       target_ns)
            except NotFound:
                obj.metadata.annotations = {
                    **(obj.metadata.annotations or {}),
                    LAST_APPLIED_ANNOTATION: last_applied}
                created = self.client.create(resource, obj, target_ns)
                self.out.write(
                    f"{resource}/{created.metadata.name} created\n")
            else:
                live_dict = self.scheme.encode_dict(live)
                original = jsonlib.loads(
                    (live.metadata.annotations or {}).get(
                        LAST_APPLIED_ANNOTATION, "{}"))
                merged = three_way_merge(original, config, live_dict)
                md = merged.setdefault("metadata", {})
                md["annotations"] = {
                    **(md.get("annotations") or {}),
                    LAST_APPLIED_ANNOTATION: last_applied}
                updated = self.client.update(
                    resource, self.scheme.decode_dict(merged), target_ns)
                self.out.write(
                    f"{resource}/{updated.metadata.name} configured\n")

    def delete(self, ns, args, filename="", selector="",
               delete_all=False, grace_period=-1, cascade=True) -> None:
        # negative = unset (delete.go: "Ignored if negative")
        grace = grace_period if grace_period >= 0 else None

        def _one(resource, name, target_ns):
            if cascade and resource in self.REAPABLE:
                self._reap(resource, name, target_ns, grace)
            else:
                self.client.delete(resource, name, target_ns,
                                   grace_period_seconds=grace)
            self.out.write(f"{resource}/{name} deleted\n")

        if filename:
            for obj in load_manifest(filename, self.scheme):
                _one(resource_for_object(obj, self.scheme),
                     obj.metadata.name, obj.metadata.namespace or ns)
            return
        for resource, name in parse_resource_args(args):
            if name is not None:
                _one(resource, name, ns)
            elif selector or delete_all:
                items, _ = self.client.list(resource, ns, selector)
                for obj in items:
                    _one(resource, obj.metadata.name, ns)
            else:
                raise ApiError(
                    "resource name, --selector, or --all is required")

    def scale(self, ns, args, replicas, current_replicas=None) -> None:
        """(ref: pkg/kubectl/scale.go ScalerFor — RCs, jobs,
        deployments)"""
        for resource, name in parse_resource_args(args):
            obj = self.client.get(resource, name, ns)
            if resource == "jobs":
                field = "parallelism"
                current = obj.spec.parallelism
            else:
                field = "replicas"
                current = obj.spec.replicas
            if current_replicas is not None and current != current_replicas:
                raise ApiError(
                    f"precondition failed: current {current}, "
                    f"expected {current_replicas}")
            updated = replace(obj, spec=replace(obj.spec,
                                                **{field: replicas}))
            self.client.update(resource, updated, ns)
            self.out.write(f"{resource}/{name} scaled\n")

    def label(self, ns, args, overwrite=False) -> None:
        self._metadata_edit(ns, args, "labels", overwrite)

    def annotate(self, ns, args, overwrite=False) -> None:
        self._metadata_edit(ns, args, "annotations", overwrite)

    def _metadata_edit(self, ns, args, field, overwrite) -> None:
        targets, kv_args = _find_kv_split(args)
        updates, removals = _split_kv(kv_args, field[:-1])
        for resource, name in targets:
            obj = self.client.get(resource, name, ns)
            current = dict(getattr(obj.metadata, field))
            for k in updates:
                if k in current and not overwrite:
                    raise ApiError(
                        f"'{k}' already has a value; use --overwrite")
            current.update(updates)
            for k in removals:
                current.pop(k, None)
            updated = replace(obj, metadata=replace(obj.metadata,
                                                    **{field: current}))
            self.client.update(resource, updated, ns)
            self.out.write(f"{resource}/{name} {field[:-1]}ed\n")

    def expose(self, ns, args, port, target_port=None, name="",
               svc_type="ClusterIP") -> None:
        """(ref: pkg/kubectl/cmd/expose.go — selector from the exposed
        controller/service)"""
        ((resource, target),) = parse_resource_args(args)
        obj = self.client.get(resource, target, ns)
        if resource in ("replicationcontrollers", "services"):
            selector = dict(obj.spec.selector)
        elif resource == "pods":
            selector = dict(obj.metadata.labels)
        else:
            raise ApiError(f"cannot expose {resource}")
        svc = api.Service(
            metadata=api.ObjectMeta(name=name or target, namespace=ns),
            spec=api.ServiceSpec(
                selector=selector, type=svc_type,
                ports=[api.ServicePort(
                    name="default", port=port,
                    target_port=target_port or port)]))
        created = self.client.create("services", svc, ns)
        self.out.write(f"services/{created.metadata.name} exposed "
                       f"(ip {created.spec.cluster_ip})\n")

    def run(self, ns, name, image, replicas=1, labels="") -> None:
        """(ref: pkg/kubectl/cmd/run.go — image as an RC)"""
        if labels:
            label_map, removals = _split_kv(labels.split(","), "label")
            if removals or not label_map:
                raise ApiError(f"invalid --labels {labels!r}")
        else:
            label_map = {"run": name}
        rc = api.ReplicationController(
            metadata=api.ObjectMeta(name=name, namespace=ns,
                                    labels=dict(label_map)),
            spec=api.ReplicationControllerSpec(
                replicas=replicas, selector=dict(label_map),
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels=dict(label_map)),
                    spec=api.PodSpec(containers=[
                        api.Container(name=name, image=image)]))))
        self.client.create("replicationcontrollers", rc, ns)
        self.out.write(f"replicationcontrollers/{name} created\n")

    def rolling_update(self, ns, old_name, new_name, image="",
                       filename="", update_period=0.0) -> None:
        """(ref: pkg/kubectl/rolling_updater.go — scale new up one, old
        down one, until old is drained, then delete old)"""
        old = self.client.get("replicationcontrollers", old_name, ns)
        if filename:
            (new,) = load_manifest(filename, self.scheme)
        elif image:
            tpl = old.spec.template
            containers = [replace(c, image=image)
                          for c in tpl.spec.containers]
            selector = dict(old.spec.selector)
            selector["deployment"] = new_name
            labels = dict(tpl.metadata.labels)
            labels["deployment"] = new_name
            new = api.ReplicationController(
                metadata=api.ObjectMeta(name=new_name, namespace=ns,
                                        labels=dict(labels)),
                spec=api.ReplicationControllerSpec(
                    replicas=0, selector=selector,
                    template=api.PodTemplateSpec(
                        metadata=api.ObjectMeta(labels=labels),
                        spec=replace(tpl.spec, containers=containers))))
        else:
            raise ApiError("--image or -f is required")
        # disjoint the old RC's selector FIRST, or it adopts the new RC's
        # pods and the scale-down deletes them (ref: rolling_updater.go
        # AddDeploymentKeyToReplicationController: label existing pods,
        # then narrow the old selector)
        old = self._add_deployment_key(old, old_name, ns)
        desired = old.spec.replicas
        try:
            new = self.client.create("replicationcontrollers", new, ns)
        except AlreadyExists:  # resuming an interrupted update
            new = self.client.get("replicationcontrollers",
                                  new.metadata.name, ns)
        while new.spec.replicas < desired or old.spec.replicas > 0:
            if new.spec.replicas < desired:
                new = self.client.update(
                    "replicationcontrollers",
                    replace(new, spec=replace(
                        new.spec, replicas=new.spec.replicas + 1)), ns)
                self.out.write(
                    f"Scaling {new.metadata.name} up to "
                    f"{new.spec.replicas}\n")
            if old.spec.replicas > 0:
                old = self.client.update(
                    "replicationcontrollers",
                    replace(old, spec=replace(
                        old.spec, replicas=old.spec.replicas - 1)), ns)
                self.out.write(
                    f"Scaling {old.metadata.name} down to "
                    f"{old.spec.replicas}\n")
            if update_period:
                time.sleep(update_period)
        # delete the old RC only once its scale-down has been OBSERVED
        # (status.replicas from the RC manager) — deleting earlier orphans
        # the pods it hadn't removed yet (rolling_updater.go waits on each
        # resize before the final cleanup). Generous: a starved RC
        # manager (1-core box under full-suite load) can need minutes
        deadline = time.time() + 90
        drained = False
        while time.time() < deadline:
            fresh = self.client.get("replicationcontrollers", old_name, ns)
            if fresh.status.replicas == 0:
                drained = True
                break
            time.sleep(0.1)
        if not drained:
            # deleting an undrained RC orphans its remaining pods with a
            # misleading success message; fail loudly instead and leave
            # both RCs for the operator (rolling_updater.go errors on
            # its resize waits the same way)
            raise ApiError(
                f"timed out waiting for {old_name} to scale down; "
                f"not deleting it")
        self.client.delete("replicationcontrollers", old_name, ns)
        self.out.write(
            f"Update succeeded. Deleting {old_name}\n")

    def _add_deployment_key(self, rc, value, ns):
        """Label the RC's pods with deployment=<value>, then narrow the
        RC's selector+template to include it — making it disjoint from
        the new RC's pods (rolling_updater.go
        AddDeploymentKeyToReplicationController)."""
        if rc.spec.selector.get("deployment") == value:
            return rc
        from ..core.labels import selector_from_set
        sel = selector_from_set(rc.spec.selector)
        for pod in self.client.list("pods", ns)[0]:
            if not sel.matches(pod.metadata.labels):
                continue
            labels = dict(pod.metadata.labels)
            labels["deployment"] = value
            try:
                self.client.update("pods", replace(
                    pod, metadata=replace(pod.metadata, labels=labels)), ns)
            except ApiError:
                pass  # pod vanished mid-update: fine
        selector = dict(rc.spec.selector)
        selector["deployment"] = value
        tpl = rc.spec.template
        tpl_labels = dict(tpl.metadata.labels)
        tpl_labels["deployment"] = value
        updated = replace(rc, spec=replace(
            rc.spec, selector=selector,
            template=api.PodTemplateSpec(
                metadata=replace(tpl.metadata, labels=tpl_labels),
                spec=tpl.spec)))
        return self.client.update("replicationcontrollers", updated, ns)

    def autoscale(self, ns, args, min_replicas, max_replicas,
                  cpu_percent) -> None:
        ((resource, name),) = parse_resource_args(args)
        kind = {"replicationcontrollers": "ReplicationController",
                "deployments": "Deployment"}.get(resource)
        if kind is None:
            raise ApiError(f"cannot autoscale {resource}")
        hpa = api.HorizontalPodAutoscaler(
            metadata=api.ObjectMeta(name=name, namespace=ns),
            spec=api.HorizontalPodAutoscalerSpec(
                scale_ref=api.SubresourceReference(
                    kind=kind, name=name, namespace=ns),
                min_replicas=min_replicas, max_replicas=max_replicas,
                cpu_utilization_target_percentage=cpu_percent))
        self.client.create("horizontalpodautoscalers", hpa, ns)
        self.out.write(f"horizontalpodautoscalers/{name} autoscaled\n")

    def logs(self, ns, pod_name, container="", follow=False,
             previous=False) -> None:
        """Stream from the node's kubelet via the pod log subresource
        (the kubelet log endpoint, server.go:242). Nodes that serve no
        kubelet endpoint fall back to a container-state summary."""
        from ..core.errors import BadRequest
        if follow and previous:
            raise BadRequest("only one of follow (-f) or previous (-p) "
                             "may be specified")
        if previous:
            # -p must error loudly when no previous instance exists —
            # the state-summary fallback below would mask it
            try:
                self.out.write(self.client.pod_logs(
                    pod_name, ns, container, previous=True))
            except KeyError as e:
                raise NotFound(
                    f"previous terminated container for pod "
                    f"{pod_name!r} not found") from e
            return
        try:
            if follow:
                for piece in self.client.pod_logs_stream(
                        pod_name, ns, container):
                    self.out.write(piece)
                    if hasattr(self.out, "flush"):
                        self.out.flush()
                return
            self.out.write(self.client.pod_logs(pod_name, ns, container))
            return
        except (NotFound, NotImplementedError, KeyError):
            # no kubelet endpoint (or container unknown to the node):
            # fall back to the state summary. Transport/server failures
            # (BadGateway, BadRequest) surface as errors, not silence.
            pass
        pod = self.client.get("pods", pod_name, ns)
        for cs in pod.status.container_statuses:
            if container and cs.name != container:
                continue
            state = ("running" if cs.state.running
                     else "terminated" if cs.state.terminated else "waiting")
            self.out.write(f"[{cs.name}] state={state} "
                           f"restarts={cs.restart_count}\n")

    def replace(self, ns, filename, force=False) -> None:
        """kubectl replace: full update from a manifest (ref:
        cmd/replace.go — PUT semantics; --force deletes and re-creates,
        resetting resourceVersion/uid)."""
        for obj in load_manifest(filename, self.scheme):
            resource = resource_for_object(obj, self.scheme)
            target_ns = obj.metadata.namespace or ns
            if force:
                try:
                    self.client.delete(resource, obj.metadata.name,
                                       target_ns)
                except NotFound:
                    pass
                self.client.create(resource, obj, target_ns)
                self.out.write(f"{resource}/{obj.metadata.name} "
                               f"replaced (forced)\n")
                continue
            live = self.client.get(resource, obj.metadata.name, target_ns)
            # PUT needs the optimistic-concurrency token of the live
            # object unless the manifest pinned one itself
            if not obj.metadata.resource_version:
                obj.metadata.resource_version = \
                    live.metadata.resource_version
            self.client.update(resource, obj, target_ns)
            self.out.write(f"{resource}/{obj.metadata.name} replaced\n")

    def patch(self, ns, args, patch_json) -> None:
        """kubectl patch: strategic-merge a JSON fragment onto the live
        object SERVER-SIDE (ref: cmd/patch.go — the CLI sends the raw
        patch with the strategic content type and the apiserver's patch
        handler does the merge + optimistic-concurrency retry)."""
        import json as jsonlib

        resource, name = parse_resource_args(args)[0]
        try:
            patch = jsonlib.loads(patch_json)
        except jsonlib.JSONDecodeError as e:
            raise ApiError(f"invalid patch: {e}")
        if not isinstance(patch, dict):
            raise ApiError("patch must be a JSON object")
        self.client.patch(resource, name, patch, ns)
        self.out.write(f"{resource}/{name} patched\n")

    # kinds with a reaper (ref: pkg/kubectl/stop.go ReaperFor) — the
    # cascade path drains their managed pods before the final delete
    REAPABLE = ("replicationcontrollers", "jobs", "daemonsets")

    def _reap(self, resource: str, name: str, target_ns: str,
              grace=None) -> None:
        """Drain a controller's pods, then delete it (ref:
        pkg/kubectl/stop.go): RCs scale to 0 and wait on
        status.replicas; Jobs scale parallelism to 0, wait on
        status.active, then delete their (dead) pods; DaemonSets
        retarget to an unmatchable node selector and wait for the
        controller to kill every daemon pod. A drain that never
        completes raises instead of deleting (the reference reapers
        return the wait error) — deleting anyway would orphan the
        pods silently. A target that vanishes mid-drain counts as
        reaped (a concurrent delete won the race)."""
        deadline = time.time() + 30

        def _drained(check) -> bool:
            """Poll until check(current) or deadline; NotFound = gone =
            drained."""
            while time.time() < deadline:
                try:
                    if check(self.client.get(resource, name, target_ns)):
                        return True
                except NotFound:
                    return True
                time.sleep(0.1)
            try:
                return check(self.client.get(resource, name, target_ns))
            except NotFound:
                return True

        try:
            drained = self._reap_drain(resource, name, target_ns,
                                       grace, _drained)
        except NotFound:
            return  # already gone: a concurrent deleter won the race
        if not drained:
            raise ApiError(
                f"timed out waiting for {resource}/{name} to drain; "
                f"not deleting (pods would be orphaned — use "
                f"--cascade=false to delete the object anyway)")
        try:
            self.client.delete(resource, name, target_ns,
                               grace_period_seconds=grace)
        except NotFound:
            pass  # a concurrent deleter finished first: outcome reached

    def _reap_drain(self, resource, name, target_ns, grace,
                    _drained) -> bool:
        drained = True
        if resource == "replicationcontrollers":
            rc = self.client.get(resource, name, target_ns)
            # never mutate a cached object: stored objects are frozen
            self.client.update(
                resource,
                replace(rc, spec=replace(rc.spec, replicas=0)),
                target_ns)
            # wait for the manager to observe the scale-down before
            # deleting (stop.go's reaper does exactly this) — delete
            # racing the controller's informer would orphan the pods
            drained = _drained(lambda live: live.status.replicas == 0)
        elif resource == "jobs":
            job = self.client.get(resource, name, target_ns)
            self.client.update(
                resource,
                replace(job, spec=replace(job.spec, parallelism=0)),
                target_ns)
            drained = _drained(lambda live: live.status.active == 0)
            # only dead pods remain; remove them (JobReaper.Stop)
            sel = ",".join(f"{k}={v}"
                           for k, v in sorted(job.spec.selector.items()))
            if drained and sel:
                pods, _ = self.client.list("pods", target_ns, sel)
                for p in pods:
                    try:
                        self.client.delete("pods", p.metadata.name,
                                           target_ns,
                                           grace_period_seconds=grace)
                    except ApiError:
                        pass
        elif resource == "daemonsets":
            import uuid as _uuid
            ds = self.client.get(resource, name, target_ns)
            tpl = ds.spec.template
            # an unmatchable selector: the controller deletes every
            # daemon pod (DaemonSetReaper.Stop's random-label move)
            unmatchable = {str(_uuid.uuid4()): str(_uuid.uuid4())}
            self.client.update(
                resource,
                replace(ds, spec=replace(
                    ds.spec,
                    template=replace(tpl, spec=replace(
                        tpl.spec, node_selector=unmatchable)))),
                target_ns)
            drained = _drained(
                lambda live: live.status.current_number_scheduled
                + live.status.number_misscheduled == 0)
        return drained

    def stop(self, ns, args, filename="") -> None:
        """kubectl stop: graceful shutdown — controllers drain before
        deletion so their pods terminate first (ref: pkg/kubectl/stop.go
        ReaperFor)."""
        targets = []
        if filename:
            for obj in load_manifest(filename, self.scheme):
                targets.append((resource_for_object(obj, self.scheme),
                                obj.metadata.name,
                                obj.metadata.namespace or ns))
        else:
            for resource, name in parse_resource_args(args):
                if name is None:
                    raise ApiError("stop requires TYPE NAME")
                targets.append((resource, name, ns))
        for resource, name, target_ns in targets:
            self._reap(resource, name, target_ns)
            self.out.write(f"{resource}/{name} stopped\n")

    def edit(self, ns, args) -> int:
        """kubectl edit: round the live object through $EDITOR, update
        on change (ref: cmd/edit.go)."""
        import json as jsonlib
        import os as _os
        import subprocess as _subprocess
        import tempfile as _tempfile

        resource, name = parse_resource_args(args)[0]
        live = self.client.get(resource, name, ns)
        doc = jsonlib.dumps(self.scheme.encode_dict(live), indent=2,
                            sort_keys=True)
        editor = _os.environ.get("EDITOR", "vi")
        with _tempfile.NamedTemporaryFile(
                mode="w+", suffix=".json", delete=False) as f:
            f.write(doc)
            path = f.name
        try:
            rc = _subprocess.call(f"{editor} {path}", shell=True)
            if rc != 0:
                self.err.write(f"error: editor exited {rc}\n")
                return 1
            edited = open(path).read()
        finally:
            _os.unlink(path)
        if edited.strip() == doc.strip():
            self.out.write("Edit cancelled, no changes made.\n")
            return 0
        obj = self.scheme.decode_dict(jsonlib.loads(edited))
        self.client.update(resource, obj, ns)
        self.out.write(f"{resource}/{name} edited\n")
        return 0

    def explain(self, path) -> None:
        """kubectl explain: field documentation reflected from the
        API dataclasses (ref: cmd/explain.go over swagger models; our
        swagger reflects from the same classes, so this cannot
        drift)."""
        import dataclasses as _dc
        import typing as _typing

        from ..api.registry import Registry
        from .resource import resolve_resource
        parts = path.split(".")
        info = Registry.info(resolve_resource(parts[0]))
        cls = info.cls
        for seg in parts[1:]:
            hints = _typing.get_type_hints(cls)
            if seg not in hints:
                raise ApiError(f"field {seg!r} does not exist in "
                               f"{cls.__name__}")
            tp = hints[seg]
            # unwrap Optional[X] / List[X] to the element type
            for _ in range(3):
                args = _typing.get_args(tp)
                if args:
                    tp = next((a for a in args if a is not type(None)),
                              tp)
                else:
                    break
            cls = tp
        self.out.write(f"KIND:     {info.kind}\n")
        self.out.write(f"RESOURCE: {path}\n\n")
        if getattr(cls, "__doc__", None):
            first = (cls.__doc__ or "").strip().splitlines()
            if first:
                self.out.write(f"DESCRIPTION:\n  {first[0]}\n\n")
        if _dc.is_dataclass(cls):
            self.out.write("FIELDS:\n")
            hints = _typing.get_type_hints(cls)
            for fld in _dc.fields(cls):
                tname = getattr(hints[fld.name], "__name__",
                                str(hints[fld.name]))
                self.out.write(f"  {fld.name}\t<{tname}>\n")
        else:
            self.out.write(f"TYPE: {getattr(cls, '__name__', cls)}\n")

    def convert(self, filename) -> None:
        """kubectl convert: normalize a manifest through the served
        codec (one wire version here, so convert == canonicalize)."""
        import json as jsonlib
        for obj in load_manifest(filename, self.scheme):
            self.out.write(jsonlib.dumps(
                self.scheme.encode_dict(obj), indent=2, sort_keys=True)
                + "\n")

    def proxy(self, address="127.0.0.1", port=8001, block=True):
        """kubectl proxy: a local HTTP server relaying every request to
        the apiserver with this client's credentials (ref:
        cmd/proxy.go)."""
        from .proxy import ApiProxy
        base = getattr(self.client, "base_url", None)
        if not base:
            raise ApiError("proxy requires an apiserver URL (-s)")
        srv = ApiProxy(self.client, address, port).start()
        self.out.write(f"Starting to serve on {address}:{srv.port}\n")
        if hasattr(self.out, "flush"):
            self.out.flush()
        if not block:
            self._proxy_server = srv  # tests stop it explicitly
            return 0
        try:
            while True:
                srv.join(1.0)
        except KeyboardInterrupt:
            return 0
        finally:
            srv.stop()

    def config(self, args, kubeconfig_path=None) -> int:
        """kubectl config: view / current-context / use-context /
        set-cluster / set-credentials / set-context / get-contexts over
        the kubeconfig file (ref: pkg/kubectl/cmd/config; the file
        format is clientcmd's v1 Config)."""
        import json as jsonlib

        from ..api.kubeconfig import (AuthInfo, Cluster, Context,
                                      KubeConfig, dump_kubeconfig,
                                      load_kubeconfig, save_kubeconfig)
        try:
            cfg = load_kubeconfig(kubeconfig_path or None)
        except FileNotFoundError:
            cfg = KubeConfig()
        action = args.action
        if action == "view":
            doc = dump_kubeconfig(cfg)
            if not getattr(args, "raw", False):
                # the reference masks credentials unless --raw: view is
                # a command users treat as safe to paste
                for entry in doc["users"]:
                    for secret in ("token", "password"):
                        if entry["user"].get(secret):
                            entry["user"][secret] = "REDACTED"
            self.out.write(jsonlib.dumps(doc, indent=2) + "\n")
            return 0
        if action == "current-context":
            if not cfg.current_context:
                self.err.write("error: current-context is not set\n")
                return 1
            self.out.write(cfg.current_context + "\n")
            return 0
        if action == "get-contexts":
            self.out.write("CURRENT   NAME   CLUSTER   NAMESPACE\n")
            for name, ctx in sorted(cfg.contexts.items()):
                star = "*" if name == cfg.current_context else " "
                self.out.write(f"{star}         {name}   {ctx.cluster}"
                               f"   {ctx.namespace or 'default'}\n")
            return 0
        if not args.name:
            raise ApiError(f"config {action} requires a NAME")
        if action == "use-context":
            if args.name not in cfg.contexts:
                self.err.write(
                    f"error: no context exists with the name "
                    f"{args.name!r}\n")
                return 1
            cfg.current_context = args.name
            msg = f'Switched to context "{args.name}".'
        elif action == "set-cluster":
            cfg.clusters[args.name] = Cluster(server=args.server)
            msg = f'Cluster "{args.name}" set.'
        elif action == "set-credentials":
            cfg.users[args.name] = AuthInfo(
                token=args.token, username=args.username,
                password=args.password)
            msg = f'User "{args.name}" set.'
        else:  # set-context
            cfg.contexts[args.name] = Context(
                cluster=args.cluster, user=args.user,
                namespace=args.context_namespace)
            msg = f'Context "{args.name}" created.'
        save_kubeconfig(cfg, kubeconfig_path or None)
        self.out.write(msg + "\n")
        return 0

    def namespace_cmd(self, name=None) -> None:
        """(ref: cmd/namespace.go — deprecated in the reference too)"""
        self.out.write(
            "namespace has been superseded by context switching; "
            "use kubeconfig contexts to select a namespace\n")

    def attach(self, ns, pod_name, container="", stdin=False,
               stdin_stream=None) -> int:
        """kubectl attach: stream the container's live output (and feed
        stdin with -i) over the websocket attach subresource (ref:
        cmd/attach.go; SPDY there, RFC 6455 here).
        stdin_stream: byte-stream override for tests (defaults to this
        process's stdin buffer)."""
        import codecs
        import threading as _threading

        from ..utils import wsstream
        ws = self.client.attach_open(pod_name, ns, container, stdin=stdin)
        # incremental decode: the kubelet's 64KiB frames split at
        # arbitrary byte offsets, so a multi-byte character straddling a
        # frame boundary must not decode fragment-by-fragment
        decode = codecs.getincrementaldecoder("utf-8")(
            errors="replace").decode
        try:
            if stdin:
                src = stdin_stream if stdin_stream is not None \
                    else sys.stdin.buffer

                def pump_stdin():
                    try:
                        while True:
                            # read1: forward whatever the terminal has —
                            # BufferedReader.read(n) would block until n
                            # bytes amass and typed input would never
                            # reach the container
                            data = (src.read1(4096)
                                    if hasattr(src, "read1")
                                    else src.read(4096))
                            if not data:
                                wsstream.write_frame(
                                    ws.sendall, wsstream.EOF_MARKER,
                                    wsstream.TEXT, mask=True)
                                return
                            wsstream.write_frame(ws.sendall, data,
                                                 wsstream.BINARY,
                                                 mask=True)
                    except (ConnectionError, OSError, ValueError):
                        pass

                _threading.Thread(target=pump_stdin, daemon=True).start()
            while True:
                opcode, payload = wsstream.read_frame(ws.recv)
                if opcode == wsstream.CLOSE:
                    return 0
                if opcode == wsstream.BINARY and payload:
                    self.out.write(decode(payload))
                    if hasattr(self.out, "flush"):
                        self.out.flush()
        except KeyboardInterrupt:
            return 0  # Ctrl-C is the detach gesture, not an error
        except (ConnectionError, OSError) as e:
            # a broken transport is a failure, not a clean detach (the
            # reference kubectl reports it and exits non-zero)
            self.err.write(f"error: attach transport: {e}\n")
            return 1
        finally:
            ws.close()

    def port_forward(self, ns, pod_name, mapping, address="127.0.0.1",
                     block=True) -> int:
        """kubectl port-forward POD LOCAL:REMOTE (ref: cmd/portforward.go
        — SPDY there, websocket legs here; see cli/portforward.py)."""
        from .portforward import PortForwarder
        parts = mapping.split(":")
        if len(parts) == 1:
            local = remote = int(parts[0])
        elif len(parts) == 2:
            local, remote = int(parts[0] or 0), int(parts[1])
        else:
            raise ApiError(f"bad port mapping {mapping!r}")
        fwd = PortForwarder(self.client, pod_name, ns, local, remote,
                            address).start()
        self.out.write(f"Forwarding from {address}:{fwd.local_port} "
                       f"-> {remote}\n")
        if hasattr(self.out, "flush"):
            self.out.flush()
        if not block:
            self._forwarder = fwd  # tests stop it explicitly
            return 0
        try:
            while True:
                fwd._accept_thread.join(1.0)
                if not fwd._accept_thread.is_alive():
                    return 0
        except KeyboardInterrupt:
            return 0
        finally:
            fwd.stop()

    def exec_cmd(self, ns, pod_name, container, cmd, stdin=False,
                 stdin_stream=None) -> int:
        """Run a command in a container. Non-interactive: the
        apiserver's node-proxy exec relay (one-shot {exitCode, output}).
        With -i: the websocket exec subresource streams output live,
        feeds stdin, and propagates the real exit code (ref: kubectl
        exec -> kubelet ExecInContainer, server.go:242; SPDY there,
        RFC 6455 here)."""
        import json as jsonlib
        import urllib.parse as up
        pod = self.client.get("pods", pod_name, ns)
        if not pod.spec.node_name:
            raise ApiError(f"pod {pod_name!r} is not scheduled yet")
        if not container:
            if len(pod.spec.containers) > 1:
                raise ApiError(
                    f"pod {pod_name!r} has several containers; use -c")
            container = pod.spec.containers[0].name
        if stdin:
            return self._exec_interactive(ns, pod_name, container, cmd,
                                          stdin_stream)
        query = up.urlencode([("command", c) for c in cmd])
        raw = self.client.node_proxy(
            pod.spec.node_name,
            f"exec/{ns}/{pod_name}/{container}?{query}")
        result = jsonlib.loads(raw)
        self.out.write(result.get("output", ""))
        return int(result.get("exitCode", 0))

    def _exec_interactive(self, ns, pod_name, container, cmd,
                          stdin_stream=None) -> int:
        """The attach loop with an exec session at the far end: BINARY
        frames are output, the final TEXT frame carries the exit code."""
        import codecs
        import json as jsonlib
        import threading as _threading

        from ..utils import wsstream
        ws = self.client.exec_open(pod_name, ns, cmd, container,
                                   stdin=True)
        decode = codecs.getincrementaldecoder("utf-8")(
            errors="replace").decode
        exit_code = 0
        try:
            src = stdin_stream if stdin_stream is not None \
                else sys.stdin.buffer

            def pump_stdin():
                try:
                    while True:
                        data = (src.read1(4096) if hasattr(src, "read1")
                                else src.read(4096))
                        if not data:
                            wsstream.write_frame(
                                ws.sendall, wsstream.EOF_MARKER,
                                wsstream.TEXT, mask=True)
                            return
                        wsstream.write_frame(ws.sendall, data,
                                             wsstream.BINARY, mask=True)
                except (ConnectionError, OSError, ValueError):
                    pass

            _threading.Thread(target=pump_stdin, daemon=True).start()
            while True:
                opcode, payload = wsstream.read_frame(ws.recv)
                if opcode == wsstream.CLOSE:
                    return exit_code
                if opcode == wsstream.BINARY and payload:
                    self.out.write(decode(payload))
                    if hasattr(self.out, "flush"):
                        self.out.flush()
                elif opcode == wsstream.TEXT and \
                        payload != wsstream.EOF_MARKER:
                    try:
                        exit_code = int(
                            jsonlib.loads(payload).get("exitCode", 0))
                    except (ValueError, AttributeError):
                        pass
        except KeyboardInterrupt:
            return exit_code
        except (ConnectionError, OSError) as e:
            self.err.write(f"error: exec transport: {e}\n")
            return 1
        finally:
            ws.close()

    def version(self) -> None:
        self.out.write(f"Client Version: {VERSION}\n")

    def api_versions(self) -> None:
        self.out.write("v1\nextensions/v1beta1\n")

    def cluster_info(self, server_url) -> None:
        self.out.write(f"Kubernetes master is running at {server_url}\n")


def main(argv: Optional[List[str]] = None, client=None, out=None,
         err=None) -> int:
    parser = build_parser()
    ns_args = parser.parse_args(argv)
    if ns_args.command is None:
        parser.print_help()
        return 1
    ns = ns_args.namespace
    if ns_args.command == "config":
        # config edits the kubeconfig file itself — no apiserver needed
        k = Kubectl(client or HttpClient("http://127.0.0.1:8080"),
                    out=out, err=err)
        try:
            return k.config(ns_args, ns_args.kubeconfig or None)
        except (ApiError, OSError, ValueError) as e:
            # unreadable/unwritable/malformed config files included: a
            # clean error beats a traceback (same contract as below)
            (err or sys.stderr).write(f"Error: {e}\n")
            return 1
        except Exception as e:
            # yaml's concrete errors (ScannerError/ParserError) only
            # subclass YAMLError — check the MRO, not the leaf name
            if any(c.__name__ == "YAMLError" for c in type(e).__mro__):
                (err or sys.stderr).write(f"Error: {e}\n")
                return 1
            raise
    if client is None:
        # credential resolution mirrors clientcmd: explicit -s/--token
        # beats kubeconfig; kubeconfig is consulted when -s is absent
        # and a config exists (--kubeconfig / $KUBECONFIG /
        # ~/.kube/config)
        from ..api.kubeconfig import DEFAULT_PATH, client_from_kubeconfig
        import os as _os
        kc_path = (ns_args.kubeconfig or _os.environ.get("KUBECONFIG")
                   or (DEFAULT_PATH if _os.path.exists(DEFAULT_PATH)
                       else ""))
        if not ns_args.server and not ns_args.token and kc_path:
            try:
                client, kc_ns = client_from_kubeconfig(
                    kc_path, ns_args.context)
                ns = ns or kc_ns
            except Exception as e:  # unreadable/malformed config: a
                # clean one-liner, whatever the parser raised
                (err or sys.stderr).write(f"Error loading kubeconfig: {e}\n")
                return 1
        else:
            headers = ({"Authorization": f"Bearer {ns_args.token}"}
                       if ns_args.token else None)
            client = HttpClient(
                ns_args.server or "http://127.0.0.1:8080",
                headers=headers)
    k = Kubectl(client, out=out, err=err)
    ns = ns or "default"
    try:
        if ns_args.command == "get":
            k.get(ns, ns_args.args, ns_args.output, ns_args.selector,
                  ns_args.field_selector, ns_args.all_namespaces,
                  ns_args.watch, ns_args.sort_by)
        elif ns_args.command == "describe":
            k.describe(ns, ns_args.args)
        elif ns_args.command == "create":
            k.create(ns, ns_args.filename)
        elif ns_args.command == "apply":
            k.apply(ns, ns_args.filename)
        elif ns_args.command == "delete":
            k.delete(ns, ns_args.args, ns_args.filename, ns_args.selector,
                     ns_args.all, ns_args.grace_period, ns_args.cascade)
        elif ns_args.command == "scale":
            k.scale(ns, ns_args.args, ns_args.replicas,
                    ns_args.current_replicas)
        elif ns_args.command == "label":
            k.label(ns, ns_args.args, ns_args.overwrite)
        elif ns_args.command == "annotate":
            k.annotate(ns, ns_args.args, ns_args.overwrite)
        elif ns_args.command == "expose":
            k.expose(ns, ns_args.args, ns_args.port, ns_args.target_port,
                     ns_args.name, ns_args.type)
        elif ns_args.command == "run":
            k.run(ns, ns_args.name, ns_args.image, ns_args.replicas,
                  ns_args.labels)
        elif ns_args.command == "rolling-update":
            k.rolling_update(ns, ns_args.old_name, ns_args.new_name,
                             ns_args.image, ns_args.filename,
                             ns_args.update_period)
        elif ns_args.command == "autoscale":
            k.autoscale(ns, ns_args.args, ns_args.min, ns_args.max,
                        ns_args.cpu_percent)
        elif ns_args.command == "logs":
            k.logs(ns, ns_args.pod, ns_args.container,
                   follow=ns_args.follow, previous=ns_args.previous)
        elif ns_args.command == "exec":
            return k.exec_cmd(ns, ns_args.pod, ns_args.container,
                              ns_args.cmd, stdin=ns_args.stdin)
        elif ns_args.command == "port-forward":
            return k.port_forward(ns, ns_args.pod, ns_args.mapping,
                                  ns_args.address)
        elif ns_args.command == "attach":
            return k.attach(ns, ns_args.pod, ns_args.container,
                            ns_args.stdin)
        elif ns_args.command == "replace":
            k.replace(ns, ns_args.filename, ns_args.force)
        elif ns_args.command == "patch":
            k.patch(ns, ns_args.args, ns_args.patch)
        elif ns_args.command == "stop":
            k.stop(ns, ns_args.args, ns_args.filename)
        elif ns_args.command == "edit":
            return k.edit(ns, ns_args.args)
        elif ns_args.command == "explain":
            k.explain(ns_args.path)
        elif ns_args.command == "convert":
            k.convert(ns_args.filename)
        elif ns_args.command == "proxy":
            return k.proxy(ns_args.address, ns_args.port)
        elif ns_args.command == "namespace":
            k.namespace_cmd(ns_args.name)
        elif ns_args.command == "version":
            k.version()
        elif ns_args.command == "api-versions":
            k.api_versions()
        elif ns_args.command == "cluster-info":
            k.cluster_info(getattr(client, "base_url", None)
                           or ns_args.server)
        return 0
    except ApiError as e:
        (err or sys.stderr).write(f"Error: {e}\n")
        return 1
    except (OSError, ValueError) as e:
        # bad -f path, unreadable/malformed manifest (JSONDecodeError is
        # a ValueError): a clean error beats a traceback
        (err or sys.stderr).write(f"Error: {e}\n")
        return 1


if __name__ == "__main__":
    sys.exit(main())
