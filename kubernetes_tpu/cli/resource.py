"""Resource argument builder: names, aliases, TYPE/NAME forms, -f files.

Reference: pkg/kubectl/resource (the Builder) and kubectl.ShortForms
(pkg/kubectl/kubectl.go expandResourceShortcut).
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

from ..core.errors import BadRequest

# ref: pkg/kubectl/cmd/cmd.go shortForms
ALIASES = {
    "po": "pods", "pod": "pods",
    "no": "nodes", "node": "nodes",
    "svc": "services", "service": "services",
    "rc": "replicationcontrollers",
    "replicationcontroller": "replicationcontrollers",
    "ns": "namespaces", "namespace": "namespaces",
    "ev": "events", "event": "events",
    "ep": "endpoints",
    "limits": "limitranges", "limitrange": "limitranges",
    "quota": "resourcequotas", "resourcequota": "resourcequotas",
    "secret": "secrets",
    "sa": "serviceaccounts", "serviceaccount": "serviceaccounts",
    "pv": "persistentvolumes", "persistentvolume": "persistentvolumes",
    "pvc": "persistentvolumeclaims",
    "persistentvolumeclaim": "persistentvolumeclaims",
    "deploy": "deployments", "deployment": "deployments",
    "ds": "daemonsets", "daemonset": "daemonsets",
    "job": "jobs",
    "hpa": "horizontalpodautoscalers",
    "horizontalpodautoscaler": "horizontalpodautoscalers",
    "ing": "ingresses", "ingress": "ingresses",
}


def resolve_resource(arg: str) -> str:
    return ALIASES.get(arg.lower(), arg.lower())


def parse_resource_args(args: List[str]) -> List[Tuple[str, Optional[str]]]:
    """kubectl arg forms -> [(resource, name-or-None)]:
    `get pods`, `get pods name1 name2`, `get pod/name`, `get pods,svc`.
    """
    if not args:
        raise BadRequest("resource type required")
    head = args[0]
    out: List[Tuple[str, Optional[str]]] = []
    if "/" in head:
        for item in args:
            if "/" not in item:
                raise BadRequest(
                    f"mixed TYPE/NAME and bare arguments: {item!r}")
            rtype, _, name = item.partition("/")
            out.append((resolve_resource(rtype), name))
        return out
    resources = [resolve_resource(r) for r in head.split(",")]
    names = args[1:]
    if names and len(resources) > 1:
        raise BadRequest("names cannot be combined with multiple resources")
    if not names:
        return [(r, None) for r in resources]
    return [(resources[0], n) for n in names]


def load_manifest(path: str, scheme) -> List:
    """-f input: one object, a JSON list, or a v1 List kind."""
    if path == "-":
        import sys
        raw = sys.stdin.read()
    else:
        with open(path) as f:
            raw = f.read()
    data = json.loads(raw)
    if isinstance(data, list):
        return [scheme.decode_dict(d) for d in data]
    if isinstance(data, dict) and data.get("kind", "").endswith("List"):
        return [scheme.decode_dict(d) for d in data.get("items", [])]
    return [scheme.decode_dict(data)]


def resource_for_object(obj, scheme) -> str:
    kind = scheme.kind_for(obj)
    from ..api.registry import RESOURCES
    for name, info in RESOURCES.items():
        if info.kind == kind:
            return name
    raise BadRequest(f"no resource for kind {kind}")
