from .server import ClusterDNS, DEFAULT_CLUSTER_DOMAIN  # noqa: F401
