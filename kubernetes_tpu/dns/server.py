"""Cluster DNS: the kube-dns addon role as one process.

The reference runs two containers (ref: cluster/addons/dns): kube2sky
watches services/endpoints and writes skydns records into etcd, and
skydns serves DNS from them. Here both roles collapse into one server
fed directly by services + endpoints informers — no etcd hop, no
record-sync lag beyond the watch itself (DIVERGENCES #16).

Served schema (ref: cluster/addons/dns/README.md):

- A ``{svc}.{ns}.svc.{domain}`` → the service's cluster IP; for
  headless services (clusterIP "None") → one A record per ready
  endpoint address.
- SRV ``_{port}._{proto}.{svc}.{ns}.svc.{domain}`` → (10, 10, port,
  ``{svc}.{ns}.svc.{domain}``) for each *named* port.
- A ``{a-b-c-d}.{ns}.pod.{domain}`` → a.b.c.d (pods get synthesized
  ip-derived names; enabled by default like the addon).
- Names under the cluster domain that exist but lack the queried type
  → NODATA (NOERROR, zero answers); unknown names → NXDOMAIN; queries
  outside the cluster domain → SERVFAIL, or relayed verbatim to an
  ``upstream`` resolver when one is configured (the skydns forwarding
  role).

Wire protocol is real RFC 1035 over both UDP and length-prefixed TCP
(DNS's canonical transports — the UDP proxy path this repo grew in
round 4 exists exactly because of this service). Responses compress
the owner name with a pointer to the question (0xC00C).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..api.cache import Informer
from ..core import types as api

DEFAULT_CLUSTER_DOMAIN = "cluster.local"

TYPE_A = 1
TYPE_CNAME = 5
TYPE_SRV = 33
CLASS_IN = 1

RCODE_NOERROR = 0
RCODE_SERVFAIL = 2
RCODE_NXDOMAIN = 3
RCODE_NOTIMP = 4

_TTL = 30  # skydns default TTL for kube records


# ------------------------------------------------------------ wire codec

def encode_name(name: str) -> bytes:
    name = name.rstrip(".")
    if not name:  # the root name encodes as a lone terminator
        return b"\x00"
    out = b""
    for label in name.split("."):
        raw = label.encode("ascii")
        if not 0 < len(raw) < 64:
            raise ValueError(f"bad label in {name!r}")
        out += bytes([len(raw)]) + raw
    return out + b"\x00"


def decode_name(buf: bytes, off: int) -> Tuple[str, int]:
    """Returns (name, next offset). Follows compression pointers."""
    labels: List[str] = []
    jumped = False
    end = off
    seen = 0
    while True:
        if off >= len(buf):
            raise ValueError("truncated name")
        length = buf[off]
        if length & 0xC0 == 0x40 or length & 0xC0 == 0x80:
            # 0x40/0x80 high bits are reserved (RFC 1035 4.1.4 allows
            # only 00 = label, 11 = pointer): treating them as label
            # lengths would admit labels >63 bytes that encode_name
            # later rejects INSIDE build_response — a malformed query
            # must fail here, in the parse step handle_packet drops
            raise ValueError(f"reserved label length 0x{length:02x}")
        if length & 0xC0 == 0xC0:  # pointer
            if off + 1 >= len(buf):
                raise ValueError("truncated pointer")
            ptr = ((length & 0x3F) << 8) | buf[off + 1]
            if not jumped:
                end = off + 2
            off = ptr
            jumped = True
            seen += 1
            if seen > 64:
                raise ValueError("pointer loop")
            continue
        off += 1
        if length == 0:
            if not jumped:
                end = off
            return ".".join(labels), end
        labels.append(buf[off:off + length].decode("ascii"))
        off += length


def parse_query(data: bytes) -> Tuple[int, str, int, int]:
    """Returns (id, qname, qtype, qclass) for a single-question query."""
    if len(data) < 12:
        raise ValueError("short packet")
    qid, flags, qd, _an, _ns, _ar = struct.unpack("!HHHHHH", data[:12])
    if flags & 0x8000:
        raise ValueError("not a query")
    if qd != 1:
        raise ValueError("expected one question")
    qname, off = decode_name(data, 12)
    if off + 4 > len(data):
        raise ValueError("truncated question")
    qtype, qclass = struct.unpack("!HH", data[off:off + 4])
    return qid, qname, qtype, qclass


def build_response(qid: int, qname: str, qtype: int, qclass: int,
                   answers: List[bytes], rcode: int) -> bytes:
    # QR=1, AA=1, RD echoed off; the question section is echoed verbatim
    flags = 0x8400 | (rcode & 0xF)
    head = struct.pack("!HHHHHH", qid, flags, 1, len(answers), 0, 0)
    question = encode_name(qname) + struct.pack("!HH", qtype, qclass)
    return head + question + b"".join(answers)


def rr_a(ip: str) -> bytes:
    return (b"\xc0\x0c" + struct.pack("!HHIH", TYPE_A, CLASS_IN, _TTL, 4)
            + socket.inet_aton(ip))


def rr_srv(port: int, target: str) -> bytes:
    rdata = struct.pack("!HHH", 10, 10, port) + encode_name(target)
    return (b"\xc0\x0c" + struct.pack("!HHIH", TYPE_SRV, CLASS_IN, _TTL,
                                      len(rdata)) + rdata)


# ------------------------------------------------------------- the server

class ClusterDNS:
    """Serves the cluster schema from live service/endpoints caches.

    client: any list/watch client (InProc or HTTP). upstream: optional
    ``(host, port)`` resolver that queries outside the cluster domain
    are relayed to verbatim (skydns's forwarding role); without one
    they answer SERVFAIL so resolvers fail over per resolv.conf.
    """

    def __init__(self, client, host: str = "127.0.0.1", port: int = 0,
                 cluster_domain: str = DEFAULT_CLUSTER_DOMAIN,
                 upstream: Optional[Tuple[str, int]] = None,
                 serve_pod_records: bool = True):
        self.client = client
        self.cluster_domain = cluster_domain.strip(".").lower()
        self.upstream = upstream
        self.serve_pod_records = serve_pod_records
        self._services = Informer(client, "services")
        self._endpoints = Informer(client, "endpoints")
        dns = self

        class _UDPHandler(socketserver.BaseRequestHandler):
            def handle(self):
                data, sock = self.request
                reply = dns.handle_packet(data)
                if reply is not None:
                    if len(reply) > 512:
                        # RFC 1035 4.2.1: UDP messages cap at 512
                        # bytes — truncate to the empty-answer header
                        # with TC set so the resolver retries over the
                        # TCP listener this server already runs (a
                        # headless service with ~30 endpoints exceeds
                        # the cap)
                        head = bytearray(reply[:12])
                        head[2] |= 0x02          # TC bit
                        head[6:8] = b"\x00\x00"  # ANCOUNT = 0
                        # keep header + question section only: scan to
                        # the end of QNAME then 4 fixed bytes
                        i = 12
                        while i < len(reply) and reply[i] != 0:
                            i += 1 + reply[i]
                        i += 1 + 4
                        reply = bytes(head) + reply[12:i]
                    sock.sendto(reply, self.client_address)

        class _TCPHandler(socketserver.BaseRequestHandler):
            def handle(self):
                raw = b""
                while len(raw) < 2:  # the prefix can arrive split too
                    chunk = self.request.recv(2 - len(raw))
                    if not chunk:
                        return
                    raw += chunk
                (n,) = struct.unpack("!H", raw)
                data = b""
                while len(data) < n:
                    chunk = self.request.recv(n - len(data))
                    if not chunk:
                        return
                    data += chunk
                reply = dns.handle_packet(data)
                if reply is not None:
                    self.request.sendall(struct.pack("!H", len(reply))
                                         + reply)

        self._udp = socketserver.ThreadingUDPServer((host, port),
                                                    _UDPHandler)
        self._udp.daemon_threads = True
        self.port = self._udp.server_address[1]
        # same port on TCP (the DNS convention)
        self._tcp = socketserver.ThreadingTCPServer(
            (host, self.port), _TCPHandler, bind_and_activate=False)
        self._tcp.allow_reuse_address = True
        self._tcp.daemon_threads = True
        self._tcp.server_bind()
        self._tcp.server_activate()
        self.host = host
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------- lifecycle

    def start(self) -> "ClusterDNS":
        self._services.start()
        self._endpoints.start()
        for srv in (self._udp, self._tcp):
            t = threading.Thread(target=srv.serve_forever, daemon=True,
                                 name=f"cluster-dns-{self.port}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        for srv in (self._udp, self._tcp):
            srv.shutdown()
            srv.server_close()
        self._services.stop()
        self._endpoints.stop()

    # -------------------------------------------------------- resolution

    def handle_packet(self, data: bytes) -> Optional[bytes]:
        try:
            qid, qname, qtype, qclass = parse_query(data)
        except ValueError:
            return None  # unparseable: drop, like a lost datagram
        lname = qname.rstrip(".").lower()
        if not (lname == self.cluster_domain
                or lname.endswith("." + self.cluster_domain)):
            if self.upstream is not None:
                relayed = self._relay_upstream(data)
                if relayed is not None:
                    return relayed
            return build_response(qid, qname, qtype, qclass, [],
                                  RCODE_SERVFAIL)
        if qclass != CLASS_IN:
            return build_response(qid, qname, qtype, qclass, [],
                                  RCODE_NOTIMP)
        answers, exists = self.resolve(lname, qtype)
        rcode = RCODE_NOERROR if exists else RCODE_NXDOMAIN
        return build_response(qid, qname, qtype, qclass, answers, rcode)

    def resolve(self, lname: str, qtype: int) -> Tuple[List[bytes], bool]:
        """Returns (answer RRs, name exists). Empty+exists = NODATA."""
        rel = lname[:-len(self.cluster_domain)].strip(".") \
            if lname != self.cluster_domain else ""
        labels = rel.split(".") if rel else []
        # {svc}.{ns}.svc  |  _{port}._{proto}.{svc}.{ns}.svc
        if len(labels) == 3 and labels[2] == "svc":
            svc = self._service(labels[1], labels[0])
            if svc is None:
                return [], False
            return (self._service_a(svc) if qtype == TYPE_A else []), True
        if (len(labels) == 5 and labels[4] == "svc"
                and labels[0].startswith("_")
                and labels[1].startswith("_")):
            svc = self._service(labels[3], labels[2])
            if svc is None:
                return [], False
            port = self._named_port(svc, labels[0][1:], labels[1][1:])
            if port is None:
                return [], False
            if qtype != TYPE_SRV:
                return [], True
            target = (f"{svc.metadata.name}.{svc.metadata.namespace}"
                      f".svc.{self.cluster_domain}")
            return [rr_srv(port, target)], True
        # {a-b-c-d}.{ns}.pod
        if (len(labels) == 3 and labels[2] == "pod"
                and self.serve_pod_records):
            ip = labels[0].replace("-", ".")
            try:
                socket.inet_aton(ip)
            except OSError:
                return [], False
            if ip.count(".") != 3:
                return [], False
            return ([rr_a(ip)] if qtype == TYPE_A else []), True
        # the zone itself and intermediate names (ns.svc.domain, svc.
        # domain, domain) exist so resolv.conf search-path probing gets
        # NODATA rather than NXDOMAIN on its way to the full name
        if len(labels) <= 2:
            return [], True
        return [], False

    # --------------------------------------------------------- records

    def _service(self, namespace: str, name: str) -> Optional[api.Service]:
        # keyed cache lookup, not a scan — this is the hottest path of
        # a server every pod's resolver points at (object names are
        # already lowercase per DNS-1123, matching the lowered qname)
        return self._services.cache.get_by_key(f"{namespace}/{name}")

    def _service_a(self, svc: api.Service) -> List[bytes]:
        ip = svc.spec.cluster_ip
        if ip and ip != "None":
            return [rr_a(ip)]
        # headless: one A per endpoint address, deterministic order
        ips = set()
        ep = self._endpoints.cache.get_by_key(
            f"{svc.metadata.namespace}/{svc.metadata.name}")
        if ep is not None:
            for subset in ep.subsets:
                for addr in subset.addresses:
                    ips.add(addr.ip)
        return [rr_a(ip) for ip in sorted(ips)]

    @staticmethod
    def _named_port(svc: api.Service, port_name: str,
                    proto: str) -> Optional[int]:
        for sp in svc.spec.ports:
            if (sp.name and sp.name.lower() == port_name
                    and (sp.protocol or "TCP").lower() == proto):
                return sp.port
        return None

    # -------------------------------------------------------- forwarding

    def _relay_upstream(self, data: bytes) -> Optional[bytes]:
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.settimeout(2.0)
                s.sendto(data, self.upstream)
                reply, _ = s.recvfrom(4096)
                return reply
        except OSError:
            return None
