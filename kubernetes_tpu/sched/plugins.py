"""Predicate/priority provider registry.

Reference: plugin/pkg/scheduler/factory/plugins.go:55-315 (global maps of
named FitPredicateFactory / PriorityConfigFactory, RegisterCustomFitPredicate
:91, RegisterCustomPriorityFunction :158, provider sets :68-71) and
algorithmprovider/defaults/defaults.go:34-96 (DefaultProvider + 1.0-compat
aliases).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..core.errors import BadRequest
from . import predicates as preds
from . import priorities as prios
from .api import Policy, PredicatePolicy, PriorityPolicy


@dataclass
class PluginFactoryArgs:
    """(ref: plugins.go PluginFactoryArgs)"""
    pod_lister: object = None
    service_lister: object = None
    controller_lister: object = None
    node_lister: object = None


PredicateFactory = Callable[[PluginFactoryArgs], Callable]
PriorityFactory = Callable[[PluginFactoryArgs], Tuple[Callable, int]]

_fit_predicate_factories: Dict[str, PredicateFactory] = {}
_priority_factories: Dict[str, Callable[[PluginFactoryArgs], Callable]] = {}
_default_priority_weights: Dict[str, int] = {}
_algorithm_providers: Dict[str, Tuple[Set[str], Set[str]]] = {}


def register_fit_predicate(name: str, factory: PredicateFactory) -> str:
    _fit_predicate_factories[name] = factory
    return name


def register_priority(name: str, factory, weight: int = 1) -> str:
    _priority_factories[name] = factory
    _default_priority_weights[name] = weight
    return name


def register_algorithm_provider(name: str, predicate_keys: Set[str],
                                priority_keys: Set[str]) -> str:
    _algorithm_providers[name] = (set(predicate_keys), set(priority_keys))
    return name


def get_algorithm_provider(name: str) -> Tuple[Set[str], Set[str]]:
    try:
        return _algorithm_providers[name]
    except KeyError:
        raise BadRequest(f"plugin {name!r} has not been registered")


def get_fit_predicates(names: Set[str],
                       args: PluginFactoryArgs) -> Dict[str, Callable]:
    out = {}
    for name in names:
        if name not in _fit_predicate_factories:
            raise BadRequest(f"invalid predicate name {name!r}")
        out[name] = _fit_predicate_factories[name](args)
    return out


def get_priority_configs(names: Set[str], args: PluginFactoryArgs,
                         weights: Optional[Dict[str, int]] = None
                         ) -> List[Tuple[Callable, int]]:
    out = []
    for name in sorted(names):
        if name not in _priority_factories:
            raise BadRequest(f"invalid priority name {name!r}")
        weight = (weights or {}).get(name, _default_priority_weights.get(name, 1))
        out.append((_priority_factories[name](args), weight))
    return out


# ------------------------------------------------------- custom (policy)

def predicate_from_policy(policy: PredicatePolicy,
                          args: PluginFactoryArgs) -> Callable:
    """(ref: plugins.go:91 RegisterCustomFitPredicate)"""
    if policy.service_affinity is not None:
        node_by_name = getattr(args.node_lister, "get", None)
        return preds.new_service_affinity_predicate(
            args.pod_lister, args.service_lister,
            policy.service_affinity.labels, node_by_name)
    if policy.labels_presence is not None:
        return preds.new_node_label_predicate(
            policy.labels_presence.labels, policy.labels_presence.presence)
    if policy.name in _fit_predicate_factories:
        return _fit_predicate_factories[policy.name](args)
    raise BadRequest(f"invalid predicate policy {policy.name!r}")


def priority_from_policy(policy: PriorityPolicy,
                         args: PluginFactoryArgs) -> Tuple[Callable, int]:
    """(ref: plugins.go:158 RegisterCustomPriorityFunction)"""
    if policy.service_anti_affinity is not None:
        fn = prios.ServiceAntiAffinity(
            args.service_lister,
            policy.service_anti_affinity.label).calculate_anti_affinity_priority
        return fn, policy.weight
    if policy.label_preference is not None:
        fn = prios.new_node_label_priority(
            policy.label_preference.label, policy.label_preference.presence)
        return fn, policy.weight
    if policy.name in _priority_factories:
        return _priority_factories[policy.name](args), policy.weight
    raise BadRequest(f"invalid priority policy {policy.name!r}")


# --------------------------------------------------------- registrations
# (ref: defaults.go:54-96 defaultPredicates/defaultPriorities and the
#  1.0-compatibility aliases :34-52)

register_fit_predicate("PodFitsHostPorts",
                       lambda args: preds.pod_fits_host_ports)
register_fit_predicate("PodFitsPorts",  # 1.0 alias
                       lambda args: preds.pod_fits_host_ports)
register_fit_predicate("PodFitsResources",
                       lambda args: preds.pod_fits_resources)
register_fit_predicate("NoDiskConflict",
                       lambda args: preds.no_disk_conflict)
register_fit_predicate("MatchNodeSelector",
                       lambda args: preds.pod_selector_matches)
register_fit_predicate("HostName", lambda args: preds.pod_fits_host)
register_fit_predicate("NodeSchedulable",
                       lambda args: preds.pod_fits_node_schedulable)


def _inter_pod_affinity_factory(args: PluginFactoryArgs) -> Callable:
    # BASELINE config 4 extension (the quadratic pod x pod term). The
    # node lister MUST resolve arbitrary cached nodes by name — anything
    # less silently disables anti-affinity, so fail loudly at wiring time.
    if not hasattr(args.node_lister, "get"):
        raise BadRequest(
            "InterPodAffinity requires a node lister with get(name)")
    return preds.new_inter_pod_affinity_predicate(
        args.pod_lister, args.node_lister.get)


register_fit_predicate("InterPodAffinity", _inter_pod_affinity_factory)

register_priority(
    "LeastRequestedPriority",
    lambda args: prios.least_requested_priority, 1)
register_priority(
    "BalancedResourceAllocation",
    lambda args: prios.balanced_resource_allocation, 1)
register_priority(
    "SelectorSpreadPriority",
    lambda args: prios.SelectorSpread(
        args.service_lister, args.controller_lister).calculate_spread_priority, 1)
register_priority(
    "ServiceSpreadingPriority",  # 1.0 alias: services only
    lambda args: prios.SelectorSpread(
        args.service_lister, None).calculate_spread_priority, 1)
register_priority("EqualPriority", lambda args: prios.equal_priority, 1)

DEFAULT_PROVIDER = "DefaultProvider"

# Deliberate divergence from defaults.go:54-96: InterPodAffinity joins the
# default predicate set (the reference has no inter-pod affinity at v1.1;
# the batch engine enforces it unconditionally for pods that carry
# spec.affinity, so the serial fallback must too — path-independent
# bindings). Pods without affinity specs are unaffected. NodeSchedulable
# joins too: the reference leans on the filtered node watch alone, but a
# node that dies between the informer's candidate filter and the
# predicate walk (or a static node lister that never filtered) must not
# receive bindings — the device engine enforces the same via its
# sched_ok mask column, so the serial provider must match.
register_algorithm_provider(
    DEFAULT_PROVIDER,
    {"PodFitsHostPorts", "PodFitsResources", "NoDiskConflict",
     "MatchNodeSelector", "HostName", "InterPodAffinity",
     "NodeSchedulable"},
    {"LeastRequestedPriority", "BalancedResourceAllocation",
     "SelectorSpreadPriority"})
