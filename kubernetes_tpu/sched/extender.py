"""Scheduler extender — the reference's HTTP RPC seam, client side.

Reference: plugin/pkg/scheduler/extender.go:38-172 and api/types.go:27-158.
Wire protocol (kept verbatim so our TPU backend can also bolt onto a stock
kube-scheduler, and so stock extenders can bolt onto us):

    POST {urlPrefix}/{apiVersion}/{filterVerb}
        body: ExtenderArgs{"pod": <Pod>, "nodes": <NodeList>}
        resp: ExtenderFilterResult{"nodes": <NodeList>, "error": str}
    POST {urlPrefix}/{apiVersion}/{prioritizeVerb}
        body: ExtenderArgs
        resp: HostPriorityList [{"host": str, "score": int}]

Filter errors fail the pod; prioritize errors are ignored by the caller
(generic_scheduler.go:197-199). Default timeout 5s (extender.go:33).
"""

from __future__ import annotations

import json
import urllib.request
from typing import List, Sequence, Tuple

from ..core import types as api
from ..core.scheme import Scheme, default_scheme
from .api import ExtenderConfig, HostPriority


class ExtenderError(Exception):
    pass


class HTTPExtender:
    """(ref: extender.go:52 HTTPExtender)"""

    def __init__(self, config: ExtenderConfig,
                 scheme: Scheme = default_scheme):
        self.config = config
        self.scheme = scheme

    def _url(self, verb: str) -> str:
        return "/".join(
            [self.config.url_prefix.rstrip("/"), self.config.api_version, verb])

    def _post(self, verb: str, args: dict) -> dict:
        req = urllib.request.Request(
            self._url(verb), data=json.dumps(args).encode(),
            headers={"Content-Type": "application/json",
                     "Accept": "application/json"}, method="POST")
        with urllib.request.urlopen(req,
                                    timeout=self.config.http_timeout) as resp:
            return json.loads(resp.read().decode())

    def _extender_args(self, pod: api.Pod,
                       nodes: Sequence[api.Node]) -> dict:
        return {
            "pod": self.scheme.encode_dict(pod),
            "nodes": self.scheme.encode_list("Node", nodes),
        }

    def filter(self, pod: api.Pod,
               nodes: Sequence[api.Node]) -> List[api.Node]:
        """(ref: extender.go:95 Filter — errors fail the pod)"""
        if not self.config.filter_verb:
            return list(nodes)
        result = self._post(self.config.filter_verb,
                            self._extender_args(pod, nodes))
        if result.get("error"):
            raise ExtenderError(result["error"])
        items = (result.get("nodes") or {}).get("items") or []
        return [self.scheme.decode_dict({**n, "kind": "Node"}) for n in items]

    def prioritize(self, pod: api.Pod, nodes: Sequence[api.Node]
                   ) -> Tuple[List[HostPriority], int]:
        """(ref: extender.go:119 Prioritize)"""
        if not self.config.prioritize_verb:
            return [], 1
        result = self._post(self.config.prioritize_verb,
                            self._extender_args(pod, nodes))
        return ([HostPriority(e["host"], int(e["score"])) for e in result],
                self.config.weight)
