"""The generic scheduler: filter -> score -> select.

Reference: plugin/pkg/scheduler/generic_scheduler.go:65-236.

Deliberate divergence (documented per SURVEY.md section 7 step 4): the
reference breaks score ties with `rand.Int() % len(best)`
(generic_scheduler.go:105); we default to a DETERMINISTIC tie-break — the
first host in the reference's sorted order (score desc, host name desc, per
api/types.go Less + sort.Reverse) — and optionally accept an RNG for
replicating the reference's distribution. "Identical bindings" for the
parity gate means: chosen host is a member of the reference's max-score set.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core import types as api
from .api import HostPriority
from .predicates import map_pods_to_machines
from .priorities import equal_priority


class NoNodesAvailable(Exception):
    """(ref: generic_scheduler.go ErrNoNodesAvailable)"""
    def __str__(self) -> str:
        return "no nodes available to schedule pods"


class FitError(Exception):
    """(ref: generic_scheduler.go FitError)"""

    def __init__(self, pod: api.Pod, failed_predicates: Dict[str, set]):
        self.pod = pod
        self.failed_predicates = failed_predicates
        super().__init__(self._message())

    def _message(self) -> str:
        # ref: FitError.Error "failed to fit in any node"
        reasons = {r for rs in self.failed_predicates.values() for r in rs}
        return ("pod (%s) failed to fit in any node\n" % self.pod.metadata.name
                + "\n".join(f"fit failure on node: {r}" for r in sorted(reasons)))


# Predicate: fn(pod, existing_pods, node) -> (bool, Optional[str])
# Priority:  fn(pod, pod_lister, node_lister) -> List[HostPriority]
PriorityConfig = Tuple[Callable, int]  # (function, weight)


class _StaticNodeLister:
    def __init__(self, nodes: Sequence[api.Node]):
        self._nodes = list(nodes)

    def list(self) -> List[api.Node]:
        return list(self._nodes)


def find_nodes_that_fit(pod: api.Pod, pod_lister,
                        predicates: Dict[str, Callable],
                        nodes: Sequence[api.Node],
                        extenders: Sequence = ()
                        ) -> Tuple[List[api.Node], Dict[str, set]]:
    """(ref: generic_scheduler.go:111 findNodesThatFit) — the serial
    O(nodes x predicates x pods) hot loop the TPU engine replaces."""
    machine_to_pods = map_pods_to_machines(pod_lister)
    filtered: List[api.Node] = []
    failed: Dict[str, set] = {}
    for node in nodes:
        name = node.metadata.name
        fits = True
        for pred_name, predicate in predicates.items():
            fit, reason = predicate(pod, machine_to_pods.get(name, []), node)
            if not fit:
                fits = False
                failed.setdefault(name, set()).add(reason or pred_name)
                break  # ref: short-circuits per node on first failure
        if fits:
            filtered.append(node)
    if filtered and extenders:
        for extender in extenders:
            filtered = extender.filter(pod, filtered)
            if not filtered:
                break
    return filtered, failed


def prioritize_nodes(pod: api.Pod, pod_lister,
                     priority_configs: Sequence[PriorityConfig],
                     node_lister, extenders: Sequence = ()
                     ) -> List[HostPriority]:
    """(ref: generic_scheduler.go:164 PrioritizeNodes)"""
    if not priority_configs and not extenders:
        return equal_priority(pod, pod_lister, node_lister)
    combined: Dict[str, int] = {}
    for func, weight in priority_configs:
        if weight == 0:
            continue
        for entry in func(pod, pod_lister, node_lister):
            combined[entry.host] = combined.get(entry.host, 0) \
                + entry.score * weight
    if extenders and node_lister is not None:
        nodes = node_lister.list()
        for extender in extenders:
            try:
                prioritized, weight = extender.prioritize(pod, nodes)
            except Exception:
                # ref: generic_scheduler.go:197-199 — extender prioritize
                # errors are ignored
                continue
            for entry in prioritized:
                combined[entry.host] = combined.get(entry.host, 0) \
                    + entry.score * weight
    return [HostPriority(host, score) for host, score in combined.items()]


def sort_host_priorities(priority_list: List[HostPriority]) -> List[HostPriority]:
    """Reference order: score descending, then host name DESCENDING
    (sort.Reverse over Less comparing (score, host) ascending,
    api/types.go:164-169 + generic_scheduler.go:98)."""
    return sorted(priority_list, key=lambda h: (h.score, h.host), reverse=True)


def get_best_hosts(priority_list: List[HostPriority]) -> List[str]:
    """All hosts tied at the top score, in sorted order
    (ref: generic_scheduler.go:214 getBestHosts)."""
    ordered = sort_host_priorities(priority_list)
    best = [h.host for h in ordered if h.score == ordered[0].score]
    return best


class GenericScheduler:
    """(ref: generic_scheduler.go:50 genericScheduler struct + Schedule)"""

    def __init__(self, predicates: Dict[str, Callable],
                 prioritizers: Sequence[PriorityConfig],
                 pod_lister, extenders: Sequence = (),
                 rng: Optional[random.Random] = None):
        self.predicates = predicates
        self.prioritizers = list(prioritizers)
        self.pod_lister = pod_lister
        self.extenders = list(extenders)
        # None -> deterministic tie-break (documented divergence)
        self.rng = rng

    def schedule(self, pod: api.Pod, node_lister) -> str:
        return self.select_host(self._prioritized(pod, node_lister))

    def _prioritized(self, pod: api.Pod, node_lister) -> List[HostPriority]:
        """Shared filter->score pipeline for schedule() and tie_set()."""
        nodes = node_lister.list()
        if not nodes:
            raise NoNodesAvailable()
        filtered, failed = find_nodes_that_fit(
            pod, self.pod_lister, self.predicates, nodes, self.extenders)
        priority_list = prioritize_nodes(
            pod, self.pod_lister, self.prioritizers,
            _StaticNodeLister(filtered), self.extenders)
        if not priority_list:
            raise FitError(pod, failed)
        return priority_list

    def select_host(self, priority_list: List[HostPriority]) -> str:
        """(ref: generic_scheduler.go:95 selectHost)"""
        if not priority_list:
            raise ValueError("empty priority list")
        best = get_best_hosts(priority_list)
        if self.rng is not None:
            return best[self.rng.randrange(0, 1 << 62) % len(best)]
        return best[0]

    def tie_set(self, pod: api.Pod, node_lister) -> List[str]:
        """The max-score host set — what binding parity is judged against."""
        return get_best_hosts(self._prioritized(pod, node_lister))
