"""Algorithm-facing listers + in-memory fakes for tests.

Reference: plugin/pkg/scheduler/algorithm/listers.go (FakePodLister,
FakeNodeLister, FakeServiceLister, FakeControllerLister). The live
implementations are api.cache.StoreTo*Lister; these fakes mirror the
reference's fake-per-boundary test pattern (SURVEY.md section 4).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..core import labels as labelspkg
from ..core import types as api


class FakePodLister:
    def __init__(self, pods: Sequence[api.Pod] = ()):
        self.pods = list(pods)

    def list(self, selector: Optional[labelspkg.Selector] = None) -> List[api.Pod]:
        if selector is None or selector.empty():
            return list(self.pods)
        return [p for p in self.pods if selector.matches(p.metadata.labels)]

    def exists(self, pod: api.Pod) -> bool:
        key = (pod.metadata.namespace, pod.metadata.name)
        return any((p.metadata.namespace, p.metadata.name) == key
                   for p in self.pods)


class FakeNodeLister:
    def __init__(self, nodes: Sequence[api.Node] = ()):
        self.nodes = list(nodes)

    def list(self) -> List[api.Node]:
        return list(self.nodes)

    def get(self, name: str) -> Optional[api.Node]:
        for n in self.nodes:
            if n.metadata.name == name:
                return n
        return None


class FakeServiceLister:
    def __init__(self, services: Sequence[api.Service] = ()):
        self.services = list(services)

    def list(self) -> List[api.Service]:
        return list(self.services)

    def get_pod_services(self, pod: api.Pod) -> List[api.Service]:
        out = []
        for svc in self.services:
            if svc.metadata.namespace and \
                    svc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = svc.spec.selector
            if not sel:
                continue
            if labelspkg.selector_from_set(sel).matches(pod.metadata.labels):
                out.append(svc)
        return out


class FakeControllerLister:
    def __init__(self, controllers: Sequence[api.ReplicationController] = ()):
        self.controllers = list(controllers)

    def list(self) -> List[api.ReplicationController]:
        return list(self.controllers)

    def get_pod_controllers(self, pod: api.Pod) -> List[api.ReplicationController]:
        out = []
        for rc in self.controllers:
            if rc.metadata.namespace and \
                    rc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = rc.spec.selector
            if not sel:
                continue
            if labelspkg.selector_from_set(sel).matches(pod.metadata.labels):
                out.append(rc)
        return out
