"""Batch scheduling control loop: the TPU fast path.

Where the reference's scheduleOne is strictly serial (scheduler.go:120 —
one pod, one Schedule() call, one binding POST), this loop drains the
pending FIFO into a tile, schedules the whole tile on device in one
compiled scan (sched.device), and commits the resulting bindings in one
batched CAS pass (registry.bind_batch — single lock acquisition, per-pod
conflict semantics; SURVEY.md section 7 hard part 2).

Semantics parity: the engine carries assume-pod state inside the scan, so
within a tile pod k+1 sees pod k's binding exactly as the serial
scheduler's modeler would. Across tiles the modeler plays its usual role
(bind -> assume -> watch confirms). Unschedulable pods take the same
error path (backoff + requeue) as the serial loop.

Fast-path eligibility is decided by the factory (create_batch): the
default algorithm provider with no extenders maps onto the engine; any
custom policy (service affinity, label presence, anti-affinity priority,
HTTP extenders) falls back to the serial Scheduler — the provable
fallback the BASELINE requires.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

from .. import obs
from ..core import types as api
from ..core.errors import Conflict, NotFound
from ..utils.metrics import MetricsRegistry, global_metrics
from .device import BatchEngine, ClusterSnapshot
from .device.incremental import IncrementalEncoder, NeedsFullEncode
from .generic import FitError
from .predicates import node_schedulable


@dataclass
class _Inflight:
    """A tile dispatched to the device but not yet finalized: its
    assignment array is lazy (materializes on np.asarray) and its final
    carry State lives on device for the next tile to chain from."""
    pods: List[api.Pod]
    enc: Any                 # EncodeResult
    assigned: Any            # lazy jax i32[p_pad]
    state: Any               # device State (the scan's final carry)
    epoch: int               # encoder state_epoch at encode time
    flags: Tuple[bool, bool]  # (has_aff, has_spread)
    t_start: float
    t_dev: float
    # encoder shard-epoch vector at encode time (TableDelta.shard_epochs;
    # None on the full-encode path): _finalize fences on it — a tile
    # whose vector no longer matches the encoder's was dispatched
    # against a mesh that lost a shard, and is dropped whole
    shard_epochs: Optional[Tuple[int, ...]] = None
    # set once _finalize has handed the tile's bindings over (commit
    # queued or committed) — the drain_commits barrier rides behind it
    landed: threading.Event = field(default_factory=threading.Event)


def _carry_compatible(enc, prev_state) -> bool:
    """Would the device carry from the previous tile slot into this
    tile's State position bit-for-bit? Shapes and dtypes must agree
    (interner growth widens bitsets; gcd changes flip narrowing)."""
    st = enc.init_state
    pairs = ((st.cpu_used, prev_state.cpu_used),
             (st.mem_used, prev_state.mem_used),
             (st.nz_cpu, prev_state.nz_cpu),
             (st.nz_mem, prev_state.nz_mem),
             (st.pod_count, prev_state.pod_count),
             (st.port_bits, prev_state.port_bits),
             (st.disk_any, prev_state.disk_any),
             (st.disk_rw, prev_state.disk_rw),
             (st.spread, prev_state.spread),
             (st.aff_count, prev_state.aff_count),
             (st.aff_total, prev_state.aff_total),
             (st.svc_count, prev_state.svc_count),
             (st.svc_total, prev_state.svc_total))
    return all(a.shape == tuple(b.shape) and a.dtype == b.dtype
               for a, b in pairs)


class BatchSchedulerConfig:
    def __init__(self, factory, engine: Optional[BatchEngine] = None,
                 tile_size: int = 8192, min_pad: int = 64,
                 bulk_chunk: int = 1024, incremental: bool = True,
                 commit_chunk: int = 0,
                 metrics: Optional[MetricsRegistry] = None,
                 mesh=None, shard_monitor=None, preemption=None):
        self.factory = factory
        # priority preemption (sched/preemption.py PreemptionPass):
        # None (the default) keeps the pre-priority behavior — an
        # infeasible pod takes the plain error path no matter its
        # priority. Only meaningful on the incremental path (the victim
        # table is a cut of the encoder's ledger).
        self.preemption = preemption
        # shard-failure tolerance (sched/device/shardfail.py): a
        # ShardLeaseMonitor polled between tiles. An expired shard
        # lease triggers fence -> survivor re-shard -> in-flight drop;
        # None (the default) keeps the mesh un-monitored.
        self.shard_monitor = shard_monitor
        # mesh= shards the node axis of the live pipeline across devices
        # (ignored when an explicit engine is passed — the engine's own
        # mesh wins); the encoder below keeps slot capacity a multiple
        # of the mesh size so shards stay block-aligned
        self.engine = engine or BatchEngine(mesh=mesh)
        self.tile_size = tile_size
        # bind-commit sub-batch size: 0 commits the whole tile as ONE
        # multi-key store transaction (registry routes commit_txn — one
        # ledger window, one WAL frame, one publish batch); a positive
        # value restores the per-chunk store.batch() loops, kept as the
        # A/B control arm (bench.py --txn-ab; 1024 was the pre-txn
        # sweet spot on the 1-core box)
        self.commit_chunk = commit_chunk
        # scan-chunk sizes: small drains compile/run the [min_pad] program,
        # bulk drains the [bulk_chunk] one — exactly two XLA programs per
        # node-table shape, regardless of tile size (engine.run_chunked)
        self.min_pad = min_pad
        self.bulk_chunk = bulk_chunk
        # incremental device state (watch deltas -> persistent arrays,
        # SURVEY.md section 7 hard part 4). Node-static policy tiers
        # (label presence/priorities) ride along; the anti-affinity tier
        # needs per-tile service groups and keeps the full encode
        self.incremental = incremental and (
            self.engine.policy is None
            or not self.engine.policy.needs_anti_affinity)
        self.metrics = metrics or global_metrics


class BatchScheduler:
    """Tile-at-a-time scheduler over the device engine.

    HA: pass `elector` (utils/leaderelection.LeaderElector) and the
    scheduler becomes a CANDIDATE — the scan loop idles until the
    elector wins the lease, and every leadership session starts from a
    fresh device state (see _on_started_leading). N replicas can run
    against one apiserver; the bind CAS guarantees a pod binds once no
    matter how leadership moved mid-tile.
    """

    def __init__(self, config: BatchSchedulerConfig, elector=None):
        self.config = config
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._inc: Optional[IncrementalEncoder] = None
        # leadership gate: the scan loop only drains the FIFO while
        # set. Electorless schedulers lead unconditionally.
        self._leading = threading.Event()
        self._killed = False
        self.elector = elector
        if elector is None:
            self._leading.set()
        else:
            elector.on_started_leading = self._on_started_leading
            elector.on_stopped_leading = self._on_stopped_leading
        # the dispatched-but-unfinalized tile (device pipeline depth 1):
        # scheduler-thread only
        self._prev: Optional[_Inflight] = None
        # the most recently handed-off unfinalized tile (scheduler-
        # thread writes; FIFO means its landed event implies every
        # earlier handoff landed too — see _ledger_current)
        self._last_handed: Optional[_Inflight] = None
        # the commit pipeline (SURVEY.md section 7 hard part 2 + the
        # reference's scheduler->binder two-stage analogue,
        # scheduler.go:120-165): tile k's binding commit runs on this
        # thread while tile k+1 encodes and executes on device. Sound
        # because the incremental state is advanced OPTIMISTICALLY at
        # schedule time (assume-before-bind); a failed bind is corrected
        # by the watch echo (deleted pod -> remove, bound-elsewhere ->
        # node change), and until then the error is conservative (the
        # node looks fuller than it is). Bounded queue = backpressure.
        self._commit_q: "queue.Queue[Optional[list]]" = queue.Queue(
            maxsize=4)
        self._commit_thread: Optional[threading.Thread] = None
        # longest FIFO wait among the pods of the last drained tile
        # (scheduler-thread only) — the "queue" stage span reads it
        self._last_drain_wait = 0.0

    def _incremental(self) -> Optional[IncrementalEncoder]:
        """Lazily attach the incremental encoder (the factory's informers
        must be running; attach+bootstrap is idempotent via the ledger)."""
        if not self.config.incremental:
            return None
        if self._inc is None:
            inc = IncrementalEncoder(
                policy=self.config.engine.policy,
                mesh_devices=self.config.engine.n_shards)
            # narrowing must budget for a dispatched-but-unassumed tile
            inc.inflight_pad = self.config.tile_size
            self._inc = inc.attach(self.config.factory)
        return self._inc

    def run(self) -> "BatchScheduler":
        self._thread = threading.Thread(target=self._loop,
                                        name="batch-scheduler", daemon=True)
        self._thread.start()
        self._commit_thread = threading.Thread(
            target=self._commit_loop, name="batch-binder", daemon=True)
        self._commit_thread.start()
        if self.elector is not None:
            self.elector.run()
        return self

    # ------------------------------------------------------- leadership

    def _on_started_leading(self, term: int) -> None:
        """Failover rebuild: drop every pre-leadership carry — the
        in-flight tile and the incremental device ledger — and
        bootstrap a fresh encoder from the informer caches (a fresh
        re-list of bound pods and nodes) on the next tile. The pending
        FIFO needs no rebuild: the unassigned reflector has been
        feeding it all along, and a pod the old leader managed to bind
        mid-failover leaves via its filtered-watch DELETE (or, at
        worst, the bind CAS rejects the duplicate and _bind_failed
        re-reads it)."""
        self._prev = None
        self._last_handed = None
        old = self._inc
        self._inc = None
        if old is not None:
            old.detach()
        self._leading.set()

    def _on_stopped_leading(self) -> None:
        self._leading.clear()

    @property
    def is_leader(self) -> bool:
        return self._leading.is_set()

    def kill(self) -> None:
        """Simulated process death (chaos/crash.py): scheduling halts
        NOW, queued-but-uncommitted tiles are dropped (a dead binder
        binds nothing), and the lease is NOT released — the standby
        waits out the expiry and takes over under a new fencing term,
        re-scheduling whatever this process left unbound."""
        self._killed = True
        self._leading.clear()
        if self.elector is not None:
            self.elector.kill()
        self._stop.set()

    def stop(self) -> None:
        if self.elector is not None:
            self.elector.stop()  # demotes + releases the lease
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=30)
        if self._thread and self._thread.is_alive():
            # the scheduler thread is wedged mid-tile (e.g. a cold XLA
            # compile): leave the committer alive so a tile published
            # after this point still binds — both threads are daemons
            return
        # flush: every scheduled-but-uncommitted tile still binds
        try:
            self._commit_q.put(None, timeout=30)
        except queue.Full:
            # committer wedged mid-tile (e.g. per-pod CAS fallback over
            # a big tile): it's a daemon, let it drain in the background
            # rather than hanging shutdown
            return
        if self._commit_thread:
            self._commit_thread.join(timeout=30)

    def drain_commits(self, timeout: float = 30.0) -> None:
        """Block until every dispatched tile has been committed AND
        assumed (a barrier Event rides the queue behind the pending
        tiles). The full-encode path snapshots the modeler's merged
        lister — tiles still queued here are bound-but-unassumed, and
        scheduling against that snapshot would see their capacity as
        free.

        Under the deep pipeline the dispatched-but-unfinalized tile in
        self._prev is NOT in the queue yet: its bindings only enqueue
        when _finalize hands them over, so a barrier queued before that
        handoff would fire with the tile still in flight. The barrier
        therefore rides BEHIND it — on the scheduler thread by
        finalizing it first, elsewhere by waiting for its landed event
        (set after the handoff, so FIFO puts the barrier behind the
        bindings)."""
        deadline = time.monotonic() + timeout
        fl = self._prev
        if fl is not None:
            if threading.current_thread() is self._thread:
                self._finalize_prev()
            else:
                fl.landed.wait(timeout=max(0.0,
                                           deadline - time.monotonic()))
        barrier = threading.Event()
        try:
            self._commit_q.put(barrier, timeout=max(
                0.001, deadline - time.monotonic()))
        except queue.Full:
            return  # committer wedged; the caller's snapshot is stale
                    # either way and the epoch guard catches it
        barrier.wait(timeout=max(0.0, deadline - time.monotonic()))

    def _commit_loop(self) -> None:
        while True:
            item = self._commit_q.get()
            if item is None:
                return
            if isinstance(item, threading.Event):
                # drain barrier: every commit before it has RETURNED —
                # but under NativeStore's publish ring "committed" only
                # means enqueued, so flush the native publisher before
                # firing: drained must keep meaning visible to watchers
                # (in-proc client only; over HTTP there is no handle,
                # and no in-proc snapshot to go stale either)
                store = getattr(getattr(getattr(
                    self.config.factory, "client", None),
                    "registry", None), "store", None)
                flush = getattr(store, "publish_flush", None)
                if flush is not None:
                    try:
                        flush(timeout=5.0)
                    except Exception:
                        pass  # barrier still fires; epoch guard covers
                item.set()  # drain barrier: everything before it landed
                continue
            if self._killed:
                continue  # a dead binder binds nothing (kill())
            if isinstance(item, _Inflight):
                # deep pipeline (scan/commit overlap): the scheduler
                # thread handed over a dispatched-but-unfinalized tile —
                # the blocking np.asarray happens HERE, double-buffered
                # against the next tile's encode/execute on device.
                # _finalize routes its own failures (asarray -> whole
                # tile to error path, commit -> per-pod fallback).
                try:
                    self._finalize(item, on_committer=True)
                except Exception as e:
                    logger.exception("tile finalize failed")
                    for pod in item.pods:
                        try:
                            self._error(pod, e)
                        except Exception:
                            pass
                continue
            try:
                # No tile-wide modeler lock here: the merged lister
                # dedupes scheduled-vs-assumed by key, so bind→assume
                # need not be atomic against the confirm reflector's
                # forgets (a forget racing ahead of the assume leaves a
                # stale assumed entry that list() prunes on sight).
                # Holding the lock across a whole tile starved the
                # reflector's per-event forgets on small-core hosts.
                self._commit(item, inc_assumed=True)
            except Exception as e:
                # _commit routes per-pod failures itself; anything
                # escaping aborted the tile mid-way — route the whole
                # tile to backoff+requeue (error_func re-reads the pod,
                # so already-bound ones are dropped) instead of
                # stranding it Pending
                logger.exception("tile commit failed")
                for pod, _host in item:
                    try:
                        self._error(pod, e)
                    except Exception:
                        pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self._leading.is_set():
                # standby / demoted: land any in-flight tile (its binds
                # are CAS-protected — the new leader's duplicates lose
                # cleanly on one side) and stop draining the FIFO
                self._finalize_prev()
                self._stop.wait(0.02)
                continue
            try:
                busy = self.schedule_tile()
            except Exception:
                # schedule_tile itself routes pod-level failures; anything
                # escaping here would otherwise kill the daemon thread and
                # stall scheduling cluster-wide
                busy = True
            if not busy:
                # idle: land the in-flight tile before parking
                self._finalize_prev()
                self._stop.wait(0.01)
        if not self._killed:
            self._finalize_prev()

    def _drain_tile(self, timeout: float = 0.5) -> List[api.Pod]:
        f = self.config.factory
        pods: List[api.Pod] = []
        # tile queue-wait = the longest per-pod FIFO wait in the drain
        # (fifo.pop stamps last_pop_wait; getattr tolerates the fake
        # queues tests substitute)
        max_wait = 0.0
        q_wait = lambda: getattr(f.pod_queue, "last_pop_wait", 0.0)
        pod = f.pod_queue.pop(timeout=timeout)
        if pod is None:
            self._last_drain_wait = 0.0
            return pods
        max_wait = q_wait()
        pods.append(pod)
        while len(pods) < self.config.tile_size:
            pod = f.pod_queue.pop(timeout=0)
            if pod is None:
                break
            w = q_wait()
            if w > max_wait:
                max_wait = w
            pods.append(pod)
        # Top-up while a tile is in flight: until the device reports the
        # previous assignments ready, dispatching this tile would only
        # queue behind it — so keep accumulating instead. Under a create
        # storm this turns 12 ragged ~2.5k-pod tiles (each padded to a
        # full scan) into 4 full ones (~3x less device work); when the
        # device is idle or the result is already ready, nothing waits.
        prev = self._prev
        if prev is not None and len(pods) < self.config.tile_size:
            ready = getattr(prev.assigned, "is_ready", lambda: True)
            while (len(pods) < self.config.tile_size and not ready()
                   and not self._stop.is_set()):
                # 20ms poll: long enough not to busy-spin the
                # scheduling thread at ~500 wakeups/s against an empty
                # queue for a whole device scan, short enough that the
                # post-ready finalize lags the device by at most one
                # poll (a full-tile scan runs far longer than 20ms)
                pod = f.pod_queue.pop(timeout=0.02)
                if pod is not None:
                    w = q_wait()
                    if w > max_wait:
                        max_wait = w
                    pods.append(pod)
        self._last_drain_wait = max_wait
        return pods

    @staticmethod
    def _chunk_for(c: BatchSchedulerConfig, n: int) -> int:
        # fixed scan-chunk ladder -> stable shapes -> XLA compiles one
        # program per rung. Big drains run as ONE tile-sized dispatch:
        # on an idle chip, small chunks win (tail padding burns scan
        # steps), but in situ — 30 writer threads contending — each
        # extra dispatch re-enters Python behind the GIL, and the
        # measured e2e is ~20% better at chunk=tile than chunk=1024
        if n <= c.min_pad:
            return c.min_pad
        if n <= 2 * c.bulk_chunk:
            return c.bulk_chunk
        return c.tile_size

    def schedule_tile(self) -> bool:
        """Returns True if any pods were processed."""
        c = self.config
        f = c.factory
        if c.shard_monitor is not None:
            # between-tile shard failure detection: the scan itself is
            # never interrupted — an expired shard lease is observed
            # HERE, before the next dispatch
            self._check_shards()
        # with a tile in flight, don't park on the FIFO — an empty drain
        # must fall through so the idle path can finalize promptly
        pods = self._drain_tile(0 if self._prev is not None else 0.5)
        if not pods:
            return False
        if f.rate_limiter is not None:
            for _ in pods:
                f.rate_limiter.accept()
        start = time.monotonic()
        tr = obs.tracer()
        if tr.enabled:
            # "queue" stage, tile-granular: informer delivery -> this
            # drain, per the FIFO's first-enqueue stamps; the first
            # pod's annotation context is the exemplar parent
            tr.record("sched.queue_wait", start - self._last_drain_wait,
                      start, parent=obs.ctx_of(pods[0]), stage="queue",
                      attrs={"pods": len(pods)})

        inc = self._incremental()
        if inc is not None:
            try:
                return self._schedule_incremental(pods, start)
            except NeedsFullEncode:
                pass  # this tile needs the full encoder
            except Exception as e:
                self._fail_tile(pods, e)
                return True

        # full-encode path: strictly ordered after any in-flight tile
        # AND every queued commit (the encoder below reads the modeler's
        # merged lister; assume_pods runs on the committer thread, so
        # tiles still in _commit_q are bound-but-unassumed phantom
        # capacity until the queue drains)
        self._finalize_prev()
        self.drain_commits()
        try:
            chunk = self._chunk_for(c, len(pods))
            # the full node cache (not just ready nodes) resolves
            # existing pods' topology domains for affinity terms,
            # mirroring the serial predicate's node_by_name
            # (ReadyNodeLister.get)
            node_cache = getattr(f.node_lister, "cache", None)
            snap = ClusterSnapshot(
                nodes=f.node_lister.list(),
                existing_pods=f.pod_lister.list(),
                services=f.service_lister.list(),
                controllers=f.controller_lister.list(),
                pending_pods=pods,
                all_nodes=(node_cache.list()
                           if node_cache is not None else None))
            c.metrics.observe("batch_snapshot_latency_microseconds",
                              (time.monotonic() - start) * 1e6)
            t_dev = time.monotonic()
            hosts, _enc = c.engine.schedule(snap, chunk=chunk)
            t_done = time.monotonic()
            c.metrics.observe("batch_device_latency_microseconds",
                              (t_done - t_dev) * 1e6)
            if tr.enabled:
                ctx0 = obs.ctx_of(pods[0])
                tr.record("sched.encode", start, t_dev, parent=ctx0,
                          stage="schedule", attrs={"pods": len(pods)})
                tr.record("sched.device", t_dev, t_done, parent=ctx0,
                          stage="device", attrs={"pods": len(pods)})
        except Exception as e:
            self._fail_tile(pods, e)
            return True
        c.metrics.observe("scheduling_algorithm_latency_microseconds",
                          (time.monotonic() - start) * 1e6)

        scheduled = [(pod, host) for pod, host in zip(pods, hosts)
                     if host is not None]
        unscheduled = [pod for pod, host in zip(pods, hosts) if host is None]

        if self._inc is not None:
            # the incremental ledger exists but this tile went through
            # the full encoder: feed the assumes back one by one
            for pod, host in scheduled:
                self._inc.assume(api.fast_replace(
                    pod, spec=api.fast_replace(pod.spec, node_name=host)))
            self._commit_q.put(scheduled)
        else:
            # policy engines: the encoder reads the modeler's merged
            # lister, so commit stays on this thread to keep the next
            # tile's snapshot ordered after the binds
            f.modeler.locked_action(
                lambda: self._commit(scheduled, inc_assumed=False))

        self._route_unscheduled(unscheduled)
        c.metrics.observe("scheduler_e2e_scheduling_latency_microseconds",
                          (time.monotonic() - start) * 1e6)
        return True

    def _schedule_incremental(self, pods: List[api.Pod],
                              start: float) -> bool:
        """Dispatch one tile through the incremental encoder, chaining
        off the in-flight tile's device carry when provably equivalent;
        the previous tile finalizes (host assume + commit enqueue) while
        this one runs on device — the reference's scheduler->binder
        two-stage pipeline (scheduler.go:120-165), depth 2."""
        c = self.config
        f = c.factory
        inc = self._inc
        chunk = self._chunk_for(c, len(pods))
        # pre-pad the pod axis to a chunk multiple at encode time:
        # run_chunked then slices exact [chunk] pieces and never
        # concatenates under the GIL
        pad = ((len(pods) + chunk - 1) // chunk) * chunk
        services = f.service_lister.list()
        controllers = f.controller_lister.list()
        # spread groups make the device State tile-local (its [G, N]
        # rows are this tile's groups): chain only group-free tiles
        if self._prev is not None and (services or controllers
                                       or inc.groups):
            self._finalize_prev()
        if self._prev is None and not self._ledger_current():
            # about to dispatch from the encoder's init state (nothing
            # to chain off): tiles handed to the committer but not yet
            # assumed would read as free capacity — land them first
            self.drain_commits()
        enc = inc.encode_tile(pods, services, controllers, pad_to=pad)
        c.metrics.observe("batch_snapshot_latency_microseconds",
                          (time.monotonic() - start) * 1e6)
        flags = c.engine._enc_flags(enc)
        prev = self._prev
        chained = False
        t_dev = time.monotonic()
        if prev is not None:
            if (flags == (False, False) and prev.flags == (False, False)
                    and enc.state_epoch == prev.epoch
                    and enc.mem_scale == prev.enc.mem_scale
                    and _carry_compatible(enc, prev.state)):
                # self._prev stays set until the dispatch succeeds — an
                # exception here must not strand the in-flight tile
                assigned, state = c.engine.run_chunked(
                    enc, chunk, state_override=prev.state, block=False)
                chained = True
                self._prev = None
            else:
                # can't chain: land the previous tile (and any older
                # handoffs still with the committer), then re-encode so
                # this tile's init state includes every assume
                self._finalize_prev()
                if not self._ledger_current():
                    self.drain_commits()
                prev = None
                enc = inc.encode_tile(pods, services, controllers,
                                      pad_to=pad)
                flags = c.engine._enc_flags(enc)
        if not chained:
            t_dev = time.monotonic()
            assigned, state = c.engine.run_chunked(enc, chunk, block=False)
        c.metrics.inc("batch_tiles_total",
                      {"chained": str(chained).lower()})
        self._prev = _Inflight(pods=pods, enc=enc, assigned=assigned,
                               state=state, epoch=enc.state_epoch,
                               flags=flags, t_start=start, t_dev=t_dev,
                               shard_epochs=(enc.delta.shard_epochs
                                             if enc.delta is not None
                                             else None))
        tr = obs.tracer()
        if tr.enabled:
            # "schedule" stage ends at device dispatch; the matching
            # "device" span closes in _finalize when the assignments
            # materialize (possibly on the committer thread)
            tr.record("sched.encode", start, t_dev,
                      parent=obs.ctx_of(pods[0]), stage="schedule",
                      attrs={"pods": len(pods),
                             "chained": str(chained).lower()})
        if chained and prev is not None:
            # scan/commit overlap, committer-side double-buffer: hand
            # tile k over UNFINALIZED — the blocking np.asarray (and the
            # bind commit behind it) runs on the committer thread while
            # tile k+1 executes on device and this thread encodes tile
            # k+2. Sound for the same assume-before-bind reason as the
            # commit queue itself; chaining means tile k+1's carry
            # already contains tile k's placements, so the encoder
            # ledger lagging behind the committer's assume_assigned is
            # invisible to chained dispatches (non-chained ones drain
            # via _ledger_current above). Bounded queue = backpressure.
            self._commit_q.put(prev)
            self._last_handed = prev
        return True

    def _ledger_current(self) -> bool:
        """Has every tile handed to the committer been assumed into the
        incremental encoder's ledger? FIFO order: if the most recent
        handoff landed (assume_assigned + commit handed over), every
        earlier one did too."""
        lh = self._last_handed
        return lh is None or lh.landed.is_set()

    def _finalize_prev(self) -> None:
        fl = self._prev
        self._prev = None
        if fl is not None:
            self._finalize(fl)

    def _finalize(self, fl: _Inflight, on_committer: bool = False) -> None:
        """Land a dispatched tile: block on its assignments, assume them
        into the persistent encoder state, hand bindings to the
        committer (or, on the committer thread itself, commit them
        directly — enqueueing into its own bounded queue would
        deadlock), route no-fit pods to backoff. The landed event fires
        once the bindings are queued/committed, whatever path ran —
        it's what drain_commits and _ledger_current key off."""
        c = self.config
        f = c.factory
        inc = self._inc
        delta = getattr(fl.enc, "delta", None)
        if (inc is not None and delta is not None
                and fl.shard_epochs is not None
                and delta.encoder_id == inc.encoder_id
                and inc.shard_epochs() != fl.shard_epochs):
            # shard-epoch fence: a shard owner died (and the mesh
            # re-sharded) after this tile's dispatch. Its assignments
            # were computed against the dead shard's slot mapping —
            # none may bind. Drop the tile whole; its pods requeue
            # FIFO and re-schedule against the survivor mesh. Epochs
            # are compared only within ONE encoder instance
            # (encoder_id): a failover successor's vector is
            # incomparable, and those tiles keep the existing
            # bind-then-reconcile semantics.
            try:
                for pod in fl.pods:
                    try:
                        self._requeue(pod, "mesh",
                                      "re-sharded since dispatch")
                    except Exception:
                        logger.exception("requeue of %s failed",
                                         pod.metadata.name)
            finally:
                fl.landed.set()
            return
        try:
            try:
                assigned = np.asarray(fl.assigned)
            except Exception as e:
                self._fail_tile(fl.pods, e)
                return
            t_done = time.monotonic()
            c.metrics.observe("batch_device_latency_microseconds",
                              (t_done - fl.t_dev) * 1e6)
            tr = obs.tracer()
            if tr.enabled:
                tr.record("sched.device", fl.t_dev, t_done,
                          parent=obs.ctx_of(fl.pods[0]), stage="device",
                          attrs={"pods": len(fl.pods)})
            enc = fl.enc
            idx = assigned[: enc.n_pods]
            names = enc.node_names
            scheduled: List[Tuple[api.Pod, str]] = []
            unscheduled: List[api.Pod] = []
            for j, pod in enumerate(fl.pods):
                i = idx[j]
                if i >= 0:
                    scheduled.append((pod, names[i]))
                else:
                    unscheduled.append(pod)
            c.metrics.observe("scheduling_algorithm_latency_microseconds",
                              (time.monotonic() - fl.t_start) * 1e6)
            try:
                # self._inc can be None mid-failover (_on_started_leading
                # discards it); the tile still binds — the fresh encoder's
                # bootstrap re-list covers its capacity
                if self._inc is not None:
                    self._inc.assume_assigned(enc, fl.pods, idx)
            except Exception:
                # the slow path inside assume_assigned is the robust one;
                # anything escaping means the ledger may be torn for this
                # tile — scheduling continues (the watch echo reconciles),
                # binds still commit
                logger.exception("assume_assigned failed")
            if on_committer:
                try:
                    self._commit(scheduled, inc_assumed=True)
                except Exception as e:
                    # same whole-tile error routing as _commit_loop's
                    # list path: error_func re-reads, bound pods drop out
                    logger.exception("tile commit failed")
                    for pod, _host in scheduled:
                        try:
                            self._error(pod, e)
                        except Exception:
                            pass
            else:
                self._commit_q.put(scheduled)
        finally:
            fl.landed.set()
        self._route_unscheduled(unscheduled)
        c.metrics.observe("scheduler_e2e_scheduling_latency_microseconds",
                          (time.monotonic() - fl.t_start) * 1e6)

    def _route_unscheduled(self, unscheduled: List[api.Pod]) -> None:
        """Per-pod robust: _finalize may run while a LATER tile is
        already dispatched and registered in _prev — an exception
        escaping here would be caught by schedule_tile's handler and
        error-requeue that tile's pods even though it still lands,
        double-processing them."""
        f = self.config.factory
        for pod in unscheduled:
            try:
                if self._try_preempt(pod):
                    continue
                err = FitError(pod, {})
                if f.recorder is not None:
                    f.recorder.eventf(pod, "Warning", "FailedScheduling",
                                      str(err))
                self._error(pod, err)
            except Exception:
                logger.exception("routing unscheduled pod failed")

    def _try_preempt(self, pod: api.Pod) -> bool:
        """Priority preemption for one unschedulable pod (the tentpole
        wiring; selection rule + wrongful-eviction invariants in
        sched/preemption.py). Returns True when the pod was handled —
        requeued FIFO after evicting its victim set, after finding
        freed capacity, or while a prior round's victims drain — and
        False to fall through to the plain error path.

        Ordering invariant: the preemptor is NEVER bound here. It
        requeues FIFO and binds on a later tile, which only sees the
        victims' capacity once their DELETE echoes journal the release
        into the encoder — no optimistic double-booking. Evictions are
        uid-preconditioned graceful deletes (the PR-5 _evict_pods
        contract: Conflict means a same-name replacement won the name,
        NotFound means someone else finished the job), and the whole
        round is fenced on the shard-epoch vector captured with the
        victim table — a mid-preemption reshard drops the victim set
        instead of evicting against stale capacity."""
        c = self.config
        pre = c.preemption
        inc = self._inc
        if pre is None or inc is None:
            return False
        from .preemption import PreemptionDecision, preemptor_eligible
        if not preemptor_eligible(pod):
            # ports/volumes/affinity: predicates the victim search does
            # not model — preempting for this pod could be wrongful
            return False
        f = c.factory
        c.metrics.inc("preemption_attempts_total")
        try:
            table = inc.victim_table(pod)
            # nominated nodes have draining victims another preemptor
            # already claimed: masking them spreads a burst of
            # preemptors across distinct nodes instead of serializing
            # one grace period per pod on the argmax node. The pod's
            # OWN nomination stays visible (exclude_uid): its draining
            # node re-selects the identical victim set and the cooldown
            # hold — not a second eviction elsewhere — handles it
            nominated = pre.nominated_nodes(
                exclude_uid=pod.metadata.uid)
            masked = False
            if nominated:
                for j, nm in enumerate(table.node_names):
                    if nm in nominated and table.cand[j]:
                        table.cand[j] = False
                        masked = True
            res = c.engine.find_victims(table)
        except Exception:
            logger.exception("victim search failed")
            return False
        if not res.feasible:
            if masked:
                # only the nomination mask stood between this pod and a
                # victim set: stay hot in the FIFO (priority pop keeps
                # the preemptor ahead of the batch backlog) instead of
                # paying the error path's escalating backoff while the
                # other preemptors' capacity frees
                self._requeue(pod, "mesh", "all victim nodes nominated")
                return True
            return False  # no victim set helps: plain error path
        node = table.node_names[res.pick]
        victims = res.victim_keys(table)
        if res.kstar <= 0 or not victims:
            # a feasible NON-preempting node exists right now (capacity
            # freed since the scan failed): wrongful-eviction rule 2
            # says never evict here — plain immediate requeue
            self._requeue(pod, node, "has free capacity; no preemption")
            return True
        vkey = pre.vset_key(node, victims)
        if pre.blocked(pod, vkey):
            # same victim set inside its cooldown window (a prior round
            # evicted it and the terminations haven't journaled, or a
            # delete lost a race): requeue FIFO, do NOT re-evict
            self._requeue(pod, node, "awaiting preempted capacity")
            return True
        if (table.encoder_id != inc.encoder_id
                or inc.shard_epochs() != table.shard_epochs):
            # reshard (or encoder swap) since the table was cut: the
            # victim set was computed against a dead shard's mapping
            self._requeue(pod, "mesh", "re-sharded during victim search")
            return True
        evicted = 0
        struck = False
        for ns, name, uid in victims:
            try:
                f.client.delete("pods", name, ns,
                                grace_period_seconds=(
                                    pre.grace_period_seconds),
                                uid=uid or None)
            except (NotFound, Conflict):
                # the victim moved under us — the remaining prefix was
                # chosen assuming this one's release, so stop the round
                struck = True
                break
            except Exception:
                struck = True
                break
            evicted += 1
            c.metrics.inc("preemption_victims_total")
        if f.recorder is not None:
            f.recorder.eventf(
                pod, "Normal", "Preempting",
                f"evicting {evicted}/{len(victims)} lower-priority "
                f"pods on {node}")
        pre.record(PreemptionDecision(
            pod_key=(pod.metadata.namespace, pod.metadata.name),
            pod_uid=pod.metadata.uid, prio=table.prio, node=node,
            pick=res.pick, kstar=res.kstar,
            score=int(res.node_score[res.pick]), victims=victims,
            table=table, state_epoch=table.state_epoch,
            shard_epochs=table.shard_epochs, evicted=evicted,
            t=pre.now()))
        if evicted:
            pre.nominate(node, uid=pod.metadata.uid)
        pre.hold(pod, vkey, escalate=struck)
        self._requeue(pod, node,
                      "victim moved; preemption cooling down" if struck
                      else f"preempted {evicted} pods; will bind after "
                           f"release is journaled")
        return True

    def _fail_tile(self, pods: List[api.Pod], e: Exception) -> None:
        """Encode/device failure: the tile is already drained from the
        FIFO, so every pod must take the error path (backoff+requeue)
        like the serial loop's algorithm failures (scheduler.go:129)."""
        f = self.config.factory
        for pod in pods:
            try:
                if f.recorder is not None:
                    f.recorder.eventf(pod, "Warning", "FailedScheduling",
                                      str(e))
                self._error(pod, e)
            except Exception:
                logger.exception("error-routing pod failed")

    def _check_shards(self) -> None:
        """Shard-failure recovery, scheduler-thread only: poll the
        shard lease monitor; on expiry, fence the dead owner (CAS
        takeover advancing lease_transitions — a resurrecting owner
        loses every subsequent CAS), re-shard the slot mapping onto the
        survivors (encoder re-journals + re-epochs, engine rebuilds
        over the survivor mesh), and drop the in-flight tile — it was
        dispatched against the dead shard's epoch, so its assignments
        must never bind. Its pods requeue FIFO, the same immediate
        no-backoff path as the commit-time health gate (PR 5), now at
        shard granularity."""
        from .device.shardfail import reshard_survivors
        c = self.config
        dead = c.shard_monitor.poll()
        if not dead:
            return
        res = reshard_survivors(dead, c.shard_monitor, encoder=self._inc,
                                engine=c.engine, metrics=c.metrics)
        if res is None:
            return  # every fence lost: the owners renewed after all
        logger.warning("shard(s) %s expired: fenced (terms %s), "
                       "re-sharded onto %d survivors, %d rows replayed",
                       res.dead, res.fence_terms, res.survivors,
                       res.replay_rows)
        fl = self._prev
        self._prev = None
        if fl is not None:
            try:
                for pod in fl.pods:
                    try:
                        self._requeue(pod, f"shard-{res.dead[0]}",
                                      "lease expired mid-tile")
                    except Exception:
                        logger.exception("requeue of %s failed",
                                         pod.metadata.name)
            finally:
                fl.landed.set()

    def _target_alive(self, host: str) -> bool:
        """Is the bind target still a live node RIGHT NOW, per the node
        informer cache? The scan decided with encode-time knowledge; a
        node can go NotReady/Unknown, get cordoned, or vanish between
        scan and commit — binding to it anyway starts the bind -> evict
        -> recreate -> rebind-to-the-corpse loop the NodeController
        then has to fight."""
        cache = getattr(self.config.factory.node_lister, "cache", None)
        if cache is None:
            return True
        node = cache.get_by_key(host)
        return node is not None and node_schedulable(node)

    def _requeue(self, pod: api.Pod, host: str, reason: str) -> None:
        """Immediate requeue, no error backoff: the pod did nothing
        wrong — its target died (or a racing write collided) between
        scan and commit. The FIFO re-add re-schedules it against the
        post-death mask on the very next tile."""
        f = self.config.factory
        if f.recorder is not None:
            f.recorder.eventf(pod, "Normal", "SchedulingRequeued",
                              f"node {host} {reason}; pod requeued")
        self.config.metrics.inc("batch_commit_requeues_total")
        f.pod_queue.add(pod)

    def _bind_failed(self, pod: api.Pod, host: str, err: Exception) -> None:
        """A per-pod CAS bind was rejected. Re-read the pod: still
        unbound -> requeue it NOW for a fresh placement instead of
        paying the error path's 1s->60s backoff; already bound (a
        racing scheduler won) or deleted -> done is done; the re-read
        itself failing -> the classic error path (backoff + requeue)."""
        f = self.config.factory
        try:
            fresh = f.client.get("pods", pod.metadata.name,
                                 pod.metadata.namespace)
        except NotFound:
            return
        except Exception:
            self._error(pod, err)
            return
        if fresh.spec.node_name:
            return
        self._requeue(fresh, host, f"rejected the bind ({err})")

    def _commit(self, scheduled: List[Tuple[api.Pod, str]],
                inc_assumed: bool) -> None:
        """Bind a tile (batched CAS, per-pod fallback), record events,
        and assume into the modeler. The committer thread runs this
        lock-free (assume_pods takes the modeler lock once at the end;
        a confirm-reflector forget racing ahead of it wins via the
        modeler's tombstones); only the policy-engine path still wraps
        it in locked_action for snapshot ordering."""
        c = self.config
        f = c.factory
        # commit-time health gate: a target that went NotReady/Unknown,
        # cordoned, or deleted since the scan gets its pods requeued
        # rather than bound to a corpse (the incremental assume is
        # corrected by the watch echo once the pod binds elsewhere)
        live: List[Tuple[api.Pod, str]] = []
        for pod, host in scheduled:
            if self._target_alive(host):
                live.append((pod, host))
            else:
                try:
                    self._requeue(pod, host, "went unschedulable")
                except Exception:
                    logger.exception("requeue of %s failed",
                                     pod.metadata.name)
        scheduled = live
        # columnar commit: (ns, name, host) rows, no Binding carrier
        # objects on the hot path (client.bind_batch_hosts expands them
        # only for wire transports)
        rows = [(p.metadata.namespace, p.metadata.name, h)
                for p, h in scheduled]
        bind_start = time.monotonic()
        committed: List[bool] = [False] * len(rows)
        tr = obs.tracer()
        bind_span = obs.NOOP
        if tr.enabled and rows:
            # "bind" stage, tile-granular; installed as current context
            # so the client's http spans and the store's txn spans nest
            # under it
            bind_span = tr.start_span(
                "sched.bind", parent=obs.ctx_of(scheduled[0][0]),
                stage="bind", attrs={"pods": len(rows)}, start=bind_start)
        # whole-tile commit by default (commit_chunk=0): the registry
        # routes one multi-key transaction per tile — one ledger-lock
        # acquisition, one WAL frame, one publish fan-out — so the
        # per-chunk lock/WAL/publish overheads that made 1024 the
        # pre-txn sweet spot (the A/B that kept 1024 ahead of 2048 at
        # 5000x30000: shorter ledger windows interleaved the
        # reflector/status consumers better) are paid once, not
        # ceil(tile/1024) times. A positive commit_chunk restores the
        # bounded sub-batch loop as the --txn-ab control arm; either
        # way each call keeps all-or-nothing CAS semantics and the
        # per-pod fallback scopes a conflict to its sub-batch.
        commit_chunk = c.commit_chunk or max(1, len(rows))
        try:
            with obs.use(bind_span):
                for lo in range(0, len(rows), commit_chunk):
                    part = rows[lo:lo + commit_chunk]
                    try:
                        f.client.bind_batch_hosts(part)
                        committed[lo:lo + len(part)] = [True] * len(part)
                    except Exception:
                        # sub-batch failed (e.g. a pod got bound by
                        # another scheduler mid-flight): degrade to
                        # per-pod CAS so one conflict doesn't waste the
                        # rest
                        for i, (ns, name, host) in enumerate(part,
                                                             start=lo):
                            try:
                                f.client.bind(api.Binding(
                                    metadata=api.ObjectMeta(namespace=ns,
                                                            name=name),
                                    target=api.ObjectReference(
                                        kind="Node", name=host)))
                                committed[i] = True
                            except Exception as e:
                                pod = scheduled[i][0]
                                if f.recorder is not None:
                                    f.recorder.eventf(
                                        pod, "Normal", "FailedScheduling",
                                        f"Binding rejected: {e}")
                                self._bind_failed(pod, host, e)
        finally:
            tr.end(bind_span)
        c.metrics.observe("binding_latency_microseconds",
                          (time.monotonic() - bind_start) * 1e6)
        to_assume = []
        for ok, (pod, host) in zip(committed, scheduled):
            if not ok:
                continue
            if f.recorder is not None:
                f.recorder.eventf(
                    pod, "Normal", "Scheduled",
                    f"Successfully assigned {pod.metadata.name} to {host}")
            assumed = api.fast_replace(
                pod, spec=api.fast_replace(pod.spec, node_name=host))
            to_assume.append(assumed)
            if self._inc is not None and not inc_assumed:
                # count the binding into the persistent device state
                # now; the watch echo dedupes via the ledger
                self._inc.assume(assumed)
        f.modeler.assume_pods(to_assume)

    def _error(self, pod: api.Pod, err: Exception) -> None:
        self.config.factory.error_func(pod, err)
