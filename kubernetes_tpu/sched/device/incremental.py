"""Incremental device-state encoder: watch deltas -> persistent arrays.

SURVEY.md section 7 hard part 4: the full encoder (tables.encode_snapshot)
re-walks every node and every existing pod for every tile, so per-tile
host cost grows with cluster size — the serial MapPodsToMachines
pathology (predicates.go:445) reborn on the host. This encoder instead
maintains the Struct-of-Arrays cluster state persistently and applies
watch-stream deltas (the reference's reflector feed,
client/cache/reflector.go:225) plus the scheduler's own assume() calls,
so encoding a tile costs O(tile), independent of cluster size.

Fidelity contract (vs tables.encode_snapshot, which remains the oracle
for parity tests):
  - aggregates, bitsets, and spread counts are maintained to the same
    definitions: resource sums replay CheckPodsExceedingFreeResources'
    skip-on-misfit accounting (predicates.go:160-185), nonzero-request
    sums (priorities.go:53-54), selector-spread groups over the
    UNfiltered pod set (selector_spreading.go:43-114), MapPodsToMachines'
    Succeeded/Failed phase filter for resource state (predicates.go:429).
  - deliberate divergence: the misfit replay runs in event ARRIVAL order,
    not snapshot list order. The two only differ when a node is
    oversubscribed with a mix of fitting and misfitting pods whose order
    matters; the full encoder stays authoritative for that edge and the
    parity suite pins it.
  - scope: the default provider tier plus the inter-pod affinity tier
    (terms/domains/scope-counts computed per tile from the LEDGER —
    one pass over cheap records, not the full O(cluster) re-encode).
    Engines configured with a DevicePolicy needing anti-affinity (zone
    spreading) should not use this path.

Shape stability: node capacity and interner word capacities grow by
doubling, so array shapes — and therefore XLA compilations — change
O(log) times over a cluster's life, not per tile.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ...core import types as api
from ..modeler import ASSUMED_POD_TTL
from ..predicates import get_resource_request, node_schedulable
from ..priorities import get_nonzero_requests
from .tables import (WORD, EncodeResult, NodeArrays, PodArrays, StateArrays,
                     TableDelta, _disk_keys, _matching_services,
                     _pod_spread_selectors, _selector_matches, _set_bit,
                     _words, collect_affinity_terms)


class NeedsFullEncode(Exception):
    """Tile needs a feature this encoder doesn't maintain incrementally.

    Currently raised by NO tier (the affinity tier, the last holdout,
    went ledger-fed) — kept as the escape-hatch contract: a future tier
    may raise it and the batch scheduler's handler (sched/batch.py)
    routes such tiles through the full snapshot encoder."""


def replace_pod_batch_dtypes(pb: PodArrays, narrow: bool,
                             mem_scale: int) -> PodArrays:
    """Narrow a freshly-built pod batch's resource arrays in place
    (the tile arrays are private to this encode call)."""
    if not narrow:
        return pb
    pb.req_cpu = pb.req_cpu.astype(np.int32)
    pb.nz_cpu = pb.nz_cpu.astype(np.int32)
    pb.req_mem = (pb.req_mem // mem_scale).astype(np.int32)
    pb.nz_mem = (pb.nz_mem // mem_scale).astype(np.int32)
    return pb


def _grow(arr: np.ndarray, axis: int, new_len: int) -> np.ndarray:
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, new_len - arr.shape[axis])
    return np.pad(arr, pad)


# process-wide encoder identity (TableDelta.encoder_id): never reused
# within a process, unlike id()
_ENCODER_ID_NEXT = 1
_ENCODER_ID_LOCK = threading.Lock()


class _GrowingInterner:
    """String->bit-index dictionary with a word capacity that doubles;
    exposes the current padded word count so bitset shapes stay stable
    between growths."""

    def __init__(self, min_words: int = 1):
        self.ids: Dict[object, int] = {}
        self.words = min_words

    def intern(self, key: object) -> Tuple[int, bool]:
        """-> (bit index, grew) — grew means bitset arrays must widen."""
        idx = self.ids.get(key)
        if idx is not None:
            return idx, False
        idx = len(self.ids)
        self.ids[key] = idx
        if _words(len(self.ids)) > self.words:
            self.words *= 2
            return idx, True
        return idx, False


class _Group:
    """One selector-spread group (ns, selector set): per-node counts plus
    the off-table bucket (unassigned '' / unknown hosts)."""

    __slots__ = ("ns", "sels", "row", "offgrid")

    def __init__(self, ns: str, sels: List[Dict[str, str]], cap: int):
        self.ns = ns
        self.sels = sels
        self.row = np.zeros(cap, np.int32)
        self.offgrid: Dict[str, int] = {}

    def matches(self, ns: str, labels: Dict[str, str]) -> bool:
        return ns == self.ns and any(
            _selector_matches(s, labels) for s in self.sels)


class _PodRecord:
    __slots__ = ("rv", "node", "slot", "ns", "labels", "counted_res",
                 "misfit", "req_cpu", "req_mem", "nz_cpu", "nz_mem",
                 "ports", "disks", "priority", "uid")

    def __init__(self):
        self.rv = ""
        self.node = ""
        self.slot: Optional[int] = None
        self.ns = ""
        self.labels: Dict[str, str] = {}
        self.counted_res = False   # phase not Succeeded/Failed at count time
        self.misfit: Optional[str] = None   # 'cpu' | 'mem' | None
        self.req_cpu = 0
        self.req_mem = 0
        self.nz_cpu = 0
        self.nz_mem = 0
        self.ports: List[int] = []
        self.disks: List[Tuple[int, bool, bool]] = []  # (bit, any_q, rw)
        # preemption columns: the victim search orders candidates by
        # priority and evicts by uid-preconditioned delete (sched/
        # preemption.py) — both must come from the record, not a re-read
        self.priority = 0
        self.uid = ""


class IncrementalEncoder:
    """Persistent cluster arrays fed by pod/node watch deltas."""

    def __init__(self, node_capacity: int = 64, policy=None,
                 mesh_devices: int = 1):
        """policy: a DevicePolicy whose NODE-STATIC tiers (label
        presence/priorities) are maintained incrementally; the
        anti-affinity tier needs per-tile service groups and stays with
        the full encoder (callers must not pass one that needs it).

        mesh_devices: shard count of the engine this encoder feeds. The
        node capacity rounds up to a multiple of it — here and on every
        growth — so the device node axis always splits evenly across
        the mesh without a caller-side pad, and a slot's shard
        assignment (block sharding over stable slots) never moves
        except at a capacity growth, which invalidates the device
        table cache wholesale anyway."""
        if policy is not None and policy.needs_anti_affinity:
            raise ValueError(
                "IncrementalEncoder: anti-affinity policies need the "
                "full per-tile encoder")
        self._policy = policy
        self.mesh_devices = max(1, int(mesh_devices))
        node_capacity = -(-max(1, node_capacity) // self.mesh_devices) \
            * self.mesh_devices
        self._lock = threading.RLock()
        # interners shared across the encoder's life
        self.labels_dict = _GrowingInterner()
        self.ports_dict = _GrowingInterner()
        self.disk_dict = _GrowingInterner()
        # spec-identity -> spec-derived record fields (columnar creates
        # share one spec across a batch; see _build_record)
        self._spec_memo: Dict[int, tuple] = {}

        # ---- node table (slot-stable: a node keeps its index for life) --
        self.n_cap = node_capacity
        self.node_slot: Dict[str, int] = {}
        self.node_names: List[str] = [""] * self.n_cap
        # raw label dicts per slot: the affinity tier resolves topology
        # domains from them (kept for INVALID slots too — a peer pod on
        # a cached-but-unschedulable node still occupies its domain,
        # the serial predicate's node_by_name view)
        self.node_labels: List[Dict[str, str]] = [
            {} for _ in range(self.n_cap)]
        self._free_slots: List[int] = []
        self._next_slot = 0   # high-water mark: len(node_slot) stops
                              # being the next-free index once slots
                              # are ever reclaimed
        # valid: slot is occupied by a known node; sched_ok: that node is
        # a live binding target (predicates.node_schedulable — Ready, not
        # Unknown, not cordoned). The engine masks on valid & sched_ok,
        # so a NotReady node keeps its slot (its pods keep counting into
        # spread rows and topology domains, the serial node_by_name view)
        # but never receives a binding. A condition flip arrives as a
        # node update -> _node_upsert bumps state_epoch, which retires
        # the node from any in-flight device carry (the batch scheduler
        # refuses to chain across an epoch change and re-encodes).
        self.valid = np.zeros(self.n_cap, bool)
        self.sched_ok = np.zeros(self.n_cap, bool)
        self.cpu_cap = np.zeros(self.n_cap, np.int64)
        self.mem_cap = np.zeros(self.n_cap, np.int64)
        self.pod_cap = np.zeros(self.n_cap, np.int32)
        self.label_words = np.zeros((self.n_cap, 1), np.uint32)
        self.tie_rank = np.full(self.n_cap, -1, np.int32)
        self._tie_dirty = False
        # node-static policy tiers (CheckNodeLabelPresence /
        # CalculateNodeLabelPriority), recomputed per node at upsert
        self.static_mask = np.ones(self.n_cap, bool)
        self.static_score = np.zeros(self.n_cap, np.int64)

        # ---- per-node aggregates (the State init the engine consumes) --
        self.cpu_used = np.zeros(self.n_cap, np.int64)
        self.mem_used = np.zeros(self.n_cap, np.int64)
        self.nz_cpu = np.zeros(self.n_cap, np.int64)
        self.nz_mem = np.zeros(self.n_cap, np.int64)
        self.pod_count = np.zeros(self.n_cap, np.int32)
        self.port_bits = np.zeros((self.n_cap, 1), np.uint32)
        self.disk_any = np.zeros((self.n_cap, 1), np.uint32)
        self.disk_rw = np.zeros((self.n_cap, 1), np.uint32)
        self.exceed_cpu = np.zeros(self.n_cap, bool)
        self.exceed_mem = np.zeros(self.n_cap, bool)

        # i32 narrowing metadata (tables._maybe_narrow's contract): the
        # HOST arrays stay raw i64 — only the per-tile device copies are
        # divided by the running gcd and cast when provably exact. The
        # gcd is monotone (only shrinks), so no rescaling ever happens.
        self._mem_gcd = 0
        self._mem_cap_max = 0
        self._mem_req_max = 0
        self._cpu_cap_max = 0
        self._cpu_req_max = 0

        # ---- ledgers --
        self.pods: Dict[str, _PodRecord] = {}
        # per-slot insertion-ordered pod keys (replay order for misfit
        # recompute); unknown-host pods parked by node name
        self.node_pods: Dict[int, List[str]] = {}
        self.unknown_node_pods: Dict[str, Set[str]] = {}
        self.groups: Dict[object, _Group] = {}
        # delete tombstones, keyed (ns/name, uid) like the modeler's
        # (modeler.py _forgotten): a DELETED event that lands BEFORE the
        # committer's assume for the same pod must win, or the assume
        # re-adds a ledger record no future event will ever remove —
        # phantom capacity and an entry leaked for the process lifetime
        # (the 5k-node soak caught ~1-in-54k churned pods doing exactly
        # this under heavy GIL contention). uid-scoped so a recreated
        # same-name pod assumes normally.
        self._del_tombstones: Dict[Tuple[str, str], float] = {}
        self._del_order: deque = deque()

        # ---- device-carry bookkeeping (the pipelined scheduler chains
        # tile k+1's scan off tile k's on-device final state; that's
        # sound only while the host arrays stay bit-equal to what the
        # device carry represents). state_epoch bumps on ANY mutation of
        # the node aggregate state except assume_assigned's own
        # vectorized updates — those match the device scan's one-hot
        # updates exactly, so they keep host == carry.
        self.state_epoch = 0
        # worst-case pods already in flight on device but not yet
        # assumed host-side: _narrow_params must budget for them
        self.inflight_pad = 0

        # ---- dirty-slot journal for the engine's device-resident table
        # cache (tables.TableDelta / engine._TableCache). _table_gen is a
        # monotonic mutation counter; the two per-slot arrays record the
        # counter value at each slot's last change, split by which device
        # table the change lands in: NodeConst rows (caps, labels, tie
        # rank, schedulability, misfit flags) move only on node events
        # and misfits, while State rows (running sums, bitsets) move on
        # every pod event — including assume_assigned's fast path, which
        # deliberately does NOT bump state_epoch (the device carry
        # already holds those updates) but DOES journal here (the cached
        # State init mirror does not). _full_dirty_gen marks the last
        # whole-table invalidation: capacity growth reshapes — and
        # therefore re-shards — every array.
        self._table_gen = 0
        self._node_dirty_gen = np.zeros(self.n_cap, np.int64)
        self._state_dirty_gen = np.zeros(self.n_cap, np.int64)
        self._full_dirty_gen = 0
        # epoch-per-shard: one counter per mesh shard, stamped into
        # every TableDelta. The slot->shard mapping is block sharding
        # over stable slots, so an epoch moves ONLY when that mapping
        # moves — reshard() (survivor re-shard after a shard owner
        # dies) replaces the vector wholesale. The engine's table cache
        # and the batch scheduler's in-flight fencing both compare the
        # whole vector: a tile encoded against a dead shard's epoch can
        # neither reuse the mirror nor commit its bindings.
        self._shard_epochs: Tuple[int, ...] = (0,) * self.mesh_devices
        # instance token stamped into every TableDelta: generations from
        # two encoders are incomparable (see tables.TableDelta), and
        # id() can be recycled after gc — a process-wide counter cannot
        with _ENCODER_ID_LOCK:
            global _ENCODER_ID_NEXT
            self._encoder_id = _ENCODER_ID_NEXT
            _ENCODER_ID_NEXT += 1

    def _mark_node(self, slots) -> None:
        """Caller holds the lock. Journal NodeConst-side change(s) at a
        fresh generation (scalar int or integer array)."""
        self._table_gen += 1
        self._node_dirty_gen[slots] = self._table_gen

    def _mark_state(self, slots) -> None:
        """Caller holds the lock. Journal State-side change(s)."""
        self._table_gen += 1
        self._state_dirty_gen[slots] = self._table_gen

    def _mark_full(self) -> None:
        self._table_gen += 1
        self._full_dirty_gen = self._table_gen

    # ================================================== watch delta feed

    def on_pod_add(self, pod: api.Pod) -> None:
        with self._lock:
            self._pod_upsert(pod)

    def on_pod_update(self, old: api.Pod, new: api.Pod) -> None:
        with self._lock:
            self._pod_upsert(new)

    # the SAME window as the modeler's forget tombstones — the two
    # solve one race at two ledgers and must not drift apart
    _DEL_TOMBSTONE_TTL = ASSUMED_POD_TTL

    def on_pod_delete(self, pod: api.Pod) -> None:
        with self._lock:
            key = f"{pod.metadata.namespace}/{pod.metadata.name}"
            now = time.monotonic()
            tkey = (key, pod.metadata.uid)
            self._del_tombstones[tkey] = now
            self._del_order.append((now, tkey))
            ttl = self._DEL_TOMBSTONE_TTL
            order = self._del_order
            while order and now - order[0][0] > ttl:
                ts, k = order.popleft()
                if self._del_tombstones.get(k) == ts:
                    del self._del_tombstones[k]
            rec = self.pods.pop(key, None)
            if rec is not None:
                self._remove_record(key, rec)

    def _deleted_recently(self, key: str, uid: str) -> bool:
        """Caller holds the lock. True while the pod's DELETED event is
        within the tombstone window — an assume arriving now lost the
        race and must not resurrect the ledger entry."""
        ts = self._del_tombstones.get((key, uid))
        return (ts is not None
                and time.monotonic() - ts <= self._DEL_TOMBSTONE_TTL)

    def assume(self, pod: api.Pod) -> None:
        """Count a just-bound pod before the watch confirms it (the
        modeler.AssumePod moment, modeler.go:113). A pod whose DELETED
        event already landed is NOT resurrected (same rule as the
        modeler's forget tombstones)."""
        with self._lock:
            key = f"{pod.metadata.namespace}/{pod.metadata.name}"
            if self._deleted_recently(key, pod.metadata.uid):
                # a device carry may have counted this pod: re-encode
                # from host truth rather than chaining
                self.state_epoch += 1
                return
            self._pod_upsert(pod)

    def assume_assigned(self, enc: EncodeResult, pods: List[api.Pod],
                        assigned: np.ndarray) -> None:
        """Vectorized assume for a whole scheduled tile.

        `enc` is the EncodeResult this encoder produced for `pods`
        (row j <-> pods[j]); `assigned` is the engine's output (node slot
        or -1 per row). The tile arrays already hold every quantity a
        ledger record needs, so the per-pod spec re-walk assume() would
        do — measured at 20-30us/pod under benchmark load, serialized on
        the scheduler thread — collapses into O(tile) numpy scatter-adds
        plus cheap record construction.

        Fast-path exactness: when no mutation landed since the encode
        (state_epoch unchanged), the device verified every assignment's
        fit sequentially against state identical to the host arrays, so
        _apply_record's misfit branch provably cannot trigger and the
        batched scatter-adds commute to the same result as ordered
        replay. The updates then equal the device scan's one-hot updates
        exactly — which is what keeps the host arrays bit-equal to a
        chained device carry — so the fast path deliberately does NOT
        bump state_epoch. Pods the fast path can't express (host ports,
        disk volumes, an existing ledger record, a non-Pending phase)
        take the slow per-pod path, which does. If the epoch moved, the
        whole tile replays through the slow path."""
        pb = enc.pod_batch
        scale = enc.mem_scale
        with self._lock:
            p = enc.n_pods
            fast_ok = (enc.state_epoch >= 0
                       and self.state_epoch == enc.state_epoch)
            # numpy scalar indexing in a tight loop costs ~10x a list
            # index: lift everything the loop reads into Python lists
            assigned_l = np.asarray(assigned[:p]).tolist()
            ports_any_l = pb.port_words[:p].any(axis=1).tolist()
            disks_any_l = pb.disk_sany[:p].any(axis=1).tolist()
            req_cpu_l = pb.req_cpu[:p].tolist()
            req_mem_l = pb.req_mem[:p].tolist()
            nz_cpu_l = pb.nz_cpu[:p].tolist()
            nz_mem_l = pb.nz_mem[:p].tolist()
            tile_set = enc.tile_groups or []
            other_groups = [g for g in self.groups.values()
                            if g not in tile_set]
            ledger = self.pods
            node_names = self.node_names
            node_pods = self.node_pods
            fast_rows: List[int] = []
            for j in range(p):
                slot = assigned_l[j]
                if slot < 0:
                    continue
                pod = pods[j]
                meta = pod.metadata
                key = f"{meta.namespace}/{meta.name}"
                if self._del_tombstones and \
                        self._deleted_recently(key, meta.uid):
                    # the pod was bound, confirmed AND deleted before
                    # this finalize ran — re-adding it would leak a
                    # ledger record no future event removes. The device
                    # carry counted the pod, the host (correctly) does
                    # not: break the chain so the next tile re-encodes
                    # from host truth.
                    self.state_epoch += 1
                    continue
                if (not fast_ok or ports_any_l[j] or disks_any_l[j]
                        or key in ledger
                        or pod.status.phase in (api.POD_SUCCEEDED,
                                                api.POD_FAILED)):
                    # slow path: full record build + misfit replay
                    # (bumps state_epoch -> the device carry resyncs)
                    self._pod_upsert(api.fast_replace(
                        pod, spec=api.fast_replace(
                            pod.spec, node_name=node_names[slot])))
                    continue
                rec = _PodRecord()
                rec.rv = meta.resource_version or ""
                rec.node = node_names[slot]
                rec.slot = slot
                rec.ns = meta.namespace
                rec.labels = dict(meta.labels)
                rec.counted_res = True
                rec.priority = pod.spec.priority
                rec.uid = meta.uid
                rec.req_cpu = req_cpu_l[j]
                rec.req_mem = req_mem_l[j] * scale
                rec.nz_cpu = nz_cpu_l[j]
                rec.nz_mem = nz_mem_l[j] * scale
                ledger[key] = rec
                lst = node_pods.get(slot)
                if lst is None:
                    node_pods[slot] = [key]
                else:
                    lst.append(key)
                fast_rows.append(j)
                # groups outside this tile may also select the pod
                # (overlapping service selectors): _apply_record checks
                # every group, so must the fast path
                for g in other_groups:
                    if g.matches(rec.ns, rec.labels):
                        g.row[slot] += 1
            if not fast_rows:
                return
            rows = np.asarray(fast_rows, np.int64)
            slots = assigned[rows].astype(np.int64)
            # no state_epoch bump (the device carry already holds these
            # updates) but the cached State init mirror does not: journal
            # the touched slots so the next non-chained dispatch
            # re-uploads exactly these rows
            self._mark_state(slots)
            np.add.at(self.pod_count, slots, 1)
            np.add.at(self.cpu_used, slots, pb.req_cpu[rows])
            np.add.at(self.mem_used, slots,
                      pb.req_mem[rows].astype(np.int64) * scale)
            np.add.at(self.nz_cpu, slots, pb.nz_cpu[rows])
            np.add.at(self.nz_mem, slots,
                      pb.nz_mem[rows].astype(np.int64) * scale)
            for gid, g in enumerate(tile_set):
                members = rows[pb.member[rows, gid] == 1]
                if members.size:
                    np.add.at(g.row, assigned[members].astype(np.int64), 1)

    def on_node_add(self, node: api.Node) -> None:
        with self._lock:
            self._node_upsert(node)

    def on_node_update(self, old: api.Node, new: api.Node) -> None:
        with self._lock:
            self._node_upsert(new)

    def on_node_delete(self, node: api.Node) -> None:
        with self._lock:
            name = node.metadata.name
            slot = self.node_slot.pop(name, None)
            if slot is None:
                return
            self.state_epoch += 1
            self.valid[slot] = False
            self.sched_ok[slot] = False
            # a DELETED node left the informer cache: the serial path's
            # node_by_name can no longer resolve it, so peers bound to
            # it must stop occupying topology domains (NotReady-but-
            # cached nodes keep their labels — they arrive as updates,
            # not deletes, and still resolve domains)
            self.node_labels[slot] = {}
            self.node_names[slot] = ""
            # RECLAIM the slot: node-name churn (autoscalers, recycled
            # hollow fleets) must not grow the device node axis — and
            # every scan's [n_cap] width — without bound. The dead
            # node's pods detach to the off-table bucket (their later
            # deletes resolve slot None and skip slot arrays) and the
            # slot's accumulated state zeroes so a future occupant
            # starts clean; the epoch bump above invalidates any
            # in-flight carry chained on the old layout.
            for key in self.node_pods.pop(slot, []):
                rec = self.pods.get(key)
                if rec is None:
                    continue
                rec.slot = None
                self.unknown_node_pods.setdefault(rec.node,
                                                  set()).add(key)
            for g in self.groups.values():
                moved = int(g.row[slot])
                if moved:
                    g.offgrid[name] = g.offgrid.get(name, 0) + moved
                    g.row[slot] = 0
            self.pod_count[slot] = 0
            self.cpu_used[slot] = 0
            self.mem_used[slot] = 0
            self.nz_cpu[slot] = 0
            self.nz_mem[slot] = 0
            self.port_bits[slot] = 0
            self.disk_any[slot] = 0
            self.disk_rw[slot] = 0
            self.cpu_cap[slot] = 0
            self.mem_cap[slot] = 0
            self.pod_cap[slot] = 0
            # misfit flags too: a reused slot must not inherit the dead
            # node's phantom-oversubscribed state (the fit gate requires
            # not_exceeded — an empty successor would be unschedulable
            # forever)
            self.exceed_cpu[slot] = False
            self.exceed_mem[slot] = False
            self._free_slots.append(slot)
            self._tie_dirty = True
            self._mark_node(slot)
            self._mark_state(slot)

    # ================================================== pod bookkeeping

    def _pod_upsert(self, pod: api.Pod) -> None:
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        old = self.pods.get(key)
        if old is not None:
            if old.rv and old.rv == pod.metadata.resource_version:
                return  # idempotent: bootstrap overlap / assume+watch echo
            new_counted = pod.status.phase not in (api.POD_SUCCEEDED,
                                                   api.POD_FAILED)
            if (old.node == pod.spec.node_name
                    and old.counted_res == new_counted
                    and old.labels == pod.metadata.labels):
                old.rv = pod.metadata.resource_version or old.rv
                return  # status-only change: nothing we count moved
            self._remove_record(key, old)
        rec = self._build_record(pod)
        self.pods[key] = rec
        self._apply_record(key, rec)

    def _build_record(self, pod: api.Pod) -> _PodRecord:
        rec = _PodRecord()
        rec.rv = pod.metadata.resource_version or ""
        rec.node = pod.spec.node_name
        rec.ns = pod.metadata.namespace
        rec.labels = dict(pod.metadata.labels)
        rec.counted_res = pod.status.phase not in (api.POD_SUCCEEDED,
                                                   api.POD_FAILED)
        # per-POD fields, set before the spec-memo early return below
        # (a shared template spec carries one priority, but uid is
        # per-object and priority may be overridden post-template)
        rec.priority = pod.spec.priority
        rec.uid = pod.metadata.uid
        # spec-derived fields memoized by spec IDENTITY: the columnar
        # create path (registry.create_from_template) shares one spec
        # across a whole batch, so the quantity parsing + port/disk
        # interning below runs once per template instead of per pod.
        # The cache entry holds the spec object itself, so the id() key
        # cannot be recycled while the entry lives; the side effects
        # the fast path skips (_note_mem gcd, _cpu_req_max, interner
        # growth) are value-idempotent — identical inputs change none
        # of them.
        sp = pod.spec
        ent = self._spec_memo.get(id(sp))
        if ent is not None and ent[0] is sp:
            (_, rec.req_cpu, rec.req_mem, rec.nz_cpu, rec.nz_mem,
             ports, disks) = ent
            rec.ports = list(ports)
            rec.disks = list(disks)
            return rec
        rec.req_cpu, rec.req_mem = get_resource_request(pod)
        for c in pod.spec.containers:
            nz_c, nz_m = get_nonzero_requests(c.resources.requests)
            rec.nz_cpu += nz_c
            rec.nz_mem += nz_m
            for cp in c.ports:
                if cp.host_port != 0:
                    bit, grew = self.ports_dict.intern(cp.host_port)
                    if grew:
                        self.port_bits = _grow(self.port_bits, 1,
                                               self.ports_dict.words)
                    rec.ports.append(bit)
        self._note_mem(rec.req_mem, is_cap=False)
        self._note_mem(rec.nz_mem, is_cap=False)
        self._cpu_req_max = max(self._cpu_req_max, rec.req_cpu,
                                rec.nz_cpu)
        for v in pod.spec.volumes:
            keys, gce_ro = _disk_keys(v)
            is_gce = v.gce_persistent_disk is not None
            for dk in keys:
                bit, grew = self.disk_dict.intern(dk)
                if grew:
                    self.disk_any = _grow(self.disk_any, 1,
                                          self.disk_dict.words)
                    self.disk_rw = _grow(self.disk_rw, 1,
                                         self.disk_dict.words)
                rec.disks.append((bit, True, is_gce and not gce_ro))
        if len(self._spec_memo) >= 64:
            # bound the held-alive specs; bound pods get fresh specs per
            # binding so ids churn — one template dominates in practice
            self._spec_memo.clear()
        self._spec_memo[id(sp)] = (sp, rec.req_cpu, rec.req_mem,
                                   rec.nz_cpu, rec.nz_mem,
                                   tuple(rec.ports), tuple(rec.disks))
        return rec

    def _apply_record(self, key: str, rec: _PodRecord) -> None:
        self.state_epoch += 1
        # spread groups see every pod (no phase filter)
        for g in self.groups.values():
            if g.matches(rec.ns, rec.labels):
                slot = self.node_slot.get(rec.node)
                if slot is None:
                    g.offgrid[rec.node] = g.offgrid.get(rec.node, 0) + 1
                else:
                    g.row[slot] += 1
        slot = self.node_slot.get(rec.node)
        if slot is None:
            self.unknown_node_pods.setdefault(rec.node, set()).add(key)
            return
        rec.slot = slot
        self.node_pods.setdefault(slot, []).append(key)
        if not rec.counted_res:
            return
        self._mark_state(slot)
        self.pod_count[slot] += 1
        self.nz_cpu[slot] += rec.nz_cpu
        self.nz_mem[slot] += rec.nz_mem
        for bit in rec.ports:
            _set_bit(self.port_bits[slot], bit)
        for bit, any_q, rw in rec.disks:
            _set_bit(self.disk_any[slot], bit)
            if rw:
                _set_bit(self.disk_rw[slot], bit)
        # skip-on-misfit replay, arrival order (predicates.go:160-185)
        cap_c = int(self.cpu_cap[slot])
        cap_m = int(self.mem_cap[slot])
        fits_cpu = cap_c == 0 or cap_c - int(self.cpu_used[slot]) >= rec.req_cpu
        fits_mem = cap_m == 0 or cap_m - int(self.mem_used[slot]) >= rec.req_mem
        if not fits_cpu:
            self.exceed_cpu[slot] = True
            rec.misfit = "cpu"
            self._mark_node(slot)  # exceed flags live in NodeConst
        elif not fits_mem:
            self.exceed_mem[slot] = True
            rec.misfit = "mem"
            self._mark_node(slot)
        else:
            self.cpu_used[slot] += rec.req_cpu
            self.mem_used[slot] += rec.req_mem

    def _remove_record(self, key: str, rec: _PodRecord) -> None:
        self.state_epoch += 1
        for g in self.groups.values():
            if g.matches(rec.ns, rec.labels):
                slot = self.node_slot.get(rec.node)
                if slot is None:
                    left = g.offgrid.get(rec.node, 0) - 1
                    if left > 0:
                        g.offgrid[rec.node] = left
                    else:
                        g.offgrid.pop(rec.node, None)
                else:
                    g.row[slot] -= 1
        if rec.slot is None:
            parked = self.unknown_node_pods.get(rec.node)
            if parked is not None:
                parked.discard(key)
                if not parked:
                    del self.unknown_node_pods[rec.node]
            return
        slot = rec.slot
        keys = self.node_pods.get(slot, [])
        try:
            keys.remove(key)
        except ValueError:
            pass
        if not rec.counted_res:
            return
        self._mark_state(slot)
        self.pod_count[slot] -= 1
        self.nz_cpu[slot] -= rec.nz_cpu
        self.nz_mem[slot] -= rec.nz_mem
        if rec.ports or rec.disks or self.exceed_cpu[slot] \
                or self.exceed_mem[slot]:
            # bitsets aren't reference-counted and the misfit replay is
            # order-dependent: rebuild this node's aggregates from its
            # remaining pods (rare path: ports/disks/oversubscription)
            self._replay_node(slot)
        elif rec.misfit is None:
            self.cpu_used[slot] -= rec.req_cpu
            self.mem_used[slot] -= rec.req_mem

    def _replay_node(self, slot: int) -> None:
        """Recompute one node's aggregate state from its pod ledger, in
        insertion order (the arrival-order replay)."""
        self._mark_state(slot)
        self._mark_node(slot)  # rewrites the exceed flags (NodeConst)
        self.cpu_used[slot] = 0
        self.mem_used[slot] = 0
        self.nz_cpu[slot] = 0
        self.nz_mem[slot] = 0
        self.pod_count[slot] = 0
        self.port_bits[slot] = 0
        self.disk_any[slot] = 0
        self.disk_rw[slot] = 0
        self.exceed_cpu[slot] = False
        self.exceed_mem[slot] = False
        cap_c = int(self.cpu_cap[slot])
        cap_m = int(self.mem_cap[slot])
        for key in self.node_pods.get(slot, []):
            rec = self.pods[key]
            if not rec.counted_res:
                continue
            rec.misfit = None
            self.pod_count[slot] += 1
            self.nz_cpu[slot] += rec.nz_cpu
            self.nz_mem[slot] += rec.nz_mem
            for bit in rec.ports:
                _set_bit(self.port_bits[slot], bit)
            for bit, any_q, rw in rec.disks:
                _set_bit(self.disk_any[slot], bit)
                if rw:
                    _set_bit(self.disk_rw[slot], bit)
            fits_cpu = cap_c == 0 or \
                cap_c - int(self.cpu_used[slot]) >= rec.req_cpu
            fits_mem = cap_m == 0 or \
                cap_m - int(self.mem_used[slot]) >= rec.req_mem
            if not fits_cpu:
                self.exceed_cpu[slot] = True
                rec.misfit = "cpu"
            elif not fits_mem:
                self.exceed_mem[slot] = True
                rec.misfit = "mem"
            else:
                self.cpu_used[slot] += rec.req_cpu
                self.mem_used[slot] += rec.req_mem

    # ================================================== node bookkeeping

    def _node_upsert(self, node: api.Node) -> None:
        self.state_epoch += 1
        name = node.metadata.name
        slot = self.node_slot.get(name)
        new_node = slot is None
        if new_node:
            slot = self._alloc_slot(name)
        self._mark_node(slot)
        cap_changed = (
            not new_node and (
                self.cpu_cap[slot] != (node.status.capacity["cpu"].milli
                                       if "cpu" in node.status.capacity else 0)
                or self.mem_cap[slot] != (
                    node.status.capacity["memory"].value
                    if "memory" in node.status.capacity else 0)))
        cap = node.status.capacity
        self.cpu_cap[slot] = cap["cpu"].milli if "cpu" in cap else 0
        self.mem_cap[slot] = cap["memory"].value if "memory" in cap else 0
        self._note_mem(int(self.mem_cap[slot]), is_cap=True)
        self._cpu_cap_max = max(self._cpu_cap_max,
                                int(self.cpu_cap[slot]))
        self.pod_cap[slot] = cap["pods"].value if "pods" in cap else 0
        self.label_words[slot] = 0
        self.node_labels[slot] = dict(node.metadata.labels)
        for kv in node.metadata.labels.items():
            bit, grew = self.labels_dict.intern(kv)
            if grew:
                self.label_words = _grow(self.label_words, 1,
                                         self.labels_dict.words)
            _set_bit(self.label_words[slot], bit)
        self.valid[slot] = True
        self.sched_ok[slot] = node_schedulable(node)
        if self._policy is not None:
            # same math as tables.py's policy tier (predicates.go:292 /
            # priorities.go:148), one node at a time
            labels = node.metadata.labels
            mask = True
            for wanted, presence in self._policy.label_presence:
                for label in wanted:
                    exists = label in labels
                    if (exists and not presence) or \
                            (not exists and presence):
                        mask = False
            score = 0
            for label, presence, weight in self._policy.label_priorities:
                exists = label in labels
                success = (exists and presence) or \
                    (not exists and not presence)
                score += (10 if success else 0) * weight
            self.static_mask[slot] = mask
            self.static_score[slot] = score
        if new_node:
            parked = self.unknown_node_pods.pop(name, None)
            if parked:
                for key in sorted(parked):
                    rec = self.pods[key]
                    # move spread counts from the offgrid bucket to the row
                    for g in self.groups.values():
                        if g.matches(rec.ns, rec.labels):
                            left = g.offgrid.get(name, 0) - 1
                            if left > 0:
                                g.offgrid[name] = left
                            else:
                                g.offgrid.pop(name, None)
                            g.row[slot] += 1
                    rec.slot = slot
                    self.node_pods.setdefault(slot, []).append(key)
                self._replay_node(slot)
        elif cap_changed:
            self._replay_node(slot)

    def _alloc_slot(self, name: str) -> int:
        if self._free_slots:
            slot = self._free_slots.pop()
        else:
            if self._next_slot >= self.n_cap:
                self._grow_nodes()
            slot = self._next_slot
            self._next_slot += 1
        self.node_slot[name] = slot
        self.node_names[slot] = name
        self._tie_dirty = True
        return slot

    def _note_mem(self, value: int, is_cap: bool) -> None:
        if value:
            self._mem_gcd = math.gcd(self._mem_gcd, value)
        if is_cap:
            self._mem_cap_max = max(self._mem_cap_max, value)
        else:
            self._mem_req_max = max(self._mem_req_max, value)

    def _narrow_params(self, static_max: int, tile_len: int):
        """-> (g, eligible) per tables._maybe_narrow's exactness rules:
        scaled scores fit i32 with x10 headroom, the already-accumulated
        running sums (measured from the arrays — zero-capacity nodes
        accumulate without a misfit gate, and nz sums grow on every
        node) plus this tile's worst-case additions stay in range, and
        the composite argmax fits for default-scale weights (the engine
        re-widens itself for larger ones)."""
        g = self._mem_gcd or 1

        def amax(arr):
            return int(arr.max()) if arr.size else 0

        cap_s = self._mem_cap_max // g
        req_s = self._mem_req_max // g
        mem_base = max(cap_s, amax(self.mem_used) // g,
                       amax(self.nz_mem) // g)
        cpu_base = max(self._cpu_cap_max, amax(self.cpu_used),
                       amax(self.nz_cpu))
        # inflight_pad: pods dispatched but not yet assumed host-side
        # (the pipelined scheduler) still add to the running sums the
        # device sees — budget them or the carry could overflow i32
        tiles = max(tile_len, 1) + self.inflight_pad
        bound = max((mem_base + tiles * req_s) * 10,
                    (cpu_base + tiles * self._cpu_req_max) * 10,
                    (30 * 64 + static_max) * max(self.n_cap, 1))
        return g, bound < (1 << 30)

    def _grow_nodes(self) -> None:
        self.state_epoch += 1
        # growth reshapes (and re-shards) the node axis: the device
        # table cache invalidates wholesale
        self._mark_full()
        # double while small, then step by 1024: a 5000-node cluster pads
        # to 5120 lanes (2% waste), not 8192 (64%) — every scan step pays
        # for the full node axis width. Rounded up to a mesh multiple so
        # the sharded axis always splits evenly (slot->shard stays block
        # sharding over stable slots).
        new_cap = self.n_cap * 2 if self.n_cap < 1024 else self.n_cap + 1024
        new_cap = -(-new_cap // self.mesh_devices) * self.mesh_devices
        self._grow_to(new_cap)

    def _grow_to(self, new_cap: int) -> None:
        """Caller holds the lock and has journaled the invalidation.
        Widen every slot-axis array to `new_cap` lanes in place."""
        self._node_dirty_gen = _grow(self._node_dirty_gen, 0, new_cap)
        self._state_dirty_gen = _grow(self._state_dirty_gen, 0, new_cap)
        for attr in ("valid", "sched_ok", "cpu_cap", "mem_cap", "pod_cap",
                     "tie_rank",
                     "cpu_used", "mem_used", "nz_cpu", "nz_mem", "pod_count",
                     "exceed_cpu", "exceed_mem", "static_score"):
            setattr(self, attr, _grow(getattr(self, attr), 0, new_cap))
        self.tie_rank[self.n_cap:] = -1
        # _grow zero-fills; the static mask's neutral value is True
        grown_mask = np.ones(new_cap, bool)
        grown_mask[:self.n_cap] = self.static_mask
        self.static_mask = grown_mask
        for attr in ("label_words", "port_bits", "disk_any", "disk_rw"):
            setattr(self, attr, _grow(getattr(self, attr), 0, new_cap))
        for g in self.groups.values():
            g.row = _grow(g.row, 0, new_cap)
        self.node_names.extend([""] * (new_cap - self.n_cap))
        self.node_labels.extend({} for _ in range(new_cap - self.n_cap))
        self.n_cap = new_cap

    # ================================================ shard epoch / reshard

    @property
    def encoder_id(self) -> int:
        """The instance token stamped into every TableDelta. Two
        encoders' generations AND shard epochs are incomparable; any
        cross-instance comparison must check this first."""
        return self._encoder_id

    def shard_epochs(self) -> Tuple[int, ...]:
        """Current epoch vector (one entry per mesh shard). Compare to
        a dispatched tile's TableDelta.shard_epochs to fence stale
        in-flight work after a reshard (sched/batch.py _finalize)."""
        with self._lock:
            return self._shard_epochs

    def reshard(self, survivors: int) -> int:
        """Re-shard the stable slot->device mapping onto `survivors`
        shards after a shard owner's lease expired.

        The slot axis keeps its stable indices — no row moves WITHIN
        the host truth — but the block partition over devices changes,
        so every device-resident row is on the wrong owner: capacity
        re-rounds to a multiple of the survivor count (growth only; the
        rounded-up cap never shrinks below the occupied high-water
        mark), every occupied slot re-journals at fresh generations,
        full_gen advances (whole-mirror invalidation), state_epoch
        bumps (no device carry survives the mesh change), and the epoch
        vector is replaced — new length, every entry past the old
        maximum, so ANY tile or mirror stamped with the old vector is
        detectably stale. Returns the number of occupied slots the
        journal replay rebuilds on the survivors (the caller feeds
        shard_replay_rows_total)."""
        survivors = max(1, int(survivors))
        with self._lock:
            self.state_epoch += 1
            self._mark_full()
            self.mesh_devices = survivors
            new_cap = -(-self.n_cap // survivors) * survivors
            if new_cap != self.n_cap:
                self._grow_to(new_cap)
            occupied = np.nonzero(self.valid)[0]
            if occupied.size:
                # re-journal every surviving row: the replay the new
                # owners consume (TableDelta.replay_slots from the
                # pre-failure full_gen returns exactly this set)
                self._mark_node(occupied)
                self._mark_state(occupied)
            nxt = max(self._shard_epochs, default=0) + 1
            self._shard_epochs = (nxt,) * survivors
            return int(occupied.size)

    # ==================================================== preemption table

    def victim_table(self, pod: api.Pod):
        """One consistent cut of the preemption search inputs for `pod`
        (sched/preemption.py VictimTable): per-node State columns plus
        the per-node victim prefix arrays, gathered under the encoder
        lock so the columns, the victim identities and the fencing
        epochs (state_epoch / shard_epochs / encoder_id) agree.

        Candidate nodes are live, schedulable, selector/host-matching
        and NOT exceed-flagged: on a non-exceed node every counted pod
        has misfit None, so a victim's release frees exactly its
        recorded request — the prefix-sum search needs no misfit
        replay. Victims are the counted pods of strictly lower
        priority, (priority asc, insertion asc) — stable sort over the
        node_pods insertion order. The victim axis pads to a power of
        two so the device kernel compiles one program per (n_cap,
        v_pad) rung, mirroring the engine's chunk ladder."""
        from ..preemption import PMAX, VictimTable
        sp = pod.spec
        prio = sp.priority
        req_cpu, req_mem = get_resource_request(pod)
        sel = sp.node_selector
        with self._lock:
            if self._tie_dirty:
                self._recompute_tie_rank()
            n = self.n_cap
            cand = (self.valid & self.sched_ok & self.static_mask
                    & ~self.exceed_cpu & ~self.exceed_mem)
            if sel:
                for j in np.nonzero(cand)[0]:
                    labels = self.node_labels[j]
                    if any(labels.get(k) != v for k, v in sel.items()):
                        cand[j] = False
            if sp.node_name:
                host_slot = self.node_slot.get(sp.node_name)
                host = np.zeros(n, bool)
                if host_slot is not None:
                    host[host_slot] = True
                cand &= host
            victims: List[List[Tuple[str, str, str]]] = [
                [] for _ in range(n)]
            rows: List[List[Tuple[int, int, int]]] = [[] for _ in range(n)]
            max_v = 0
            for j in np.nonzero(cand)[0]:
                recs = []
                for key in self.node_pods.get(int(j), []):
                    rec = self.pods.get(key)
                    if (rec is None or not rec.counted_res
                            or rec.priority >= prio):
                        continue
                    recs.append((key, rec))
                # stable by priority: insertion order breaks ties
                recs.sort(key=lambda kr: kr[1].priority)
                for key, rec in recs:
                    ns, _, name = key.partition("/")
                    victims[int(j)].append((ns, name, rec.uid))
                    rows[int(j)].append((rec.priority, rec.req_cpu,
                                         rec.req_mem))
                if len(recs) > max_v:
                    max_v = len(recs)
            v_pad = 1
            while v_pad < max_v:
                v_pad *= 2
            v_prio = np.full((n, v_pad), PMAX + 1, np.int64)
            v_cpu = np.zeros((n, v_pad), np.int64)
            v_mem = np.zeros((n, v_pad), np.int64)
            v_valid = np.zeros((n, v_pad), bool)
            for j in range(n):
                for i, (p, c, m) in enumerate(rows[j]):
                    v_prio[j, i] = p
                    v_cpu[j, i] = c
                    v_mem[j, i] = m
                    v_valid[j, i] = True
            return VictimTable(
                pod_key=(pod.metadata.namespace, pod.metadata.name),
                pod_uid=pod.metadata.uid,
                prio=prio, req_cpu=req_cpu, req_mem=req_mem,
                zero_req=(req_cpu == 0 and req_mem == 0),
                cand=cand,
                cpu_cap=self.cpu_cap.astype(np.int64),
                mem_cap=self.mem_cap.astype(np.int64),
                pod_cap=self.pod_cap.astype(np.int64),
                cpu_used=self.cpu_used.astype(np.int64),
                mem_used=self.mem_used.astype(np.int64),
                pod_count=self.pod_count.astype(np.int64),
                tie_rank=self.tie_rank.astype(np.int64),
                v_prio=v_prio, v_cpu=v_cpu, v_mem=v_mem, v_valid=v_valid,
                victims=victims, node_names=list(self.node_names),
                state_epoch=self.state_epoch,
                shard_epochs=self._shard_epochs,
                encoder_id=self._encoder_id)

    def _recompute_tie_rank(self) -> None:
        # rank over ALL known names: relative order among valid nodes is
        # what the tie-break consumes, and a superset ranking preserves it
        old = self.tie_rank.copy()
        self.tie_rank[:] = -1
        for rank, name in enumerate(sorted(self.node_slot)):
            self.tie_rank[self.node_slot[name]] = rank
        changed = np.nonzero(old != self.tie_rank)[0]
        if changed.size:
            # a node add/delete shifts the ranks of name-sorted
            # neighbours: journal exactly the slots whose rank moved
            self._mark_node(changed)
        self._tie_dirty = False

    # ================================================== group bookkeeping

    def _group_for(self, ns: str, sels: List[Dict[str, str]]) -> _Group:
        key = (ns, frozenset(frozenset(s.items()) for s in sels))
        g = self.groups.get(key)
        if g is None:
            g = _Group(ns, [dict(s) for s in sels], self.n_cap)
            # first sighting: one full scan of the ledger seeds the counts;
            # afterwards the group maintains itself from deltas
            for rec in self.pods.values():
                if g.matches(rec.ns, rec.labels):
                    slot = self.node_slot.get(rec.node)
                    if slot is None:
                        g.offgrid[rec.node] = g.offgrid.get(rec.node, 0) + 1
                    else:
                        g.row[slot] += 1
            self.groups[key] = g
        return g

    # ================================================== affinity tier

    def _encode_aff_terms(self, pending_pods: List[api.Pod], n_pad: int):
        """The inter-pod affinity structures of one tile
        (tables.py's term intern + domain + scope-count build), computed
        against the LEDGER: per-pod records carry ns/labels/node and the
        node_labels list resolves topology domains, so affinity tiles
        cost one pass over cheap records instead of the full O(cluster)
        api-object re-encode they used to force (the last
        NeedsFullEncode case). Caller holds the lock."""
        # term interning is shared with the full encoder — the parity-
        # critical key lives in exactly one place
        term_meta, pod_terms = collect_affinity_terms(pending_pods)
        T = max(1, len(term_meta))

        # per-term topology domains over CANDIDATE (valid) slots — a
        # domain value only invalid nodes carry can never satisfy a
        # term, mirroring tables.py building domains from snap.nodes
        aff_dom = np.full((T, n_pad), -1, np.int32)
        dom_ids: List[Dict[str, int]] = [dict() for _ in range(T)]
        for tid, (_, _, topo_key) in enumerate(term_meta):
            row = aff_dom[tid]
            doms = dom_ids[tid]
            for slot, name in enumerate(self.node_names):
                if not name or not self.valid[slot] \
                        or not self.sched_ok[slot]:
                    continue
                value = self.node_labels[slot].get(topo_key)
                if value is None:
                    continue
                row[slot] = doms.setdefault(value, len(doms))
        D = max(1, max((len(d) for d in dom_ids), default=0))

        aff_count = np.zeros((T, D), np.int32)
        aff_total = np.zeros(T, np.int32)
        if term_meta:
            # scope counts over the ledger's counted (non-terminal)
            # placed pods; domains resolve through ALL known nodes
            # (valid or not — node_by_name semantics), but only
            # candidate-carried domain values scored above can match
            matchers = [
                (ns_scope, selector, topo_key, dom_ids[tid])
                for tid, (ns_scope, selector, topo_key)
                in enumerate(term_meta)]
            for rec in self.pods.values():
                if not rec.counted_res:
                    continue
                host_slot = self.node_slot.get(rec.node)
                host_labels = (self.node_labels[host_slot]
                               if host_slot is not None else None)
                for tid, (ns_scope, sel, topo_key, doms) in \
                        enumerate(matchers):
                    if rec.ns not in ns_scope:
                        continue
                    if not _selector_matches(sel, rec.labels):
                        continue
                    aff_total[tid] += 1
                    if host_labels is None:
                        continue
                    value = host_labels.get(topo_key)
                    dom = doms.get(value) if value is not None else None
                    if dom is not None:
                        aff_count[tid, dom] += 1
        return (term_meta, pod_terms, aff_dom, dom_ids, aff_count,
                aff_total, T, D)

    # ================================================== tile assembly

    def _intern_pending(self, pod: api.Pod) -> None:
        """Intern every key a pending pod references, growing the
        persistent bitset arrays in lockstep — BEFORE tile arrays are
        allocated, so tile and persistent widths always agree."""
        for c in pod.spec.containers:
            for cp in c.ports:
                if cp.host_port != 0:
                    _, grew = self.ports_dict.intern(cp.host_port)
                    if grew:
                        self.port_bits = _grow(self.port_bits, 1,
                                               self.ports_dict.words)
        for kv in pod.spec.node_selector.items():
            _, grew = self.labels_dict.intern(kv)
            if grew:
                self.label_words = _grow(self.label_words, 1,
                                         self.labels_dict.words)
        for v in pod.spec.volumes:
            for dk in _disk_keys(v)[0]:
                _, grew = self.disk_dict.intern(dk)
                if grew:
                    self.disk_any = _grow(self.disk_any, 1,
                                          self.disk_dict.words)
                    self.disk_rw = _grow(self.disk_rw, 1,
                                         self.disk_dict.words)

    def _encode_spec_cols(self, pb: PodArrays, j: int,
                          pod: api.Pod) -> None:
        """Spec-derived tile columns for row j, written in place — the
        single implementation behind both the scalar per-pod path and
        the columnar broadcast fill (encode_tile), so the two encodes
        cannot drift. Also feeds the narrowing gcd/max accumulators:
        value-idempotent, so running once per shared spec is exact."""
        req_cpu, req_mem = get_resource_request(pod)
        pb.req_cpu[j] = req_cpu
        pb.req_mem[j] = req_mem
        pb.zero_req[j] = req_cpu == 0 and req_mem == 0
        # the tile's quantities join the gcd BEFORE this encode
        # narrows (a gcd-breaking request must keep this and
        # every later tile exact)
        self._note_mem(req_mem, is_cap=False)
        self._cpu_req_max = max(self._cpu_req_max, req_cpu)
        for c in pod.spec.containers:
            nz_c, nz_m = get_nonzero_requests(c.resources.requests)
            pb.nz_cpu[j] += nz_c
            pb.nz_mem[j] += nz_m
            for cp in c.ports:
                if cp.host_port != 0:
                    # pre-interned by _intern_pending: never grows
                    bit, _ = self.ports_dict.intern(cp.host_port)
                    _set_bit(pb.port_words[j], bit)
        self._note_mem(int(pb.nz_mem[j]), is_cap=False)
        self._cpu_req_max = max(self._cpu_req_max, int(pb.nz_cpu[j]))
        for kv in pod.spec.node_selector.items():
            bit, _ = self.labels_dict.intern(kv)
            _set_bit(pb.sel_words[j], bit)
        for v in pod.spec.volumes:
            keys, gce_ro = _disk_keys(v)
            is_gce = v.gce_persistent_disk is not None
            for dk in keys:
                bit, _ = self.disk_dict.intern(dk)
                _set_bit(pb.disk_sany[j], bit)
                if is_gce and gce_ro:
                    _set_bit(pb.disk_qrw[j], bit)
                else:
                    _set_bit(pb.disk_qany[j], bit)
                if is_gce and not gce_ro:
                    _set_bit(pb.disk_srw[j], bit)
        if pod.spec.node_name:
            pb.host_idx[j] = self.node_slot.get(pod.spec.node_name, -2)

    def encode_tile(self, pending_pods: List[api.Pod],
                    services: List[api.Service],
                    controllers: List[api.ReplicationController],
                    pad_to: int = 0) -> EncodeResult:
        """O(tile) encode against the current persistent state.

        pad_to: allocate the pod axis at this length up front (invalid
        rows are zero / valid=False) so run_chunked never re-pads — the
        tail-chunk concatenate was measured GIL-hostile in situ."""
        with self._lock:
            if self._tie_dirty:
                self._recompute_tie_rank()
            seen_specs = set()
            for pod in pending_pods:
                # one interning walk per distinct spec object (columnar
                # creates share one spec across the whole tile)
                sid = id(pod.spec)
                if sid not in seen_specs:
                    seen_specs.add(sid)
                    self._intern_pending(pod)
            n_pad = self.n_cap
            L = self.labels_dict.words
            PW = self.ports_dict.words
            K = self.disk_dict.words
            p = len(pending_pods)
            p_pad = max(1, p, pad_to)

            # ---- pod batch + spread groups of this tile ----
            tile_groups: List[_Group] = []
            group_idx: Dict[int, int] = {}
            pod_groups: List[int] = []
            for pod in pending_pods:
                sels = _pod_spread_selectors(pod, services, controllers)
                if not sels:
                    pod_groups.append(-1)
                    continue
                g = self._group_for(pod.metadata.namespace, sels)
                gid = group_idx.get(id(g))
                if gid is None:
                    gid = len(tile_groups)
                    group_idx[id(g)] = gid
                    tile_groups.append(g)
                pod_groups.append(gid)
            G = max(1, len(tile_groups))

            # ---- inter-pod affinity terms of this tile (tables.py's
            # build, fed from the LEDGER instead of a full pod re-walk:
            # the per-pod records already carry ns/labels/node, so the
            # scope counts cost one pass over cheap records rather than
            # O(cluster) api-object walking per tile) ----
            (term_meta, pod_terms, aff_dom, dom_ids,
             aff_count, aff_total, T, D) = self._encode_aff_terms(
                 pending_pods, n_pad)

            pb = PodArrays(
                valid=np.zeros(p_pad, bool),
                req_cpu=np.zeros(p_pad, np.int64),
                req_mem=np.zeros(p_pad, np.int64),
                zero_req=np.zeros(p_pad, bool),
                nz_cpu=np.zeros(p_pad, np.int64),
                nz_mem=np.zeros(p_pad, np.int64),
                sel_words=np.zeros((p_pad, L), np.uint32),
                port_words=np.zeros((p_pad, PW), np.uint32),
                disk_qany=np.zeros((p_pad, K), np.uint32),
                disk_qrw=np.zeros((p_pad, K), np.uint32),
                disk_sany=np.zeros((p_pad, K), np.uint32),
                disk_srw=np.zeros((p_pad, K), np.uint32),
                host_idx=np.full(p_pad, -1, np.int32),
                group_id=np.full(p_pad, -1, np.int32),
                member=np.zeros((p_pad, G), np.int32),
                aff_req=np.zeros((p_pad, T), bool),
                anti_req=np.zeros((p_pad, T), bool),
                aff_member=np.zeros((p_pad, T), np.int32),
                svc_group=np.full(p_pad, -1, np.int32),
                svc_member=np.zeros((p_pad, 1), np.int32))
            # ---- columnar spec fill (SURVEY.md section 7 hard part 3):
            # rows sharing one spec object (the registry's
            # template-create contract) encode ONCE via the scalar
            # helper, then broadcast-copy to their sibling rows — the
            # 8192-pod bench tile collapses to one encode + a dozen
            # numpy fancy-index stores. ids are stable here because the
            # pod list holds every spec alive for the duration.
            spec_rows: Dict[int, List[int]] = {}
            for j, pod in enumerate(pending_pods):
                spec_rows.setdefault(id(pod.spec), []).append(j)
            spec_done = np.zeros(p, bool) if p else None
            for idxs in spec_rows.values():
                if len(idxs) < 8:
                    continue
                j0 = idxs[0]
                self._encode_spec_cols(pb, j0, pending_pods[j0])
                ii = np.asarray(idxs[1:], np.intp)
                for col in (pb.req_cpu, pb.req_mem, pb.zero_req,
                            pb.nz_cpu, pb.nz_mem, pb.host_idx,
                            pb.port_words, pb.sel_words, pb.disk_qany,
                            pb.disk_qrw, pb.disk_sany, pb.disk_srw):
                    col[ii] = col[j0]
                spec_done[np.asarray(idxs, np.intp)] = True

            for j, pod in enumerate(pending_pods):
                pb.valid[j] = True
                if not spec_done[j]:
                    self._encode_spec_cols(pb, j, pod)
                pb.group_id[j] = pod_groups[j]
                for gid, g in enumerate(tile_groups):
                    if g.matches(pod.metadata.namespace, pod.metadata.labels):
                        pb.member[j, gid] = 1
                aff_ids, anti_ids = pod_terms[j]
                for tid in aff_ids:
                    pb.aff_req[j, tid] = True
                for tid in anti_ids:
                    pb.anti_req[j, tid] = True
                for tid, (ns_scope, selector, _topo) in enumerate(term_meta):
                    if pod.metadata.namespace in ns_scope and \
                            _selector_matches(selector,
                                              pod.metadata.labels):
                        pb.aff_member[j, tid] = 1

            # ---- views of the persistent state (copied: the reflector
            # threads keep mutating these arrays while the scan runs).
            # The host arrays stay raw i64; when the running gcd proves
            # the i32 rescale exact (tables._maybe_narrow's rules), the
            # device copies narrow here — same single pass as the copy.
            static_max = int(np.max(np.abs(self.static_score))) \
                if self.static_score.size else 0
            mem_scale, narrow = self._narrow_params(static_max, p_pad)

            def res(arr, scale=1):
                if narrow:
                    return ((arr // scale) if scale != 1 else arr) \
                        .astype(np.int32)
                return arr.copy()

            nt = NodeArrays(
                valid=self.valid.copy(),
                sched_ok=self.sched_ok.copy(),
                cpu_cap=res(self.cpu_cap),
                mem_cap=res(self.mem_cap, mem_scale),
                pod_cap=self.pod_cap.copy(),
                label_words=self.label_words.copy(),
                tie_rank=self.tie_rank.copy(),
                exceed_cpu=self.exceed_cpu.copy(),
                exceed_mem=self.exceed_mem.copy(),
                aff_dom=aff_dom,
                zone_id=np.full(n_pad, -1, np.int32),
                zone_scratch=np.zeros(1, np.int32),
                static_mask=self.static_mask.copy(),
                static_score=res(self.static_score))
            spread = (np.stack([g.row for g in tile_groups])
                      if tile_groups else np.zeros((1, n_pad), np.int32))
            offgrid_max = np.zeros(G, np.int32)
            for gid, g in enumerate(tile_groups):
                if g.offgrid:
                    offgrid_max[gid] = max(g.offgrid.values())
            st = StateArrays(
                cpu_used=res(self.cpu_used),
                mem_used=res(self.mem_used, mem_scale),
                nz_cpu=res(self.nz_cpu),
                nz_mem=res(self.nz_mem, mem_scale),
                pod_count=self.pod_count.copy(),
                port_bits=self.port_bits.copy(),
                disk_any=self.disk_any.copy(),
                disk_rw=self.disk_rw.copy(),
                spread=spread.copy(),
                aff_count=aff_count,
                aff_total=aff_total,
                svc_count=np.zeros((1, n_pad), np.int32),
                svc_total=np.zeros(1, np.int32))
            pb = replace_pod_batch_dtypes(pb, narrow, mem_scale)
            # dirty-slot journal snapshot, captured under the same lock
            # as the host-array copies above so the generations are
            # consistent with this encode's table contents
            delta = TableDelta(table_gen=self._table_gen,
                               node_dirty_gen=self._node_dirty_gen.copy(),
                               state_dirty_gen=self._state_dirty_gen.copy(),
                               full_gen=self._full_dirty_gen,
                               encoder_id=self._encoder_id,
                               shard_epochs=self._shard_epochs)
            return EncodeResult(
                node_tab=nt, pod_batch=pb, init_state=st,
                offgrid_max=offgrid_max,
                node_names=list(self.node_names),
                n_nodes=len(self.node_slot), n_pods=p,
                mem_scale=mem_scale if narrow else 1,
                tile_groups=tile_groups,
                state_epoch=self.state_epoch,
                delta=delta)

    # ================================================== wiring helpers

    def detach(self) -> None:
        """Stop consuming informer events. The chained handlers attach()
        installed cannot be unhooked (closures over closures), so they
        stay in the chain as gated no-ops; a scheduler failing over
        builds a FRESH encoder from a fresh snapshot rather than
        trusting this one's carry (sched/batch.py _on_started_leading)."""
        self._detached = True

    def attach(self, factory) -> "IncrementalEncoder":
        """Chain onto the factory's scheduled-pod reflector and node
        informer, then bootstrap from their caches. Events that land
        between attach and bootstrap are absorbed by the ledger's
        resourceVersion idempotency check."""
        sref = factory.scheduled_reflector
        self._detached = False

        def chain(first, second):
            if first is None:
                return second
            def chained(*a):
                first(*a)
                second(*a)
            return chained

        def gate(fn):
            # detach() turns this encoder's share of the chain into a
            # no-op without disturbing other subscribers
            def gated(*a):
                if not self._detached:
                    fn(*a)
            return gated

        sref.on_add = chain(sref.on_add, gate(self.on_pod_add))
        sref.on_update = chain(
            sref.on_update,
            gate(lambda old, new: self.on_pod_update(old, new)))
        sref.on_delete = chain(sref.on_delete, gate(self.on_pod_delete))
        nref = factory.node_informer.reflector
        nref.on_add = chain(nref.on_add, gate(self.on_node_add))
        nref.on_update = chain(
            nref.on_update,
            gate(lambda old, new: self.on_node_update(old, new)))
        nref.on_delete = chain(nref.on_delete, gate(self.on_node_delete))
        for node in factory.node_informer.cache.list():
            self.on_node_add(node)
        for pod in factory.scheduled_cache.list():
            self.on_pod_add(pod)
        # reconcile the snapshot against the NOW-live cache: a pod
        # whose DELETED event raced between the list() above and its
        # bootstrap on_pod_add re-entered the ledger with no future
        # event to remove it (the rv-idempotency check dedupes
        # add/update overlap; it cannot undo an add that post-dates
        # the delete) — phantom capacity for the process lifetime
        with self._lock:
            # the live set is read under the SAME lock the chained
            # handlers serialize on: computed outside it, a pod whose
            # ADDED event landed between the list() and the lock would
            # be misread as stale and evicted
            live = {f"{p.metadata.namespace}/{p.metadata.name}"
                    for p in factory.scheduled_cache.list()}
            stale = [k for k in self.pods if k not in live]
        for key in stale:
            ns, _, name = key.partition("/")
            self.on_pod_delete(api.Pod(metadata=api.ObjectMeta(
                name=name, namespace=ns)))
        return self
