"""Pallas TPU kernel: the predicate-filter probe.

The extender sidecar's Filter verb and mixed mode's device-probe rung
(plugin/pkg/scheduler/extender.go:95 Filter; sched/device_assist.py)
evaluate every fit predicate for P pending pods against N nodes — a
[P, N] boolean mask with no sequential dependence. That makes it the
one hot op that is BOTH worth a hand kernel and provably bit-exact in
one: every predicate is pure integer/bitset arithmetic
(predicates.go:127,192,250,258,403 — resource sums, port/disk bitset
intersections, selector subset tests, hostname equality), so unlike the
scoring scan there is no f64 rounding contract to replicate (the
BalancedResourceAllocation priority keeps the scan on the XLA path; see
engine._mask_and_score).

Kernel shape: grid over (pod tiles x node tiles); node-axis data rides
the lane dimension (bitsets pre-transposed to [words, N]), pod scalars
broadcast from the sublane axis, bitset word loops unroll statically.
Output is i32 (bool carries awkward tile constraints); the wrapper
casts.

Eligibility (checked by filter_masks): i32-narrowed encoding (TPU
vector units are 32-bit; the i64 wide path falls back to the XLA
probe), no inter-pod affinity terms in the batch, single device.
On CPU backends the kernel runs in interpreter mode — that is how the
parity suite pins it against the XLA probe.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BP = 8     # pod rows per block (sublane-friendly)
BN = 512   # node lanes per block (4x the 128-lane vector width)


def _filter_kernel(n_real_nodes: int,
                   # node axis [1, BN] / [W, BN]; booleans arrive as i32
                   valid, cpu_cap, mem_cap, pod_cap, exceed_cpu,
                   exceed_mem, static_mask, labels_t,
                   cpu_used, mem_used, pod_count, port_bits_t,
                   disk_any_t, disk_rw_t,
                   # pod axis [BP, 1] / [BP, W]
                   pvalid, preq_cpu, preq_mem, pzero, psel, pports,
                   pqany, pqrw, phost,
                   out):
    j = pl.program_id(1)

    # Mosaic note: no boolean splats or boolean accumulators in this
    # body. `jnp.zeros(..., bool_)` / `ones(..., bool_)` materialize as
    # i8 vectors that Mosaic then `arith.trunci`s to i1 — a lowering it
    # rejects ("Unsupported target bitwidth for truncation", observed
    # on real v5e, TPU_EVIDENCE.json r4). Bitset conflicts therefore
    # accumulate in u32 and compare to zero ONCE; i1 values only ever
    # come from comparisons.

    # ---- PodFitsResources (predicates.go:192-222) ----
    fits_count = pod_count[:] < pod_cap[:]                      # [1, BN]
    cap_c = cpu_cap[:]
    cap_m = mem_cap[:]
    free_cpu = (cap_c == 0) | (cap_c - cpu_used[:] >= preq_cpu[:])
    free_mem = (cap_m == 0) | (cap_m - mem_used[:] >= preq_mem[:])
    not_exceeded = (exceed_cpu[:] == 0) & (exceed_mem[:] == 0)
    # where(zero_req, fits_count, fits_count & rest)
    #   == fits_count & (zero_req | rest)
    res_ok = fits_count & ((pzero[:] != 0)
                           | (not_exceeded & free_cpu & free_mem))

    # ---- PodFitsHostPorts (predicates.go:403-415) ----
    pw = pports.shape[1]
    port_acc = jnp.zeros(out.shape, jnp.uint32)
    for w in range(pw):
        port_acc = port_acc | (port_bits_t[w:w + 1, :]
                               & pports[:, w:w + 1])
    port_ok = port_acc == 0

    # ---- MatchNodeSelector (predicates.go:250 via label bitsets) ----
    lw = psel.shape[1]
    sel_acc = jnp.zeros(out.shape, jnp.uint32)
    for w in range(lw):
        sel_acc = sel_acc | (psel[:, w:w + 1] & ~labels_t[w:w + 1, :])
    sel_ok = sel_acc == 0

    # ---- NoDiskConflict (predicates.go:127-137) ----
    kw = pqany.shape[1]
    disk_acc = jnp.zeros(out.shape, jnp.uint32)
    for w in range(kw):
        disk_acc = disk_acc | (disk_any_t[w:w + 1, :] & pqany[:, w:w + 1]) \
                            | (disk_rw_t[w:w + 1, :] & pqrw[:, w:w + 1])
    disk_ok = disk_acc == 0

    # ---- PodFitsHost (predicates.go:258) ----
    node_idx = j * BN + jax.lax.broadcasted_iota(jnp.int32, out.shape, 1)
    host_ok = (phost[:] == -1) | (node_idx == phost[:])

    mask = ((valid[:] != 0) & (pvalid[:] != 0) & res_ok
            & port_ok & sel_ok & host_ok & disk_ok
            & (static_mask[:] != 0)
            & (node_idx < n_real_nodes))
    out[:] = mask.astype(jnp.int32)


def _pad_to(a: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    n = a.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _filter_call(node_args, state_args, pod_args, interpret=False):
    (valid, cpu_cap, mem_cap, pod_cap, exceed_cpu, exceed_mem,
     static_mask, labels) = node_args
    (cpu_used, mem_used, pod_count, port_bits, disk_any, disk_rw) = \
        state_args
    (pvalid, preq_cpu, preq_mem, pzero, psel, pports, pqany, pqrw,
     phost) = pod_args

    n = valid.shape[0]
    p = pvalid.shape[0]

    def nvec(a, dtype=None):
        a = a.astype(dtype) if dtype is not None else a
        return _pad_to(a.reshape(1, -1), 1, BN)

    def nbits(a):  # [N, W] -> [W, N_pad]
        return _pad_to(a.T, 1, BN)

    def pvec(a, dtype=None):
        a = a.astype(dtype) if dtype is not None else a
        return _pad_to(a.reshape(-1, 1), 0, BP)

    def pbits(a):  # [P, W]
        return _pad_to(a, 0, BP)

    node_in = (nvec(valid, jnp.int32), nvec(cpu_cap), nvec(mem_cap),
               nvec(pod_cap), nvec(exceed_cpu, jnp.int32),
               nvec(exceed_mem, jnp.int32), nvec(static_mask, jnp.int32),
               nbits(labels))
    state_in = (nvec(cpu_used), nvec(mem_used), nvec(pod_count),
                nbits(port_bits), nbits(disk_any), nbits(disk_rw))
    pod_in = (pvec(pvalid, jnp.int32), pvec(preq_cpu), pvec(preq_mem),
              pvec(pzero, jnp.int32), pbits(psel), pbits(pports),
              pbits(pqany), pbits(pqrw), pvec(phost))

    n_pad = node_in[0].shape[1]
    p_pad = pod_in[0].shape[0]
    grid = (p_pad // BP, n_pad // BN)

    def nspec(a):
        # index maps must return uniformly-typed block indices: a bare
        # python 0 traces i64 next to the i32 grid index and Mosaic's
        # AOT path rejects the (i64, i32) func.return (observed on
        # real v5e); i * 0 stays i32
        return pl.BlockSpec((a.shape[0], BN), lambda i, j: (i * 0, j))

    def pspec(a):
        return pl.BlockSpec((BP, a.shape[1]), lambda i, j: (i, j * 0))

    out = pl.pallas_call(
        functools.partial(_filter_kernel, n),
        out_shape=jax.ShapeDtypeStruct((p_pad, n_pad), jnp.int32),
        grid=grid,
        in_specs=[nspec(a) for a in node_in]
        + [nspec(a) for a in state_in]
        + [pspec(a) for a in pod_in],
        out_specs=pl.BlockSpec((BP, BN), lambda i, j: (i, j)),
        interpret=interpret,
    )(*node_in, *state_in, *pod_in)
    return out[:p, :n]


def supports(enc) -> bool:
    """Kernel eligibility for this encoding: i32-narrowed resources
    (the wide i64 path stays on XLA), no inter-pod affinity terms."""
    pb = enc.pod_batch
    if enc.node_tab.cpu_cap.dtype != np.int32:
        return False
    if bool(pb.aff_req.any() or pb.anti_req.any()):
        return False
    return True


def filter_masks(enc) -> np.ndarray:
    """-> bool[P, N] predicate-fit mask for every pending pod against
    the pre-batch state — the pallas fast path of BatchEngine.probe's
    mask half. Caller must have checked supports(enc)."""
    nt, st, pb = enc.node_tab, enc.init_state, enc.pod_batch
    interpret = jax.default_backend() not in ("tpu",)
    # sched_ok folds into the kernel's valid lane mask: the two are
    # AND-ed identically in the XLA mask, so the kernel needs no new
    # input column to match it bit-for-bit
    out = _filter_call(
        (jnp.asarray(nt.valid & nt.sched_ok), jnp.asarray(nt.cpu_cap),
         jnp.asarray(nt.mem_cap), jnp.asarray(nt.pod_cap),
         jnp.asarray(nt.exceed_cpu), jnp.asarray(nt.exceed_mem),
         jnp.asarray(nt.static_mask), jnp.asarray(nt.label_words)),
        (jnp.asarray(st.cpu_used), jnp.asarray(st.mem_used),
         jnp.asarray(st.pod_count), jnp.asarray(st.port_bits),
         jnp.asarray(st.disk_any), jnp.asarray(st.disk_rw)),
        (jnp.asarray(pb.valid), jnp.asarray(pb.req_cpu),
         jnp.asarray(pb.req_mem), jnp.asarray(pb.zero_req),
         jnp.asarray(pb.sel_words), jnp.asarray(pb.port_words),
         jnp.asarray(pb.disk_qany), jnp.asarray(pb.disk_qrw),
         jnp.asarray(pb.host_idx)),
        interpret=interpret)
    return np.asarray(out[:enc.n_pods]).astype(bool)
