"""TPU batch scheduling engine.

The serial hot loop the reference runs per pod
(plugin/pkg/scheduler/generic_scheduler.go:111 findNodesThatFit,
:164 PrioritizeNodes — O(nodes x predicates x pods) of pointer-chasing Go)
is re-founded here as dense array math on device:

  - host-side encoder (tables.py): api objects -> Struct-of-Arrays cluster
    state (label/port/disk-key interning into bitsets, integer resource
    vectors, initial per-node aggregates),
  - device kernel (engine.py): a jitted `lax.scan` over the pending-pod
    batch; each step is O(nodes) vector work — predicate masks, integer
    0..10 priority scores, masked argmax host selection with a
    deterministic tie-break — with the node axis shardable across a
    `jax.sharding.Mesh` so the argmax reduces over ICI.

Bit-exactness contract: given the same snapshot, the engine's assignments
equal the serial oracle's (GenericScheduler with deterministic tie-break)
pod for pod. Sequential-commit semantics (pod k consumes capacity seen by
pod k+1) are preserved by the scan carry. Pods using features outside the
default provider's predicate/priority set take the serial fallback path
(SURVEY.md section 7 hard part 3: provable fallback).
"""

from .tables import ClusterSnapshot, DevicePolicy, EncodeResult, encode_snapshot
from .engine import BatchEngine, schedule_batch

__all__ = [
    "ClusterSnapshot", "DevicePolicy", "EncodeResult", "encode_snapshot",
    "BatchEngine", "schedule_batch",
]
