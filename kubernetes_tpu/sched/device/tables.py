"""Host-side snapshot encoder: api objects -> Struct-of-Arrays device tables.

This is the strings->tensors boundary (SURVEY.md section 7 hard part 3).
Label key=value pairs, host ports, and volume conflict keys are interned
into per-batch dictionaries and become bitset words — exact (dictionary
interning, not hashing), so there is no collision fallback to reason about.

Semantics mirrored bit-for-bit from the serial oracle (and therefore from
the reference, plugin/pkg/scheduler/algorithm):

  - initial per-node resource sums replay CheckPodsExceedingFreeResources'
    order-dependent skip-on-misfit accounting (predicates.go:160-185) over
    the snapshot's pod list order;
  - nonzero-request default sums (100 milliCPU / 200MiB per container,
    priorities.go:53-54) are kept separately for the priority math;
  - selector-spread groups replicate SelectorSpread.calculate_spread_priority
    (selector_spreading.go:43-114): per (namespace, selector-set) group,
    per-node match counts over ALL namespace pods (no phase filter — the
    reference lists everything), plus the max count over hosts outside the
    node table (unassigned "" bucket and unknown nodes);
  - volume conflict keys encode NoDiskConflict (predicates.go:75-137):
    GCE PD read-only nuance via a separate rw bitset, AWS EBS by volume id,
    Ceph RBD one key per (monitor, pool, image).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...core import labels as labelspkg
from ...core import types as api
from ..predicates import (filter_non_running_pods, get_resource_request,
                          node_schedulable, term_namespaces)
from ..priorities import get_nonzero_requests

WORD = 32


def _words(nbits: int) -> int:
    return max(1, (nbits + WORD - 1) // WORD)


class _Interner:
    """Exact string->bit-index dictionary."""

    def __init__(self):
        self.ids: Dict[object, int] = {}

    def intern(self, key: object) -> int:
        idx = self.ids.get(key)
        if idx is None:
            idx = len(self.ids)
            self.ids[key] = idx
        return idx

    def __len__(self) -> int:
        return len(self.ids)


def _set_bit(row: np.ndarray, idx: int) -> None:
    row[idx // WORD] |= np.uint32(1 << (idx % WORD))


@dataclass
class DevicePolicy:
    """Policy knobs the device engine supports beyond the default provider
    (scheduler-policy-file surface; ref: plugin/pkg/scheduler/api).

    - anti_affinity_label: ServiceAntiAffinity custom priority — spread a
      service's pods across values of this node label
      (selector_spreading.go:117-196); weight from the policy entry.
    - label_presence: CheckNodeLabelPresence custom predicates
      (predicates.go:292) — list of (labels, presence).
    - label_priorities: CalculateNodeLabelPriority custom priorities
      (priorities.go:148) — list of (label, presence, weight).
    """
    anti_affinity_label: Optional[str] = None
    anti_affinity_weight: int = 1
    label_presence: List[Tuple[Tuple[str, ...], bool]] = field(
        default_factory=list)
    label_priorities: List[Tuple[str, bool, int]] = field(
        default_factory=list)

    @property
    def needs_anti_affinity(self) -> bool:
        return self.anti_affinity_label is not None


@dataclass
class ClusterSnapshot:
    """What the algorithm would see through its listers at batch start.

    `existing_pods` must be in the merged pod lister's list order (scheduled
    pods then assumed pods — modeler.py list()); the order matters for the
    exceeding-resources replay. `pending_pods` are the pods to place, in
    FIFO order, and must not appear in `existing_pods`.
    """
    nodes: List[api.Node]
    existing_pods: List[api.Pod] = field(default_factory=list)
    services: List[api.Service] = field(default_factory=list)
    controllers: List[api.ReplicationController] = field(default_factory=list)
    pending_pods: List[api.Pod] = field(default_factory=list)
    # Full node cache (schedulable or not) for resolving existing pods'
    # topology domains in affinity terms — the serial path's node_by_name
    # resolves ANY cached node (ReadyNodeLister.get), not just candidates.
    # None -> fall back to `nodes`.
    all_nodes: Optional[List[api.Node]] = None


@dataclass
class NodeArrays:
    valid: np.ndarray       # bool[N] — real (unpadded) table row
    sched_ok: np.ndarray    # bool[N] — node_schedulable at encode time
                            #   (Ready, not Unknown, not cordoned); the
                            #   engine masks on valid & sched_ok, so a
                            #   dead node stays IN the table (its pods
                            #   keep their spread counts and topology
                            #   domains) but never receives a binding
    cpu_cap: np.ndarray     # i64[N] (milli)
    mem_cap: np.ndarray     # i64[N] (bytes)
    pod_cap: np.ndarray     # i32[N]
    label_words: np.ndarray  # u32[N, L]
    tie_rank: np.ndarray    # i32[N] — higher wins ties (name-descending pick)
    exceed_cpu: np.ndarray  # bool[N] — snapshot had a cpu-misfit pod
    exceed_mem: np.ndarray  # bool[N]
    aff_dom: np.ndarray     # i32[T, N] — topology-domain id per affinity
                            #   term (-1: node lacks the term's topology key)
    zone_id: np.ndarray     # i32[N] — ServiceAntiAffinity label value id
                            #   (-1: unlabeled; all -1 when not configured)
    zone_scratch: np.ndarray  # i32[Z] zeros — carries the zone-count shape
                            #   into the jitted step
    static_mask: np.ndarray  # bool[N] — AND of configured label-presence
                            #   predicates (CheckNodeLabelPresence)
    static_score: np.ndarray  # i64[N] — weighted sum of configured static
                            #   priorities (CalculateNodeLabelPriority)


@dataclass
class PodArrays:
    valid: np.ndarray       # bool[P]
    req_cpu: np.ndarray     # i64[P]
    req_mem: np.ndarray     # i64[P]
    zero_req: np.ndarray    # bool[P]
    nz_cpu: np.ndarray      # i64[P]
    nz_mem: np.ndarray      # i64[P]
    sel_words: np.ndarray   # u32[P, L]
    port_words: np.ndarray  # u32[P, PW]  (query == set for host ports)
    disk_qany: np.ndarray   # u32[P, K]
    disk_qrw: np.ndarray    # u32[P, K]
    disk_sany: np.ndarray   # u32[P, K]
    disk_srw: np.ndarray    # u32[P, K]
    host_idx: np.ndarray    # i32[P] (-1 unpinned, -2 pinned off-table)
    group_id: np.ndarray    # i32[P] (-1 = no spread selectors)
    member: np.ndarray      # i32[P, G]
    aff_req: np.ndarray     # bool[P, T] — pod requires affinity term t
    anti_req: np.ndarray    # bool[P, T] — pod requires anti-affinity term t
    aff_member: np.ndarray  # i32[P, T] — pod falls in term t's scope
                            #   (counts into the term's domains once placed)
    svc_group: np.ndarray   # i32[P] — ServiceAntiAffinity service group
                            #   (-1: pod has no matching service)
    svc_member: np.ndarray  # i32[P, S] — pod matches group's (ns, selector)


@dataclass
class StateArrays:
    cpu_used: np.ndarray    # i64[N]
    mem_used: np.ndarray    # i64[N]
    nz_cpu: np.ndarray      # i64[N]
    nz_mem: np.ndarray      # i64[N]
    pod_count: np.ndarray   # i32[N]
    port_bits: np.ndarray   # u32[N, PW]
    disk_any: np.ndarray    # u32[N, K]
    disk_rw: np.ndarray     # u32[N, K]
    spread: np.ndarray      # i32[G, N]
    aff_count: np.ndarray   # i32[T, D] — placed pods in term t's scope per
                            #   topology domain
    aff_total: np.ndarray   # i32[T] — placed pods in term t's scope anywhere
                            #   (drives the bootstrap rule)
    svc_count: np.ndarray   # i32[S, N] — service-group pods per table node
                            #   (zone reduction happens under the per-step
                            #   mask, matching the oracle's filtered lister)
    svc_total: np.ndarray   # i32[S] — service-group pods anywhere


@dataclass
class TableDelta:
    """Slot-granular change journal of one encode against the encoder's
    persistent node tables, consumed by the engine's device-resident
    table cache (engine._TableCache).

    `table_gen` is the encoder's monotonic mutation counter at encode
    time; `node_dirty_gen[slot]` / `state_dirty_gen[slot]` are the
    counter values when that slot's NodeConst-side / State-side rows
    last changed (captured under the encoder lock, so they are
    consistent with the host arrays this encode copied); `full_gen` is
    the counter at the last whole-table invalidation (capacity growth,
    which reshapes and re-shards every array). A cache whose content is
    current at generation g needs exactly the rows with dirty_gen > g
    re-uploaded — and a full re-upload iff full_gen > g. The split
    matters because State rows churn on every assumed pod while
    NodeConst rows move only on node events: a steady pipeline scatters
    a handful of NodeConst rows (or none) per tile.

    `encoder_id` names the encoder INSTANCE whose mutation counter the
    generations count. Generations from two encoders are incomparable
    even at identical table shapes (each counts its own timeline), so
    the engine's cache must also match on identity — otherwise a fresh
    encoder's low generations would read as "nothing changed" against a
    mirror holding another encoder's rows.

    `shard_epochs[s]` is the encoder's epoch for mesh shard s at encode
    time. An epoch moves only when the slot->shard mapping moves — a
    survivor re-shard after a shard owner's lease expires rewrites the
    whole vector (length changes to the survivor count, every entry
    bumps past the old maximum). A cached device mirror is only valid
    for a delta carrying the SAME vector: any difference means the rows
    it holds live on the wrong devices (or on a dead one), so the cache
    must miss and reseed from host truth — the materialized journal
    replay. Epochs are scoped to one encoder_id; across instances they
    are incomparable, exactly like the generations."""
    table_gen: int
    node_dirty_gen: np.ndarray   # i64[n_cap]
    state_dirty_gen: np.ndarray  # i64[n_cap]
    full_gen: int
    encoder_id: int
    shard_epochs: Tuple[int, ...] = (0,)

    def replay_slots(self, from_gen: int) -> np.ndarray:
        """Slots journaled on EITHER side since `from_gen` — the rows a
        mirror current at that generation must replay to catch up. A
        re-shard re-journals every occupied slot at fresh generations,
        so replay_slots(pre-failure full_gen) is exactly the row set
        rebuilt on the survivors (shard_replay_rows_total counts it)."""
        return np.nonzero((self.node_dirty_gen > from_gen)
                          | (self.state_dirty_gen > from_gen))[0]


@dataclass
class EncodeResult:
    node_tab: NodeArrays
    pod_batch: PodArrays
    init_state: StateArrays
    offgrid_max: np.ndarray      # i32[G]
    node_names: List[str]        # index -> name (padded entries "")
    n_nodes: int                 # valid (unpadded) node count
    n_pods: int                  # valid (unpadded) pod count
    # >1 when the resource arrays were narrowed to i32: every memory
    # quantity is stored divided by this exact common divisor
    mem_scale: int = 1
    # incremental-encoder only: the _Group objects behind pod_batch's
    # group_id column, so assume_assigned can bump their per-node rows
    # without re-matching selectors
    tile_groups: Optional[list] = None
    # incremental-encoder only: the encoder's state_epoch at encode time
    # (assume_assigned's fast path and the device-carry chain both
    # require no intervening mutations)
    state_epoch: int = -1
    # incremental-encoder only: dirty-slot journal for the engine's
    # device-resident table cache (None -> the encode has no generation
    # tracking and the engine always uploads the full tables)
    delta: Optional[TableDelta] = None


_I32_BOUND = 1 << 30  # slack below 2^31 for the x10 score scaling


def _maybe_narrow(nt: NodeArrays, st: StateArrays, pb: PodArrays,
                  weights_hint: int = 64):
    """Narrow the i64 resource/score arrays to i32 when provably exact.

    Memory quantities (bytes) exceed i32, but every formula that touches
    them is scale-invariant under an EXACT common divisor g:
    floor((a/g)*10 / (b/g)) == floor(a*10/b) when g|a and g|b (integer
    identity), and f64((a/g))/f64((b/g)) is the correctly-rounded
    quotient of the same rational as f64(a)/f64(b), hence bit-identical.
    So divide all memory values by their collective gcd and cast to i32
    — on TPU this halves the emulated-64-bit op count of the scan step,
    on CPU it halves the per-step memory traffic. Ineligible inputs
    (scaled values still too large, oversized cpu milli-values) keep the
    wide arrays; the engine compiles per-dtype, so both coexist.

    Returns (nt, st, pb, mem_scale)."""
    mem_arrays = [nt.mem_cap, st.mem_used, st.nz_mem, pb.req_mem,
                  pb.nz_mem]
    g = 0
    for arr in mem_arrays:
        if arr.size:
            g = int(np.gcd(int(g), int(np.gcd.reduce(np.abs(arr)))))
    if g == 0:
        g = 1
    # accumulation bound: the scan adds each pod's request into the used
    # vectors (zero-capacity nodes accept without limit), so the final
    # sums must stay in range too
    max_mem = max((int(np.max(np.abs(a))) if a.size else 0)
                  for a in mem_arrays) // g
    mem_growth = (int(np.max(pb.req_mem)) // g if pb.req_mem.size else 0) \
        * max(1, pb.req_mem.shape[0])
    nz_growth = (int(np.max(pb.nz_mem)) // g if pb.nz_mem.size else 0) \
        * max(1, pb.nz_mem.shape[0])
    cpu_arrays = [nt.cpu_cap, st.cpu_used, st.nz_cpu, pb.req_cpu,
                  pb.nz_cpu]
    max_cpu = max((int(np.max(np.abs(a))) if a.size else 0)
                  for a in cpu_arrays)
    cpu_growth = (int(np.max(pb.req_cpu)) if pb.req_cpu.size else 0) \
        * max(1, pb.req_cpu.shape[0])
    max_static = int(np.max(np.abs(nt.static_score))) \
        if nt.static_score.size else 0
    # composite = total * n + tie_rank; bound total conservatively
    n = nt.valid.shape[0]
    total_bound = (30 * weights_hint + max_static) * max(n, 1)
    if max(max_mem * 10, max_mem + mem_growth, nz_growth,
           max_cpu * 10, max_cpu + cpu_growth,
           total_bound) >= _I32_BOUND:
        return nt, st, pb, 1

    i32 = np.int32
    nt = replace(
        nt, cpu_cap=nt.cpu_cap.astype(i32),
        mem_cap=(nt.mem_cap // g).astype(i32),
        static_score=nt.static_score.astype(i32))
    st = replace(
        st, cpu_used=st.cpu_used.astype(i32),
        mem_used=(st.mem_used // g).astype(i32),
        nz_cpu=st.nz_cpu.astype(i32),
        nz_mem=(st.nz_mem // g).astype(i32))
    pb = replace(
        pb, req_cpu=pb.req_cpu.astype(i32),
        req_mem=(pb.req_mem // g).astype(i32),
        nz_cpu=pb.nz_cpu.astype(i32),
        nz_mem=(pb.nz_mem // g).astype(i32))
    return nt, st, pb, g


def _selector_matches(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def collect_affinity_terms(pending_pods: Sequence[api.Pod]):
    """Intern a pod batch's inter-pod affinity terms: ->
    (term_meta [(ns_scope frozenset, selector dict, topology_key)],
     pod_terms [(aff term ids, anti term ids)] per pod).

    The interning key is parity-critical (the oracle predicate resolves
    scope per pod, predicates.new_inter_pod_affinity_predicate) and is
    shared by BOTH encoders — the full snapshot encoder below and the
    incremental encoder's ledger-fed tier — so the two cannot drift."""
    term_ids: Dict[object, int] = {}
    term_meta: List[Tuple[frozenset, Dict[str, str], str]] = []
    pod_terms: List[Tuple[List[int], List[int]]] = []

    def intern_term(pod: api.Pod, term: api.PodAffinityTerm) -> int:
        ns_scope = frozenset(term_namespaces(pod, term))
        key = (ns_scope, frozenset(term.label_selector.items()),
               term.topology_key)
        tid = term_ids.get(key)
        if tid is None:
            tid = len(term_meta)
            term_ids[key] = tid
            term_meta.append((ns_scope, dict(term.label_selector),
                              term.topology_key))
        return tid

    for pod in pending_pods:
        aff = pod.spec.affinity
        aff_ids: List[int] = []
        anti_ids: List[int] = []
        if aff is not None:
            if aff.pod_affinity is not None:
                aff_ids = [intern_term(pod, t)
                           for t in aff.pod_affinity.required_during_scheduling]
            if aff.pod_anti_affinity is not None:
                anti_ids = [
                    intern_term(pod, t)
                    for t in aff.pod_anti_affinity.required_during_scheduling]
        pod_terms.append((aff_ids, anti_ids))
    return term_meta, pod_terms


def _matching_services(pod: api.Pod, services: Sequence[api.Service]
                       ) -> List[api.Service]:
    """Services whose selector covers the pod, in lister order (the
    get_pod_services rule: empty service namespace matches any pod
    namespace, empty selectors never match)."""
    return [svc for svc in services
            if (not svc.metadata.namespace
                or svc.metadata.namespace == pod.metadata.namespace)
            and svc.spec.selector
            and _selector_matches(svc.spec.selector, pod.metadata.labels)]


def _pod_spread_selectors(pod: api.Pod,
                          services: Sequence[api.Service],
                          controllers: Sequence[api.ReplicationController]
                          ) -> List[Dict[str, str]]:
    """Selectors SelectorSpread derives for a pod (selector_spreading.go:50-64
    via the service/controller listers; an empty lister namespace matches any
    pod namespace, matching the lister implementations)."""
    out: List[Dict[str, str]] = [
        dict(svc.spec.selector) for svc in _matching_services(pod, services)]
    for rc in controllers:
        if rc.metadata.namespace and \
                rc.metadata.namespace != pod.metadata.namespace:
            continue
        if rc.spec.selector and \
                _selector_matches(rc.spec.selector, pod.metadata.labels):
            out.append(dict(rc.spec.selector))
    return out


def _disk_keys(volume: api.Volume) -> Tuple[List[object], bool]:
    """(conflict keys, gce_read_only). Keys are hashable tuples; RBD yields
    one key per monitor so a shared monitor is a shared bit
    (predicates.go:75-117 isVolumeConflict)."""
    if volume.gce_persistent_disk is not None:
        return ([("gce", volume.gce_persistent_disk.pd_name)],
                volume.gce_persistent_disk.read_only)
    if volume.aws_elastic_block_store is not None:
        return [("ebs", volume.aws_elastic_block_store.volume_id)], False
    if volume.rbd is not None:
        return ([("rbd", mon, volume.rbd.rbd_pool, volume.rbd.rbd_image)
                 for mon in volume.rbd.ceph_monitors], False)
    return [], False


def encode_snapshot(snap: ClusterSnapshot, node_pad_to: int = 1,
                    pod_pad_to: Optional[int] = None,
                    policy: Optional[DevicePolicy] = None) -> EncodeResult:
    """Encode a cluster snapshot into device-ready arrays.

    `node_pad_to`: pad the node axis to a multiple of this (shard count);
    padded nodes have valid=False and never receive assignments.
    `pod_pad_to`: pad the pod axis to at least this many entries (stable
    scan lengths -> stable XLA compile cache); padded pods are invalid and
    never match or update state.
    """
    nodes = snap.nodes
    n_real = len(nodes)
    n_pad = max(1, -(-max(n_real, 1) // node_pad_to) * node_pad_to)
    p = len(snap.pending_pods)
    p_pad = max(1, p, pod_pad_to or 0)

    node_idx: Dict[str, int] = {n.metadata.name: i for i, n in enumerate(nodes)}

    # ------------------------------------------------------ dictionaries
    labels_dict = _Interner()
    for n in nodes:
        for kv in n.metadata.labels.items():
            labels_dict.intern(kv)
    for pod in snap.pending_pods:
        for kv in pod.spec.node_selector.items():
            labels_dict.intern(kv)

    ports_dict = _Interner()
    disk_dict = _Interner()
    for pod in list(snap.existing_pods) + list(snap.pending_pods):
        for c in pod.spec.containers:
            for cp in c.ports:
                if cp.host_port != 0:
                    ports_dict.intern(cp.host_port)
        for v in pod.spec.volumes:
            for key in _disk_keys(v)[0]:
                disk_dict.intern(key)

    L = _words(len(labels_dict))
    PW = _words(len(ports_dict))
    K = _words(len(disk_dict))

    # ------------------------------------------------------ node table
    nt = NodeArrays(
        valid=np.zeros(n_pad, bool),
        sched_ok=np.zeros(n_pad, bool),
        cpu_cap=np.zeros(n_pad, np.int64),
        mem_cap=np.zeros(n_pad, np.int64),
        pod_cap=np.zeros(n_pad, np.int32),
        label_words=np.zeros((n_pad, L), np.uint32),
        tie_rank=np.full(n_pad, -1, np.int32),
        exceed_cpu=np.zeros(n_pad, bool),
        exceed_mem=np.zeros(n_pad, bool),
        aff_dom=np.zeros((0, 0), np.int32),  # filled after term interning
        zone_id=np.full(n_pad, -1, np.int32),
        zone_scratch=np.zeros(1, np.int32),
        static_mask=np.ones(n_pad, bool),
        static_score=np.zeros(n_pad, np.int64))
    for i, n in enumerate(nodes):
        nt.valid[i] = True
        nt.sched_ok[i] = node_schedulable(n)
        cap = n.status.capacity
        nt.cpu_cap[i] = cap["cpu"].milli if "cpu" in cap else 0
        nt.mem_cap[i] = cap["memory"].value if "memory" in cap else 0
        nt.pod_cap[i] = cap["pods"].value if "pods" in cap else 0
        for kv in n.metadata.labels.items():
            _set_bit(nt.label_words[i], labels_dict.intern(kv))
    # deterministic tie-break = lexicographically largest name among the
    # max-score set (reference sort order: score desc then name desc,
    # api/types.go:164-169 + sort.Reverse) -> rank by name ascending
    for rank, name in enumerate(sorted(node_idx)):
        nt.tie_rank[node_idx[name]] = rank

    # ------------------------------------------------------ initial state
    # group pending pods by spread selector set first so G is known
    group_ids: Dict[object, int] = {}
    group_meta: List[Tuple[str, List[Dict[str, str]]]] = []
    pod_groups: List[int] = []
    for pod in snap.pending_pods:
        sels = _pod_spread_selectors(pod, snap.services, snap.controllers)
        if not sels:
            pod_groups.append(-1)
            continue
        key = (pod.metadata.namespace,
               frozenset(frozenset(s.items()) for s in sels))
        gid = group_ids.get(key)
        if gid is None:
            gid = len(group_meta)
            group_ids[key] = gid
            group_meta.append((pod.metadata.namespace, sels))
        pod_groups.append(gid)
    G = max(1, len(group_meta))

    # --------------------------------------------- inter-pod affinity terms
    # (BASELINE config 4; semantics defined by the oracle predicate,
    # predicates.new_inter_pod_affinity_predicate). Terms are interned by
    # (resolved namespace scope, selector, topology key); each term gets a
    # per-node topology-domain id and running scope counts in the carry.
    term_meta, pod_terms = collect_affinity_terms(snap.pending_pods)
    T = max(1, len(term_meta))

    def in_term_scope(p: api.Pod, tid: int) -> bool:
        # same matcher the oracle's pod_matches_term uses, against the
        # interned (namespace scope, selector) pair
        ns_scope, selector, _ = term_meta[tid]
        if p.metadata.namespace not in ns_scope:
            return False
        return labelspkg.selector_from_set(selector).matches(p.metadata.labels)

    # per-term topology domains over the node table
    aff_dom = np.full((T, n_pad), -1, np.int32)
    dom_ids: List[Dict[str, int]] = [dict() for _ in range(T)]
    for tid, (_, _, topo_key) in enumerate(term_meta):
        for i, n in enumerate(nodes):
            value = n.metadata.labels.get(topo_key)
            if value is None:
                continue
            dom = dom_ids[tid].setdefault(value, len(dom_ids[tid]))
            aff_dom[tid, i] = dom
    D = max(1, max((len(d) for d in dom_ids), default=0))

    aff_count = np.zeros((T, D), np.int32)
    aff_total = np.zeros(T, np.int32)
    if term_meta:
        # scope counts over the snapshot's running pods. A pod's domain is
        # resolved through the FULL node cache (all_nodes) — a peer on a
        # cached-but-unschedulable node still occupies its domain, exactly
        # as the serial predicate sees through node_by_name. Domains whose
        # value no candidate node carries can never satisfy a term, so
        # those peers count only toward the bootstrap total.
        labels_by_node: Dict[str, Dict[str, str]] = {
            n.metadata.name: n.metadata.labels
            for n in (snap.all_nodes if snap.all_nodes is not None
                      else snap.nodes)}
        for epod in filter_non_running_pods(snap.existing_pods):
            host_labels = labels_by_node.get(epod.spec.node_name)
            for tid, (_, _, topo_key) in enumerate(term_meta):
                if not in_term_scope(epod, tid):
                    continue
                aff_total[tid] += 1
                if host_labels is None:
                    continue
                value = host_labels.get(topo_key)
                dom = dom_ids[tid].get(value) if value is not None else None
                if dom is not None:
                    aff_count[tid, dom] += 1

    # ----------------------------------------- policy tier (DevicePolicy)
    pol = policy or DevicePolicy()
    for i, n in enumerate(nodes):
        node_labels = n.metadata.labels
        for wanted, presence in pol.label_presence:
            # ref: predicates.go:292 CheckNodeLabelPresence
            for label in wanted:
                exists = label in node_labels
                if (exists and not presence) or (not exists and presence):
                    nt.static_mask[i] = False
        for label, presence, weight in pol.label_priorities:
            # ref: priorities.go:148 — 0 or 10, weighted
            exists = label in node_labels
            success = (exists and presence) or (not exists and not presence)
            nt.static_score[i] += (10 if success else 0) * weight

    # ServiceAntiAffinity groups: one per (namespace, first matching
    # service's selector) over the pending pods (the oracle consults
    # services[0] only, selector_spreading.go:140)
    svc_groups: Dict[object, int] = {}
    svc_meta: List[Tuple[str, Dict[str, str]]] = []
    pod_svc_group: List[int] = []
    if pol.needs_anti_affinity:
        zone_vals: Dict[str, int] = {}
        for i, n in enumerate(nodes):
            value = n.metadata.labels.get(pol.anti_affinity_label)
            if value is not None:
                nt.zone_id[i] = zone_vals.setdefault(value, len(zone_vals))
        nt.zone_scratch = np.zeros(max(1, len(zone_vals)), np.int32)
        for pod in snap.pending_pods:
            matches = _matching_services(pod, snap.services)
            first = matches[0] if matches else None
            if first is None:
                pod_svc_group.append(-1)
                continue
            key = (pod.metadata.namespace,
                   frozenset(first.spec.selector.items()))
            gid = svc_groups.get(key)
            if gid is None:
                gid = len(svc_meta)
                svc_groups[key] = gid
                svc_meta.append((pod.metadata.namespace,
                                 dict(first.spec.selector)))
            pod_svc_group.append(gid)
    else:
        pod_svc_group = [-1] * len(snap.pending_pods)
    S = max(1, len(svc_meta))

    svc_count = np.zeros((S, n_pad), np.int32)
    svc_total = np.zeros(S, np.int32)
    for gid, (ns, sel) in enumerate(svc_meta):
        # the oracle lists via pod_lister.list(selector) with NO phase
        # filter (selector_spreading.go:140-147)
        for epod in snap.existing_pods:
            if epod.metadata.namespace != ns:
                continue
            if not _selector_matches(sel, epod.metadata.labels):
                continue
            svc_total[gid] += 1
            i = node_idx.get(epod.spec.node_name)
            if i is not None:
                svc_count[gid, i] += 1

    st = StateArrays(
        cpu_used=np.zeros(n_pad, np.int64),
        mem_used=np.zeros(n_pad, np.int64),
        nz_cpu=np.zeros(n_pad, np.int64),
        nz_mem=np.zeros(n_pad, np.int64),
        pod_count=np.zeros(n_pad, np.int32),
        port_bits=np.zeros((n_pad, PW), np.uint32),
        disk_any=np.zeros((n_pad, K), np.uint32),
        disk_rw=np.zeros((n_pad, K), np.uint32),
        spread=np.zeros((G, n_pad), np.int32),
        aff_count=aff_count,
        aff_total=aff_total,
        svc_count=svc_count,
        svc_total=svc_total)
    nt.aff_dom = aff_dom
    offgrid: List[Dict[str, int]] = [dict() for _ in range(G)]

    by_node: Dict[int, List[api.Pod]] = {}
    for pod in snap.existing_pods:
        # spread counts use the UNfiltered pod list (selector_spreading.go)
        for gid, (ns, sels) in enumerate(group_meta):
            if pod.metadata.namespace != ns:
                continue
            if any(_selector_matches(s, pod.metadata.labels) for s in sels):
                host = pod.spec.node_name
                i = node_idx.get(host)
                if i is None:
                    offgrid[gid][host] = offgrid[gid].get(host, 0) + 1
                else:
                    st.spread[gid, i] += 1
        # everything below mirrors MapPodsToMachines' phase filter
        # (predicates.go:429,445)
        if pod.status.phase in (api.POD_SUCCEEDED, api.POD_FAILED):
            continue
        i = node_idx.get(pod.spec.node_name)
        if i is None:
            continue
        by_node.setdefault(i, []).append(pod)

    for i, pods in by_node.items():
        cpu_cap = int(nt.cpu_cap[i])
        mem_cap = int(nt.mem_cap[i])
        cpu_used = 0
        mem_used = 0
        for pod in pods:
            # order-dependent skip-on-misfit replay (predicates.go:160-185)
            req_cpu, req_mem = get_resource_request(pod)
            fits_cpu = cpu_cap == 0 or (cpu_cap - cpu_used) >= req_cpu
            fits_mem = mem_cap == 0 or (mem_cap - mem_used) >= req_mem
            if not fits_cpu:
                nt.exceed_cpu[i] = True
            elif not fits_mem:
                nt.exceed_mem[i] = True
            else:
                cpu_used += req_cpu
                mem_used += req_mem
            for c in pod.spec.containers:
                nz_c, nz_m = get_nonzero_requests(c.resources.requests)
                st.nz_cpu[i] += nz_c
                st.nz_mem[i] += nz_m
                for cp in c.ports:
                    if cp.host_port != 0:
                        _set_bit(st.port_bits[i],
                                 ports_dict.intern(cp.host_port))
            for v in pod.spec.volumes:
                keys, gce_ro = _disk_keys(v)
                for key in keys:
                    bit = disk_dict.intern(key)
                    _set_bit(st.disk_any[i], bit)
                    if v.gce_persistent_disk is not None and not gce_ro:
                        _set_bit(st.disk_rw[i], bit)
        st.cpu_used[i] = cpu_used
        st.mem_used[i] = mem_used
        st.pod_count[i] = len(pods)

    offgrid_max = np.zeros(G, np.int32)
    for gid, buckets in enumerate(offgrid):
        if buckets:
            offgrid_max[gid] = max(buckets.values())

    # ------------------------------------------------------ pod batch
    pb = PodArrays(
        valid=np.zeros(p_pad, bool),
        req_cpu=np.zeros(p_pad, np.int64),
        req_mem=np.zeros(p_pad, np.int64),
        zero_req=np.zeros(p_pad, bool),
        nz_cpu=np.zeros(p_pad, np.int64),
        nz_mem=np.zeros(p_pad, np.int64),
        sel_words=np.zeros((p_pad, L), np.uint32),
        port_words=np.zeros((p_pad, PW), np.uint32),
        disk_qany=np.zeros((p_pad, K), np.uint32),
        disk_qrw=np.zeros((p_pad, K), np.uint32),
        disk_sany=np.zeros((p_pad, K), np.uint32),
        disk_srw=np.zeros((p_pad, K), np.uint32),
        host_idx=np.full(p_pad, -1, np.int32),
        group_id=np.full(p_pad, -1, np.int32),
        member=np.zeros((p_pad, G), np.int32),
        aff_req=np.zeros((p_pad, T), bool),
        anti_req=np.zeros((p_pad, T), bool),
        aff_member=np.zeros((p_pad, T), np.int32),
        svc_group=np.full(p_pad, -1, np.int32),
        svc_member=np.zeros((p_pad, S), np.int32))
    for j, pod in enumerate(snap.pending_pods):
        pb.valid[j] = True
        req_cpu, req_mem = get_resource_request(pod)
        pb.req_cpu[j] = req_cpu
        pb.req_mem[j] = req_mem
        pb.zero_req[j] = req_cpu == 0 and req_mem == 0
        for c in pod.spec.containers:
            nz_c, nz_m = get_nonzero_requests(c.resources.requests)
            pb.nz_cpu[j] += nz_c
            pb.nz_mem[j] += nz_m
            for cp in c.ports:
                if cp.host_port != 0:
                    _set_bit(pb.port_words[j], ports_dict.intern(cp.host_port))
        for kv in pod.spec.node_selector.items():
            _set_bit(pb.sel_words[j], labels_dict.intern(kv))
        for v in pod.spec.volumes:
            keys, gce_ro = _disk_keys(v)
            is_gce = v.gce_persistent_disk is not None
            for key in keys:
                bit = disk_dict.intern(key)
                _set_bit(pb.disk_sany[j], bit)
                if is_gce and gce_ro:
                    _set_bit(pb.disk_qrw[j], bit)
                else:
                    _set_bit(pb.disk_qany[j], bit)
                if is_gce and not gce_ro:
                    _set_bit(pb.disk_srw[j], bit)
        if pod.spec.node_name:
            pb.host_idx[j] = node_idx.get(pod.spec.node_name, -2)
        aff_ids, anti_ids = pod_terms[j]
        for tid in aff_ids:
            pb.aff_req[j, tid] = True
        for tid in anti_ids:
            pb.anti_req[j, tid] = True
        if term_meta:
            for tid in range(len(term_meta)):
                if in_term_scope(pod, tid):
                    pb.aff_member[j, tid] = 1
        pb.group_id[j] = pod_groups[j]
        for gid, (ns, sels) in enumerate(group_meta):
            if pod.metadata.namespace != ns:
                continue
            if any(_selector_matches(s, pod.metadata.labels) for s in sels):
                pb.member[j, gid] = 1
        pb.svc_group[j] = pod_svc_group[j]
        for gid, (ns, sel) in enumerate(svc_meta):
            if pod.metadata.namespace == ns and \
                    _selector_matches(sel, pod.metadata.labels):
                pb.svc_member[j, gid] = 1

    nt, st, pb, mem_scale = _maybe_narrow(nt, st, pb)
    return EncodeResult(
        node_tab=nt, pod_batch=pb, init_state=st, offgrid_max=offgrid_max,
        node_names=[n.metadata.name for n in nodes] + [""] * (n_pad - n_real),
        n_nodes=n_real, n_pods=p, mem_scale=mem_scale)
