"""Shard-failure tolerance for the sharded scheduling mesh.

Every mesh shard (one device slice of the node axis, PR 15's block
sharding over stable slots) is guarded by a LEASE riding the exact
CAS/fencing machinery HA leadership already uses
(utils/leaderelection.py over the `leases` resource): the shard's
owner runs an ordinary LeaderElector against `mesh-shard-<i>`, renewing
on its cadence; a dead host simply stops renewing. Nobody tells the
engine a host died — the engine OBSERVES it, the same way a standby
observes a dead leader: the lease record's resourceVersion stops
moving, and after `lease_duration` on the OBSERVER'S monotonic clock
the shard is expired (wall-clock jumps can neither kill nor revive a
shard, same rule as LeaderElector._observe).

Recovery is a three-step protocol, run between tiles (the scan itself
is never interrupted mid-dispatch):

  1. FENCE — the coordinator CAS-takes the dead shard's lease,
     advancing `lease_transitions` (utils/leaderelection.fence_lease).
     The term is the fencing token: a resurrecting owner's renew
     carries a stale resourceVersion and loses the CAS, so nothing it
     does under the old term can land after the fence. A fence that
     LOSES the CAS means the owner renewed after all — the shard is
     alive and drops out of the dead set.
  2. RE-SHARD — the stable slot->device mapping re-blocks onto the
     survivors: IncrementalEncoder.reshard() re-rounds capacity to a
     survivor multiple, re-journals every occupied slot, advances
     full_gen, and replaces the per-shard epoch vector; the engine
     drops its compiled programs and device mirror
     (BatchEngine.reshard). The next dispatch reseeds the mirror with
     one full sharded upload — the TableDelta journal replay
     materialized, every row landing on its new owner.
  3. DROP IN-FLIGHT — any tile dispatched against the old epoch vector
     is dropped whole and its pods requeued (sched/batch.py's
     shard-epoch fence in _finalize — the PR-5 commit-time health gate
     at shard granularity). Zero bindings ever commit under a dead
     shard's stale epoch.

Metrics (pinned in utils/metrics.py SHARD_COUNTERS):
`shard_lease_transitions_total` per fence, `shard_reshards_total` per
applied re-shard, `shard_replay_rows_total` for the journal rows
rebuilt on survivors. The shard-kill soak (kubemark/shard_soak.py)
gates on all three plus bit-exact binding parity with an unfailed run
of the surviving shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ...core.errors import Conflict, NotFound
from ...utils.clock import REAL, Clock
from ...utils.leaderelection import (LeaderElectionConfig, LeaderElector,
                                     fence_lease)
from ...utils.metrics import MetricsRegistry, global_metrics


def shard_lease_name(shard: int, prefix: str = "mesh-shard") -> str:
    return f"{prefix}-{shard}"


class ShardLeaseSet:
    """The OWNER side: one LeaderElector per mesh shard. On a real pod
    each host runs the elector for the shard(s) it owns; the single-box
    emulation (DIVERGENCES #34) runs all of them in one process and
    kills an owner by stopping its renewals — elector.kill(), the same
    no-release crash semantics the control-plane chaos uses."""

    def __init__(self, client, n_shards: int,
                 identity: str = "shard-owner",
                 prefix: str = "mesh-shard",
                 namespace: str = "kube-system",
                 lease_duration: float = 15.0,
                 renew_deadline: float = 10.0,
                 retry_period: float = 2.0,
                 clock: Optional[Clock] = None,
                 metrics: Optional[MetricsRegistry] = None):
        clock = clock or REAL
        self.namespace = namespace
        self.electors: List[LeaderElector] = [
            LeaderElector(
                client,
                LeaderElectionConfig(
                    lease_name=shard_lease_name(i, prefix),
                    identity=f"{identity}-{i}", namespace=namespace,
                    lease_duration=lease_duration,
                    renew_deadline=renew_deadline,
                    retry_period=retry_period, clock=clock),
                metrics=metrics)
            for i in range(n_shards)]

    def lease_names(self) -> List[str]:
        return [e.config.lease_name for e in self.electors]

    def acquire_all(self) -> bool:
        """One synchronous CAS round per shard (the deterministic soak
        drives renewal by hand instead of elector threads). True iff
        every shard's owner holds its lease after the round."""
        return all(e.try_acquire_or_renew() for e in self.electors)

    def renew(self, skip: Sequence[int] = ()) -> None:
        """Renew every live owner's lease; `skip` shards are dead hosts
        whose renewals simply never happen (their records age out on
        the observers' clocks)."""
        dead = set(skip)
        for i, e in enumerate(self.electors):
            if i not in dead:
                e.try_acquire_or_renew()

    def run_all(self) -> "ShardLeaseSet":
        for e in self.electors:
            e.run()
        return self

    def kill(self, shard: int) -> None:
        """Crash shard `shard`'s owner: renewals stop, NO release — the
        observers must wait out expiry, exactly like a real dead host."""
        self.electors[shard].kill()

    def stop(self) -> None:
        for e in self.electors:
            e.stop(release=False)


class ShardLeaseMonitor:
    """The OBSERVER side: the scheduling engine's view of the shard
    leases. poll() re-reads each lease and applies LeaderElector's
    observation rule — the clock resets only when the resourceVersion
    MOVES — so a dead owner's frozen record ages toward expiry on THIS
    process's monotonic clock no matter how often it is re-read.
    Shards are tracked by lease name; retire() drops fenced shards so
    survivor indices stay compact (and aligned with the re-blocked
    slot->device mapping)."""

    def __init__(self, client, lease_names: Sequence[str],
                 identity: str = "reshard-coordinator",
                 namespace: str = "kube-system",
                 lease_duration: float = 15.0,
                 clock: Optional[Clock] = None,
                 metrics: Optional[MetricsRegistry] = None):
        self.client = client
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.clock = clock or REAL
        self.metrics = metrics or global_metrics
        self._names: List[str] = list(lease_names)
        self._rv = {}       # lease name -> last observed resourceVersion
        self._at = {}       # lease name -> monotonic() when rv last moved
        self._term = {}     # lease name -> last observed lease_transitions

    @property
    def n_shards(self) -> int:
        return len(self._names)

    def poll(self) -> List[int]:
        """One observation round. Returns the indices (current shard
        numbering) of shards whose lease is EXPIRED on this monitor's
        clock: observed at least once, and unmoved for lease_duration.
        A lease never yet observed (owner still starting) is not
        judged; an unreadable one keeps its last observation and ages
        toward expiry like any other silence."""
        for name in self._names:
            try:
                lease = self.client.get("leases", name, self.namespace)
            except Exception:
                continue
            rv = lease.metadata.resource_version
            if rv != self._rv.get(name):
                self._rv[name] = rv
                self._at[name] = self.clock.monotonic()
                self._term[name] = lease.spec.lease_transitions
        now = self.clock.monotonic()
        return [i for i, name in enumerate(self._names)
                if name in self._at
                and now >= self._at[name] + self.lease_duration]

    def term(self, shard: int) -> int:
        """Last observed fencing term (lease_transitions) of a shard."""
        return self._term.get(self._names[shard], 0)

    def fence(self, shard: int) -> Optional[int]:
        """CAS-take the expired shard's lease under a new term. Returns
        the advanced term, or None when the CAS loses — the owner
        renewed between poll and fence, so the shard is NOT dead and
        must stay in the mesh."""
        name = self._names[shard]
        try:
            term = fence_lease(self.client, name, self.identity,
                               self.namespace)
        except (Conflict, NotFound):
            # re-observe immediately: the renew that beat us restarts
            # the shard's liveness window
            try:
                lease = self.client.get("leases", name, self.namespace)
                self._rv[name] = lease.metadata.resource_version
                self._at[name] = self.clock.monotonic()
                self._term[name] = lease.spec.lease_transitions
            except Exception:
                pass
            return None
        except Exception:
            return None
        self.metrics.inc("shard_lease_transitions_total", {"lease": name})
        self._term[name] = term
        return term

    def retire(self, shards: Sequence[int]) -> None:
        """Drop fenced shards from the watch set; the survivors compact
        in order, matching the re-blocked slot->device mapping."""
        gone = set(shards)
        self._names = [n for i, n in enumerate(self._names)
                       if i not in gone]


@dataclass
class ShardReshard:
    """One applied survivor re-shard, for gates and MULTIHOST.json."""
    dead: Tuple[int, ...]           # shard indices, pre-reshard numbering
    dead_leases: Tuple[str, ...]
    fence_terms: Tuple[int, ...]    # advanced lease_transitions per fence
    survivors: int                  # shard count after the re-shard
    replay_rows: int                # journal rows rebuilt on survivors
    shard_epochs: Tuple[int, ...]   # encoder epoch vector after


def survivor_mesh(mesh, dead: Sequence[int], node_axis: str = "nodes"):
    """The mesh minus the dead shards' devices, order preserved (block
    shard s of the new mesh = the s'th surviving device)."""
    import numpy as np
    from jax.sharding import Mesh
    gone = set(dead)
    devs = [d for i, d in enumerate(mesh.devices.reshape(-1))
            if i not in gone]
    if not devs:
        return None
    return Mesh(np.array(devs), (node_axis,))


def reshard_survivors(dead: Sequence[int], monitor: ShardLeaseMonitor,
                      encoder=None, engine=None,
                      metrics: Optional[MetricsRegistry] = None
                      ) -> Optional[ShardReshard]:
    """The coordinator: fence the dead shards, then re-shard the slot
    mapping onto the survivors. Shards whose fence CAS loses (owner
    renewed after all) drop out; if none remain, no re-shard happens
    and None returns. Otherwise the encoder re-journals and re-epochs
    (journal replay from full_gen lands every occupied row on its new
    owner at the next dispatch), the engine rebuilds over the survivor
    mesh, and the fenced shards retire from the monitor."""
    metrics = metrics or global_metrics
    fenced: List[int] = []
    terms: List[int] = []
    for s in dead:
        term = monitor.fence(s)
        if term is not None:
            fenced.append(s)
            terms.append(term)
    if not fenced:
        return None
    names = tuple(monitor._names[s] for s in fenced)
    new_mesh = None
    survivors = max(1, monitor.n_shards - len(fenced))
    if engine is not None and engine.mesh is not None:
        new_mesh = survivor_mesh(engine.mesh, fenced, engine.node_axis)
        survivors = 1 if new_mesh is None else new_mesh.devices.size
    replay = 0
    epochs: Tuple[int, ...] = ()
    if encoder is not None:
        replay = encoder.reshard(survivors)
        epochs = encoder.shard_epochs()
    if engine is not None:
        engine.reshard(new_mesh)
    monitor.retire(fenced)
    metrics.inc("shard_reshards_total")
    metrics.inc("shard_replay_rows_total", by=replay)
    return ShardReshard(dead=tuple(fenced), dead_leases=names,
                        fence_terms=tuple(terms), survivors=survivors,
                        replay_rows=replay, shard_epochs=epochs)
