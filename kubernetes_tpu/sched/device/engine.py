"""Device kernel: batch scheduling as a jitted `lax.scan` over pending pods.

Replaces the reference's per-pod serial hot loop
(plugin/pkg/scheduler/generic_scheduler.go:111 findNodesThatFit,
:164 PrioritizeNodes, :95 selectHost) with one compiled program:

  per scan step (one pod)           reference equivalent
  -------------------------------   -----------------------------------
  predicate masks over [N] vectors  for node { for predicate { ... } }
  int 0..10 score vectors           for priority { for node { ... } }
  masked argmax + tie-rank argmax   sort + rand tie-break (selectHost)
  one-hot state update              Modeler.AssumePod (modeler.go:113)

Sequential-commit semantics (pod k consumes the capacity pod k+1 sees —
the reference serializes scheduleOne for exactly this reason,
scheduler.go:120) live in the scan carry: per-node running sums, port and
volume-conflict bitsets, and selector-spread counts.

Numerics are bit-exact with the serial oracle: resource sums in int64,
score integer division via floor (all operands non-negative), and the two
float formulas (BalancedResourceAllocation priorities.go:198,
SelectorSpread selector_spreading.go:80-114) in float64 exactly as the
oracle computes them (TPU runs f64/s64 via XLA emulation; the per-step
vectors are small so the emulation cost is noise).

Multi-chip: the node axis shards across a `jax.sharding.Mesh` — every
per-step op is node-local except the score max / tie-rank argmax, which
XLA lowers to ICI all-reduces (the "argmax-reduced over ICI" design from
BASELINE.json).

Deliberate divergence from the reference (documented, SURVEY.md section 7
step 4): ties break deterministically to the lexicographically largest
node name instead of rand.Int()%len (generic_scheduler.go:105); the chosen
host is always a member of the reference's max-score set.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .tables import ClusterSnapshot, EncodeResult, encode_snapshot

DEFAULT_WEIGHTS = (1, 1, 1)  # LeastRequested, Balanced, SelectorSpread
                             # (algorithmprovider/defaults/defaults.go:54-96)


def ensure_x64() -> None:
    """The engine's parity contract needs int64 resource sums and float64
    score formulas (the oracle — and the Go reference — compute in 64-bit).
    JAX drops 64-bit types unless jax_enable_x64 is on, so the engine
    requires it process-wide. Called at engine construction, not module
    import, so merely importing the library never mutates global JAX
    config; applications combining this engine with f32-default JAX code
    in one process should pin dtypes explicitly in that code."""
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)


class NodeConst(NamedTuple):
    valid: jax.Array       # bool[N]
    sched_ok: jax.Array    # bool[N] — node_schedulable at encode time;
                           #   dead nodes stay in the table but masked
    cpu_cap: jax.Array     # i64[N]
    mem_cap: jax.Array     # i64[N]
    pod_cap: jax.Array     # i32[N]
    labels: jax.Array      # u32[N, L]
    tie_rank: jax.Array    # i32[N]
    exceed_cpu: jax.Array  # bool[N]
    exceed_mem: jax.Array  # bool[N]
    offgrid_max: jax.Array  # i32[G]
    aff_dom: jax.Array     # i32[T, N]
    zone_id: jax.Array     # i32[N]
    zone_scratch: jax.Array  # i32[Z] zeros (shape carrier)
    static_mask: jax.Array  # bool[N]
    static_score: jax.Array  # i64[N]


class PodXs(NamedTuple):
    valid: jax.Array       # bool[P]
    req_cpu: jax.Array     # i64[P]
    req_mem: jax.Array     # i64[P]
    zero_req: jax.Array    # bool[P]
    nz_cpu: jax.Array      # i64[P]
    nz_mem: jax.Array      # i64[P]
    sel: jax.Array         # u32[P, L]
    ports: jax.Array       # u32[P, PW]
    qany: jax.Array        # u32[P, K]
    qrw: jax.Array         # u32[P, K]
    sany: jax.Array        # u32[P, K]
    srw: jax.Array         # u32[P, K]
    host_idx: jax.Array    # i32[P]
    group_id: jax.Array    # i32[P]
    member: jax.Array      # i32[P, G]
    aff_req: jax.Array     # bool[P, T]
    anti_req: jax.Array    # bool[P, T]
    aff_member: jax.Array  # i32[P, T]
    svc_group: jax.Array   # i32[P]
    svc_member: jax.Array  # i32[P, S]


class State(NamedTuple):
    cpu_used: jax.Array    # i64[N]
    mem_used: jax.Array    # i64[N]
    nz_cpu: jax.Array      # i64[N]
    nz_mem: jax.Array      # i64[N]
    pod_count: jax.Array   # i32[N]
    port_bits: jax.Array   # u32[N, PW]
    disk_any: jax.Array    # u32[N, K]
    disk_rw: jax.Array     # u32[N, K]
    spread: jax.Array      # i32[G, N]
    aff_count: jax.Array   # i32[T, D]
    aff_total: jax.Array   # i32[T]
    svc_count: jax.Array   # i32[S, N]
    svc_total: jax.Array   # i32[S]


def _floordiv_exact(num: jax.Array, den: jax.Array,
                    inv_den: jax.Array) -> jax.Array:
    """floor(num/den) for |num| < 2^53, den >= 1, computed without integer
    division: i64 vector division has no SIMD path on CPU and is emulated
    on TPU (measured 82us/pod of the scan step — the single hottest op).
    A f64 reciprocal-multiply estimate is within 1 of the true quotient
    (relative error ~2^-51 on an exact f64 product), so two integer
    compare-corrections make it exact."""
    dt = num.dtype
    e = jnp.floor(num.astype(jnp.float64) * inv_den).astype(dt)
    e = e + ((e + 1) * den <= num).astype(dt)
    e = e - (e * den > num).astype(dt)
    return e


def _mask_and_score(node: NodeConst, weights: Tuple[int, int, int],
                    anti_weight: int, state: State, pod,
                    has_aff: bool = True, has_spread: bool = True,
                    iota: Optional[jax.Array] = None,
                    spread_max_override: Optional[jax.Array] = None
                    ) -> Tuple[jax.Array, jax.Array]:
    """Predicate mask + priority totals for ONE pod against `state`.

    The shared core of the scan step and the extender sidecar's
    filter/prioritize probe (plugin/pkg/scheduler/extender.go:95,119 —
    the extender server answers per-pod, stateless between requests).

    `iota` overrides the node indices the lanes stand for (the
    speculative repair pass rescores a GATHERED lane set, so lane i is
    node iota[i], not node i — HostName matching must use the real
    index)."""
    n = node.valid.shape[0]
    if iota is None:
        iota = jnp.arange(n, dtype=jnp.int32)
    # score dtype follows the resource arrays: i64 normally, i32 when the
    # encoder narrowed (exact gcd rescale of memory + bounds checks make
    # the narrow math bit-identical — see tables._maybe_narrow)
    sdt = node.cpu_cap.dtype

    # ---- predicate masks (predicates.go:127,192,250,258,403) ----
    fits_count = state.pod_count < node.pod_cap
    free_cpu = (node.cpu_cap == 0) | \
        (node.cpu_cap - state.cpu_used >= pod.req_cpu)
    free_mem = (node.mem_cap == 0) | \
        (node.mem_cap - state.mem_used >= pod.req_mem)
    res_ok = jnp.where(
        pod.zero_req, fits_count,
        fits_count & ~node.exceed_cpu & ~node.exceed_mem & free_cpu & free_mem)
    port_conflict = jnp.any((state.port_bits & pod.ports[None, :]) != 0,
                            axis=1)
    sel_ok = jnp.all((pod.sel[None, :] & ~node.labels) == 0, axis=1)
    host_ok = jnp.where(pod.host_idx == -1, jnp.ones(n, bool),
                        iota == pod.host_idx)
    disk_conflict = jnp.any(
        ((state.disk_any & pod.qany[None, :])
         | (state.disk_rw & pod.qrw[None, :])) != 0, axis=1)

    mask = (node.valid & node.sched_ok & pod.valid & res_ok
            & ~port_conflict & sel_ok
            & host_ok & ~disk_conflict & node.static_mask)

    if has_aff:
        # inter-pod affinity/anti-affinity (BASELINE config 4; semantics =
        # sched.predicates.new_inter_pod_affinity_predicate). Per term t the
        # node's scope count is the placed-pod count in its topology domain;
        # affinity needs the key present and count>0 (or the bootstrap: the
        # pod self-matches an empty-scope term), anti-affinity needs
        # count==0. Compiled out (has_aff=False) when the batch carries no
        # terms — the tier is then provably all-True.
        has_key = node.aff_dom >= 0                                   # [T, N]
        counts = jnp.take_along_axis(
            state.aff_count, jnp.maximum(node.aff_dom, 0), axis=1)    # [T, N]
        counts = jnp.where(has_key, counts, 0)
        boot = (pod.aff_member > 0) & (state.aff_total == 0)          # [T]
        aff_ok = jnp.all(~pod.aff_req[:, None]
                         | (has_key & (boot[:, None] | (counts > 0))),
                         axis=0)                                      # [N]
        anti_ok = jnp.all(~pod.anti_req[:, None] | (counts == 0), axis=0)
        mask = mask & aff_ok & anti_ok

    # ---- priorities (priorities.go:33,77,198; selector_spreading.go:80) ----
    safe_cpu = jnp.maximum(node.cpu_cap, 1)
    safe_mem = jnp.maximum(node.mem_cap, 1)
    # reciprocals of loop-invariant capacities: XLA hoists them out of the
    # scan, so each step pays multiplies, not divisions
    inv_cpu = 1.0 / safe_cpu.astype(jnp.float64)
    inv_mem = 1.0 / safe_mem.astype(jnp.float64)
    tc = state.nz_cpu + pod.nz_cpu
    tm = state.nz_mem + pod.nz_mem
    cpu_score = jnp.where(
        (node.cpu_cap == 0) | (tc > node.cpu_cap), 0,
        _floordiv_exact((node.cpu_cap - tc) * 10, safe_cpu, inv_cpu))
    mem_score = jnp.where(
        (node.mem_cap == 0) | (tm > node.mem_cap), 0,
        _floordiv_exact((node.mem_cap - tm) * 10, safe_mem, inv_mem))
    # operands are 0..20, so the halving is a shift, not a division
    least_requested = (cpu_score + mem_score) >> 1

    # true f64 division here, NOT reciprocal-multiply: the oracle computes
    # this fraction with Python float division and the floor below must
    # agree bit-for-bit (f64 division is SIMD-cheap; only the integer
    # division above was hot)
    cpu_frac = jnp.where(node.cpu_cap == 0, jnp.float64(1.0),
                         tc.astype(jnp.float64) / safe_cpu.astype(jnp.float64))
    mem_frac = jnp.where(node.mem_cap == 0, jnp.float64(1.0),
                         tm.astype(jnp.float64) / safe_mem.astype(jnp.float64))
    diff = jnp.abs(cpu_frac - mem_frac)
    balanced = jnp.where(
        (cpu_frac >= 1.0) | (mem_frac >= 1.0), jnp.zeros((), sdt),
        jnp.floor(jnp.float64(10.0) - diff * 10.0).astype(sdt))

    total = (weights[0] * least_requested + weights[1] * balanced
             + node.static_score)

    if has_spread:
        gid = jnp.maximum(pod.group_id, 0)
        counts = state.spread[gid]
        # spread_max_override: the speculative repair rescored a
        # GATHERED lane set whose local max is not the global one — it
        # passes the block-start per-group max (exact while the
        # group's max-exceeded flag is unset; see _spec_step)
        if spread_max_override is None:
            max_count = jnp.maximum(jnp.max(counts),
                                    node.offgrid_max[gid])
        else:
            max_count = spread_max_override[gid]
        spread_f = (10.0 * (max_count - counts).astype(jnp.float64)
                    / jnp.maximum(max_count, 1).astype(jnp.float64))
        spread = jnp.where((pod.group_id < 0) | (max_count == 0),
                           jnp.full((), 10, sdt),
                           jnp.floor(spread_f).astype(sdt))
        total = total + weights[2] * spread
    # has_spread=False: every pod scores the constant 10 on all nodes
    # (group_id < 0), which shifts all totals equally and cannot change
    # the argmax — compiled out.

    if anti_weight:
        # ServiceAntiAffinity (selector_spreading.go:117-196): spread the
        # pod's service across zone-label values. The oracle only counts
        # peers on nodes that passed THIS pod's predicates, so the zone
        # reduction happens under `mask`.
        g = jnp.maximum(pod.svc_group, 0)
        row = state.svc_count[g]                               # i32[N]
        labeled = node.zone_id >= 0
        zidx = jnp.maximum(node.zone_id, 0)
        contrib = jnp.where(mask & labeled, row, 0)
        zc = jnp.zeros_like(node.zone_scratch).at[zidx].add(
            contrib, mode="drop")                              # i32[Z]
        count_n = zc[zidx]                                     # i32[N]
        svc_total = jnp.where(pod.svc_group >= 0, state.svc_total[g], 0)
        sa_f = (10.0 * (svc_total - count_n).astype(jnp.float64)
                / jnp.maximum(svc_total, 1).astype(jnp.float64))
        sa = jnp.where(
            ~labeled, jnp.zeros((), sdt),
            jnp.where(svc_total > 0,
                      jnp.floor(sa_f).astype(sdt),
                      jnp.full((), 10, sdt)))
        total = total + anti_weight * sa

    return mask, total


def _commit_node_local(state: State, pod, j: jax.Array,
                       fit_any: jax.Array):
    """The node-local half of the assume-pod commit (modeler.go:113):
    scatter the pod's resources/ports/disks onto the picked lane.
    Shared by the scan step and the speculative repair step — the spec
    engine's contract is bit-identity with the scan, so the commit
    semantics must have exactly one implementation.

    -> (dict of updated node-local State fields, add32 for the callers'
    global-tier updates)."""
    add = jnp.where(fit_any, jnp.ones((), state.cpu_used.dtype),
                    jnp.zeros((), state.cpu_used.dtype))
    add32 = add.astype(jnp.int32)
    fields = dict(
        cpu_used=state.cpu_used.at[j].add(add * pod.req_cpu),
        mem_used=state.mem_used.at[j].add(add * pod.req_mem),
        nz_cpu=state.nz_cpu.at[j].add(add * pod.nz_cpu),
        nz_mem=state.nz_mem.at[j].add(add * pod.nz_mem),
        pod_count=state.pod_count.at[j].add(add32),
        port_bits=state.port_bits.at[j].set(
            state.port_bits[j] | jnp.where(fit_any, pod.ports, 0)),
        disk_any=state.disk_any.at[j].set(
            state.disk_any[j] | jnp.where(fit_any, pod.sany, 0)),
        disk_rw=state.disk_rw.at[j].set(
            state.disk_rw[j] | jnp.where(fit_any, pod.srw, 0)))
    return fields, add32


def _step(node: NodeConst, weights: Tuple[int, int, int],
          anti_weight: int, state: State, pod,
          has_aff: bool = True, has_spread: bool = True
          ) -> Tuple[State, jax.Array]:
    n = node.valid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    mask, total = _mask_and_score(node, weights, anti_weight, state, pod,
                                  has_aff, has_spread)

    # ---- selection (generic_scheduler.go:95 selectHost) ----
    # one composite argmax: scores are non-negative and tie_rank is a
    # distinct 0..n-1 per valid node, so argmax(total*n + tie_rank) is
    # exactly "max score, then deterministic max tie-rank" in one
    # reduction instead of max + compare + argmax
    composite = jnp.where(mask, total * n + node.tie_rank,
                          jnp.full((), -1, total.dtype))
    pick = jnp.argmax(composite).astype(jnp.int32)
    fit_any = composite[pick] >= 0
    assigned = jnp.where(fit_any, pick, jnp.int32(-1))

    # ---- assume-pod state update (modeler.go:113) ----
    # scatter at the picked lane, not one-hot arithmetic over the whole
    # node axis: inside the scan the carry updates in place, so each
    # step's state write is O(1) instead of O(nodes) (the state arrays
    # are ~the same size as the score reads — this halves per-step HBM
    # traffic). A no-fit step scatters a zero delta at lane 0.
    j = jnp.maximum(pick, 0)
    fields, add32 = _commit_node_local(state, pod, j, fit_any)
    new_state = State(
        **fields,
        spread=state.spread.at[:, j].add(add32 * pod.member)
        if has_spread else state.spread,
        aff_count=_aff_count_update(node, state, pod, pick, fit_any)
        if has_aff else state.aff_count,
        aff_total=(state.aff_total + jnp.where(fit_any, pod.aff_member, 0))
        if has_aff else state.aff_total,
        svc_count=state.svc_count.at[:, j].add(add32 * pod.svc_member)
        if anti_weight else state.svc_count,
        svc_total=(state.svc_total + jnp.where(fit_any, pod.svc_member, 0))
        if anti_weight else state.svc_total)
    return new_state, assigned


def _aff_count_update(node: NodeConst, state: State, pod, pick, fit_any):
    """Placed pod joins its in-scope terms' domain counts (the quadratic
    term's running state; domain of the chosen node per term)."""
    t = state.aff_count.shape[0]
    dom_at = jnp.take(node.aff_dom, pick, axis=1)                 # [T]
    add = jnp.where(fit_any & (dom_at >= 0), pod.aff_member, 0)
    return state.aff_count.at[
        jnp.arange(t), jnp.maximum(dom_at, 0)].add(add)


# Scan unroll factor: the per-step op count is small enough that the TPU
# while-loop's per-iteration overhead dominates (measured ~30us/step at
# unroll=1 vs ~25us at 4 on a v5e; flat beyond 4). Unrolling packs 4 pods
# into one loop iteration — results are bit-identical, only the loop
# structure changes. Compile time grows ~3x (one-time per shape).
SCAN_UNROLL = 4


def _make_run(weights: Tuple[int, int, int], anti_weight: int = 0,
              has_aff: bool = True, has_spread: bool = True):
    def run(node: NodeConst, state: State, pods: PodXs):
        def step(carry, x):
            return _step(node, weights, anti_weight, carry, x,
                         has_aff, has_spread)
        return jax.lax.scan(step, state, pods, unroll=SCAN_UNROLL)
    return run


def _make_probe(weights: Tuple[int, int, int], anti_weight: int = 0,
                has_aff: bool = True, has_spread: bool = True):
    """Stateless variant: every pod scored against the same pre-batch
    state (no sequential commit) — extender Filter/Prioritize answer
    per-pod without assuming the pod lands (extender.go:95,119)."""
    def probe(node: NodeConst, state: State, pods: PodXs):
        def one(pod):
            return _mask_and_score(node, weights, anti_weight, state, pod,
                                   has_aff, has_spread)
        return jax.vmap(one)(pods)
    return probe


# ---------------------------------------------------------------------------
# Speculative tile-parallel assign + conflict repair (SURVEY.md section 7
# step 4's second branch). The sequential scan pays a full [N]-wide
# predicate+priority pipeline per pod (~60 ops x N lanes x P steps, and on
# TPU a measured ~25us/step loop floor — 0.74s for the 30k-pod north-star
# batch on its own). The speculative engine splits the work:
#
#   1. parallel pass: ONE batched vmap scores every pod in the chunk
#      against the chunk-start ("frozen") state — the expensive pipeline
#      runs once, fully vectorized, as [P, N] instead of P sequential
#      [N] steps.
#   2. repair pass: a lax.scan whose per-step body is tiny. For pod k the
#      true sequential-state score differs from the frozen row ONLY on
#      nodes some earlier pod in the chunk committed to (scoring is
#      node-local when the spread / inter-pod-affinity / service-anti
#      tiers are inactive — each node's mask+score reads that node's
#      state and nothing global). So the exact argmax is
#        max( masked argmax of the frozen row over UNTOUCHED nodes,
#             exact rescore of the <=k touched lanes ).
#      The first is one select+argmax over a precomputed row; the second
#      is the full formula on a gathered [chunk]-lane set.
#
# The result is BIT-IDENTICAL to the sequential scan (same composite
# encoding, same tie-break, disjoint touched/untouched sets can never
# tie because composite = total*n + tie_rank is injective per node), so
# the scan's oracle-parity gate transfers. Eligibility is decided per
# encode: any active global tier (has_aff / has_spread / anti_weight)
# falls back to the scan — exactly the tiers whose scores are not
# node-local.
# ---------------------------------------------------------------------------

def _make_spec_pass(weights: Tuple[int, int, int],
                    has_spread: bool = False):
    """Batched frozen-state composite scores: -> i[P, N] (-1 = no fit)."""
    def spec_pass(node: NodeConst, state: State, pods: PodXs):
        n = node.valid.shape[0]

        def one(pod):
            mask, total = _mask_and_score(node, weights, 0, state, pod,
                                          has_aff=False,
                                          has_spread=has_spread)
            return jnp.where(mask, total * n + node.tie_rank,
                             jnp.full((), -1, total.dtype))

        return jax.vmap(one)(pods)
    return spec_pass


def _gather_lanes(node: NodeConst, state: State, tidx: jax.Array,
                  lane_valid: jax.Array) -> Tuple[NodeConst, State]:
    """Node constants + mutable state at lanes tidx (clamped indices;
    invalid lanes are masked out via node.valid). Fields unused by the
    node-local tier keep their ungathered arrays — _mask_and_score with
    has_aff=False/has_spread=False/anti_weight=0 never reads them and
    XLA removes the dead bindings."""
    g = NodeConst(
        valid=node.valid[tidx] & lane_valid,
        sched_ok=node.sched_ok[tidx],
        cpu_cap=node.cpu_cap[tidx], mem_cap=node.mem_cap[tidx],
        pod_cap=node.pod_cap[tidx], labels=node.labels[tidx],
        tie_rank=node.tie_rank[tidx],
        exceed_cpu=node.exceed_cpu[tidx], exceed_mem=node.exceed_mem[tidx],
        offgrid_max=node.offgrid_max, aff_dom=node.aff_dom,
        zone_id=node.zone_id, zone_scratch=node.zone_scratch,
        static_mask=node.static_mask[tidx],
        static_score=node.static_score[tidx])
    s = State(
        cpu_used=state.cpu_used[tidx], mem_used=state.mem_used[tidx],
        nz_cpu=state.nz_cpu[tidx], nz_mem=state.nz_mem[tidx],
        pod_count=state.pod_count[tidx], port_bits=state.port_bits[tidx],
        disk_any=state.disk_any[tidx], disk_rw=state.disk_rw[tidx],
        spread=state.spread[:, tidx], aff_count=state.aff_count,
        aff_total=state.aff_total, svc_count=state.svc_count,
        svc_total=state.svc_total)
    return g, s


def _spec_step(node: NodeConst, weights: Tuple[int, int, int],
               carry, x, has_spread: bool = False):
    """One repair step: exact sequential argmax for pod k from
    (frozen row over untouched nodes) + (rescored touched lanes),
    then the same O(1) scatter commit as the scan step.

    Spread tier (has_spread): the frozen row stays exact on untouched
    nodes only while the pod's group max-count equals its block-start
    value — commits can only RAISE counts, so a per-group flag latches
    the first time any count exceeds the block-start max, and flagged
    groups' pods take a full-width rescore (the scan step's math)
    under lax.cond. Unflagged groups rescore touched lanes with the
    block-start max as the override — exact by the latch invariant."""
    state, touched, touched_idx, k, flag, max_start = carry
    pod, row = x
    n = node.valid.shape[0]
    t = touched_idx.shape[0]
    neg = jnp.full((), -1, row.dtype)

    def fast(_):
        # untouched nodes: frozen scores exact (node-local + unflagged
        # spread); touched lanes: exact rescore against current state
        frozen = jnp.where(touched, neg, row)
        fi = jnp.argmax(frozen).astype(jnp.int32)
        fv = frozen[fi]
        lane_valid = (jnp.arange(t, dtype=jnp.int32) < k) \
            & (touched_idx >= 0)
        tidx = jnp.maximum(touched_idx, 0)
        gnode, gstate = _gather_lanes(node, state, tidx, lane_valid)
        mask_t, total_t = _mask_and_score(
            gnode, weights, 0, gstate, pod, has_aff=False,
            has_spread=has_spread, iota=tidx,
            spread_max_override=max_start if has_spread else None)
        comp_t = jnp.where(mask_t, total_t * n + gnode.tie_rank, neg)
        tl = jnp.argmax(comp_t)
        tv = comp_t[tl]
        ti = tidx[tl]
        return (jnp.where(tv > fv, ti, fi).astype(jnp.int32),
                jnp.maximum(tv, fv) >= 0)

    if has_spread:
        def slow(_):
            # group max moved since block start: the frozen row is
            # globally stale for this pod — full-width rescore against
            # current state (exactly the scan step's selection math)
            mask, total = _mask_and_score(node, weights, 0, state, pod,
                                          has_aff=False, has_spread=True)
            composite = jnp.where(mask, total * n + node.tie_rank, neg)
            pick = jnp.argmax(composite).astype(jnp.int32)
            return pick, composite[pick] >= 0

        stale = (pod.group_id >= 0) & flag[jnp.maximum(pod.group_id, 0)]
        pick, fit_any = jax.lax.cond(stale, slow, fast, operand=None)
    else:
        pick, fit_any = fast(None)
    assigned = jnp.where(fit_any, pick, jnp.int32(-1))

    # commit: the scan step's scatter update; spread counts join when
    # the tier is active, other global tiers stay untouched (the spec
    # path never runs with them)
    j = jnp.maximum(pick, 0)
    fields, add32 = _commit_node_local(state, pod, j, fit_any)
    if has_spread:
        new_spread = state.spread.at[:, j].add(add32 * pod.member)
        flag = flag | (fit_any & (pod.member > 0)
                       & (state.spread[:, j] + pod.member > max_start))
    else:
        new_spread = state.spread
    new_state = State(
        **fields,
        spread=new_spread, aff_count=state.aff_count,
        aff_total=state.aff_total, svc_count=state.svc_count,
        svc_total=state.svc_total)
    touched = touched.at[j].set(touched[j] | fit_any)
    touched_idx = touched_idx.at[k].set(assigned)
    return ((new_state, touched, touched_idx, k + 1, flag, max_start),
            assigned)


# The repair step is small enough that loop overhead shows again; a mild
# unroll amortizes it without the compile-time cost of the full scan's
# body x4 (the repair body is ~10x smaller).
SPEC_UNROLL = 4

# Repair-block width: the pod axis splits into blocks of this size; each
# block gets a fresh parallel pass against the live carry state (so
# frozen rows are never stale across blocks) and its repair steps gather
# at most this many touched lanes. Smaller blocks shrink the per-step
# rescore (on TPU that is the emulated-f64 cost of the Balanced formula,
# the scan step's dominant term); larger blocks amortize the parallel
# pass's dispatch. 256 balances the two at bench shapes.
SPEC_BLOCK = 256


def _make_spec_run(weights: Tuple[int, int, int],
                   has_spread: bool = False, block: int = SPEC_BLOCK):
    """Same (node, state, pods) -> (final_state, assigned) signature as
    _make_run — drop-in for the scan wherever the encode is eligible."""
    spec_pass = _make_spec_pass(weights, has_spread)

    def run(node: NodeConst, state: State, pods: PodXs):
        p = pods.valid.shape[0]
        b = min(block, p) if p else 1
        pad = (-p) % b
        if pad:
            # pad the pod axis to a block multiple with invalid pods —
            # they score -1 everywhere and never commit
            pods = jax.tree_util.tree_map(
                lambda a: jnp.concatenate(
                    [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]), pods)
        nb = (p + pad) // b
        pods_b = jax.tree_util.tree_map(
            lambda a: a.reshape((nb, b) + a.shape[1:]), pods)
        n = node.valid.shape[0]
        g = state.spread.shape[0]

        def outer(state, pblock):
            comp = spec_pass(node, state, pblock)               # [b, N]
            touched = jnp.zeros(n, bool)
            tidx0 = jnp.full((b,), -1, jnp.int32)
            # block-start per-group max counts (the latch reference
            # for the spread tier; see _spec_step)
            max_start = jnp.maximum(jnp.max(state.spread, axis=1),
                                    node.offgrid_max)               # [G]
            flag = jnp.zeros(g, bool)

            def step(carry, x):
                return _spec_step(node, weights, carry, x,
                                  has_spread=has_spread)

            (state2, _, _, _, _, _), assigned = jax.lax.scan(
                step, (state, touched, tidx0, jnp.int32(0), flag,
                       max_start),
                (pblock, comp), unroll=SPEC_UNROLL)
            return state2, assigned

        final_state, assigned = jax.lax.scan(outer, state, pods_b)
        return final_state, assigned.reshape(nb * b)[:p]
    return run


def _tree_nbytes(tree) -> int:
    return sum(np.asarray(leaf).nbytes
               for leaf in jax.tree_util.tree_leaves(tree))


class _TableCache:
    """Device-resident mirror of one incremental encoder's node tables
    (NodeConst + State init), sharded under the engine's mesh.

    `node_gen` / `state_gen` are the encoder generations (TableDelta
    counter values) the two mirrors are current at: a tile whose encode
    carries generation g needs only the rows whose dirty_gen exceeds
    the mirror's gen scattered in. `sig` pins shapes, dtypes, and
    mem_scale — any change (capacity growth, interner widening, a
    narrowing flip) misses and reseeds with a full upload. `src` pins
    the encoder INSTANCE (TableDelta.encoder_id): generations count one
    encoder's private timeline, so a same-shaped tile from a different
    encoder must miss — its low generations would otherwise read as
    "nothing changed" against another encoder's rows.

    `epochs` pins the encoder's shard-epoch vector
    (TableDelta.shard_epochs) the mirror was seeded under. A survivor
    re-shard replaces that vector (the slot->shard block partition
    moved), so a mirror seeded before it holds rows placed on the OLD
    owners — possibly a dead device. Any vector difference misses and
    reseeds, which IS the journal replay materialized: every row
    re-journaled by the reshard lands on its new owner in one sharded
    upload."""

    __slots__ = ("sig", "src", "epochs", "node", "state",
                 "node_gen", "state_gen")

    def __init__(self, sig, src, epochs, node, state, node_gen, state_gen):
        self.sig = sig
        self.src = src
        self.epochs = epochs
        self.node = node
        self.state = state
        self.node_gen = node_gen
        self.state_gen = state_gen


# Per-slot (axis-0) fields of the two device tables — the only fields
# the dirty-row scatter touches. Everything else is either slot-axis-1
# ([G,N]/[T,N]/[S,N]) or scalar-shaped, and is a CONSTANT for
# delta-eligible encodes (no spread groups, no affinity terms, no
# service groups): zeros / -1 with shapes pinned by the cache signature.
_NODE_ROW_FIELDS = ("valid", "sched_ok", "cpu_cap", "mem_cap", "pod_cap",
                    "labels", "tie_rank", "exceed_cpu", "exceed_mem",
                    "zone_id", "static_mask", "static_score")
_STATE_ROW_FIELDS = ("cpu_used", "mem_used", "nz_cpu", "nz_mem",
                     "pod_count", "port_bits", "disk_any", "disk_rw")


def _scatter_rows_fn(tab, idx, rows):
    """Jitted per-shard scatter: write the journaled dirty rows into the
    donated device table columns (dict of axis-0 arrays; 2-D columns
    take whole rows). Under a mesh XLA lowers the scatter per shard —
    each device applies the row writes that land in its slot block."""
    return {k: tab[k].at[idx].set(rows[k]) for k in tab}


def _node_shardings(mesh: Mesh, axis: str):
    def s(*spec):
        return NamedSharding(mesh, P(*spec))
    node = NodeConst(valid=s(axis), sched_ok=s(axis),
                     cpu_cap=s(axis), mem_cap=s(axis),
                     pod_cap=s(axis), labels=s(axis, None), tie_rank=s(axis),
                     exceed_cpu=s(axis), exceed_mem=s(axis), offgrid_max=s(),
                     aff_dom=s(None, axis), zone_id=s(axis),
                     zone_scratch=s(), static_mask=s(axis),
                     static_score=s(axis))
    state = State(cpu_used=s(axis), mem_used=s(axis), nz_cpu=s(axis),
                  nz_mem=s(axis), pod_count=s(axis), port_bits=s(axis, None),
                  disk_any=s(axis, None), disk_rw=s(axis, None),
                  spread=s(None, axis), aff_count=s(), aff_total=s(),
                  svc_count=s(None, axis), svc_total=s())
    pods = PodXs(valid=s(), req_cpu=s(), req_mem=s(), zero_req=s(),
                 nz_cpu=s(), nz_mem=s(), sel=s(), ports=s(), qany=s(),
                 qrw=s(), sany=s(), srw=s(), host_idx=s(), group_id=s(),
                 member=s(), aff_req=s(), anti_req=s(), aff_member=s(),
                 svc_group=s(), svc_member=s())
    return node, state, pods


def _make_preempt():
    """Dense masked victim search over one VictimTable (the device half
    of sched/preemption.py — the docstring there is the spec; this is
    the same rule as oracle_find_victims, expressed as prefix sums and
    one composite argmax so the whole search is a single dispatch).

    Victims arrive pre-sorted (priority asc, insertion asc), so the
    minimal eviction set on a node is a PREFIX: the per-node search is
    a cumulative sum of released cpu/mem over the victim axis, a
    [N, V+1] feasibility matrix (column k = "evict the k-prefix"), and
    a first-True argmax for k*. Node choice is the injective int64
    composite (fewest evictions, lowest senior victim priority,
    tie_rank), matching preemption.composite_score exactly — under a
    mesh the final argmax reduces over ICI like the scan's per-step
    argmax. Everything is int64 end-to-end (ensure_x64): priorities
    are bounded |p| <= PMAX by validation, so no term can wrap."""
    from ..preemption import PMAX, SCORE_STRIDE, SENIOR_NONE

    def kernel(cand, cpu_cap, mem_cap, pod_cap, cpu_used, mem_used,
               pod_count, tie_rank, v_prio, v_cpu, v_mem, v_valid,
               prio, req_cpu, req_mem, zero_req):
        n, v = v_prio.shape
        vm = v_valid & (v_prio < prio)
        nv = jnp.sum(vm.astype(jnp.int64), axis=1)
        zero_col = jnp.zeros((n, 1), jnp.int64)
        rc = jnp.concatenate(
            [zero_col, jnp.cumsum(jnp.where(vm, v_cpu, 0), axis=1)], axis=1)
        rm = jnp.concatenate(
            [zero_col, jnp.cumsum(jnp.where(vm, v_mem, 0), axis=1)], axis=1)
        k = jnp.arange(v + 1, dtype=jnp.int64)[None, :]
        k_ok = k <= nv[:, None]
        fits_count = (pod_count[:, None] - k) < pod_cap[:, None]
        free_cpu = (cpu_cap[:, None] == 0) | (
            cpu_cap[:, None] - (cpu_used[:, None] - rc) >= req_cpu)
        free_mem = (mem_cap[:, None] == 0) | (
            mem_cap[:, None] - (mem_used[:, None] - rm) >= req_mem)
        res_ok = jnp.where(zero_req, fits_count,
                           fits_count & free_cpu & free_mem)
        feas = cand[:, None] & k_ok & res_ok
        any_k = jnp.any(feas, axis=1)
        kstar = jnp.argmax(feas, axis=1).astype(jnp.int64)  # first True
        senior = jnp.take_along_axis(
            v_prio, jnp.maximum(kstar - 1, 0)[:, None], axis=1)[:, 0]
        senior = jnp.where(kstar > 0, senior, SENIOR_NONE)
        score = ((v - kstar) * SCORE_STRIDE + (PMAX - senior)) * n \
            + tie_rank
        score = jnp.where(any_k, score, jnp.int64(-1))
        pick = jnp.argmax(score)
        return pick, kstar, score

    return kernel


class BatchEngine:
    """Compiled batch scheduler. With a mesh, the node axis shards across
    devices and the per-step argmax reduces over ICI; without, single-chip.
    jit caches per (N, P, word-count) shape signature."""

    # process-wide: set when the pallas filter kernel fails to
    # compile/run on this platform (filter_masks then stays on XLA)
    _pallas_broken = False

    def __init__(self, weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
                 mesh: Optional[Mesh] = None, node_axis: str = "nodes",
                 policy=None, speculative: Optional[bool] = None):
        ensure_x64()
        self.weights = tuple(int(w) for w in weights)
        self.mesh = mesh
        self.node_axis = node_axis
        self.policy = policy
        self._anti_weight = (policy.anti_affinity_weight
                             if policy is not None
                             and policy.needs_anti_affinity else 0)
        # speculative parallel-assign + repair replaces the scan whenever
        # the encode's tiers are node-local (bit-identical results — see
        # the _make_spec_run block). None = auto: OFF on every backend.
        # The TPU-on hypothesis (scan pays a ~25us/step loop floor the
        # repair pass amortizes) was refuted by the real-v5e A/B
        # (TPU_EVIDENCE_BEST.json engine_spec): scan 52.5k vs spec 16.6k
        # pods/s at 5000x30000-plain, scan ahead at every shape/tier —
        # the block-wide vmap rescore moves more HBM per committed pod
        # than the scan's chained carry. Spec remains an explicit knob
        # for A/B; off under a mesh regardless (the repair gathers
        # would cross shards).
        self._speculative = speculative
        # jitted variants keyed by (has_aff, has_spread): inactive tiers
        # (no affinity terms / no spread groups in the batch) compile out
        # entirely rather than running on dummy [1, N] arrays every step
        self._runs = {}
        self._run = self._get_run(True, True)
        # device-resident mirror of the incremental encoder's node tables
        # (run_chunked's delta-upload path); the scatter donates the stale
        # mirror buffers so XLA updates rows in place
        self._table_cache: Optional[_TableCache] = None
        self._scatter = jax.jit(_scatter_rows_fn, donate_argnums=(0,))
        self.delta_uploads = True  # A/B knob: False forces full uploads
        # host->device transfer accounting, read by tools/profile_e2e.py
        # and the bench multichip section
        self.upload_stats = {"full_tiles": 0, "delta_tiles": 0,
                             "reuse_tiles": 0, "full_bytes": 0,
                             "delta_bytes": 0, "pod_bytes": 0,
                             # gauge, not a counter: host nbytes of one
                             # full (NodeConst, State) pair at the last
                             # fetch — what a full upload WOULD move,
                             # even in a window that never moved one
                             "table_bytes": 0}

    @property
    def speculative(self) -> bool:
        return self.mesh is None and bool(self._speculative)

    def _get_run(self, has_aff: bool, has_spread: bool):
        # speculative covers the node-local tiers AND the spread tier
        # (block-start-max latch); inter-pod affinity and service-anti
        # scores move globally per commit — those keep the scan
        spec = (not has_aff and not self._anti_weight
                and self.speculative)
        key = ("spec", has_spread) if spec else (has_aff, has_spread)
        cached = self._runs.get(key)
        if cached is not None:
            return cached
        if spec:
            jitted = jax.jit(_make_spec_run(self.weights, has_spread))
        else:
            run = _make_run(self.weights, self._anti_weight,
                            has_aff=has_aff, has_spread=has_spread)
            if self.mesh is not None:
                shardings = _node_shardings(self.mesh, self.node_axis)
                jitted = jax.jit(
                    run, in_shardings=shardings,
                    out_shardings=(shardings[1],
                                   NamedSharding(self.mesh, P())))
            else:
                jitted = jax.jit(run)
        self._runs[key] = jitted
        return jitted

    @staticmethod
    def _enc_flags(enc: EncodeResult) -> Tuple[bool, bool]:
        pb = enc.pod_batch
        has_aff = bool(pb.aff_req.any() or pb.anti_req.any())
        has_spread = bool((pb.group_id >= 0).any())
        return has_aff, has_spread

    @property
    def n_shards(self) -> int:
        return 1 if self.mesh is None else self.mesh.devices.size

    def reshard(self, mesh: Optional[Mesh]) -> None:
        """Rebuild the engine over a survivor mesh after a shard owner
        died. Every compiled program's in/out shardings named the old
        mesh and the table mirror's rows live on its block partition
        (including the dead device), so both drop; the next dispatch
        recompiles against the new mesh and reseeds the mirror with one
        full sharded upload — the journal replay landing every row on
        its new owner."""
        self.mesh = mesh
        self._runs = {}
        self._run = self._get_run(True, True)
        self._table_cache = None

    def find_victims(self, table):
        """Run the preemption victim search for one VictimTable
        (incremental.victim_table). Returns an OracleResult whose
        fields must be bit-equal to sched.preemption.
        oracle_find_victims(table) at every shape — the parity suite's
        contract. One dispatch, one host pull after it (no per-tile
        loop, so no per-shard sync)."""
        from ..preemption import OracleResult
        fn = self._runs.get("preempt")
        if fn is None:
            kernel = _make_preempt()
            if self.mesh is not None:
                def s(*spec):
                    return NamedSharding(self.mesh, P(*spec))
                row, mat, rep = s(self.node_axis), \
                    s(self.node_axis, None), s()
                fn = jax.jit(
                    kernel,
                    in_shardings=(row, row, row, row, row, row, row,
                                  row, mat, mat, mat, mat,
                                  rep, rep, rep, rep),
                    out_shardings=(rep, row, row))
            else:
                fn = jax.jit(kernel)
            self._runs["preempt"] = fn
        pick, kstar, score = fn(
            table.cand, table.cpu_cap, table.mem_cap, table.pod_cap,
            table.cpu_used, table.mem_used, table.pod_count,
            table.tie_rank, table.v_prio, table.v_cpu, table.v_mem,
            table.v_valid, np.int64(table.prio), np.int64(table.req_cpu),
            np.int64(table.req_mem), np.bool_(table.zero_req))
        pick, kstar, score = jax.device_get((pick, kstar, score))
        pick = int(pick)
        return OracleResult(pick=pick, kstar=int(kstar[pick]),
                            feasible=bool(score[pick] >= 0),
                            node_kstar=np.asarray(kstar, np.int64),
                            node_score=np.asarray(score, np.int64))

    def _ensure_safe_dtypes(self, enc: EncodeResult) -> EncodeResult:
        """The encoder narrows with a conservative default weight bound;
        an engine configured with larger policy weights must re-widen or
        the i32 composite argmax could wrap (encode can't know the
        engine's weights — this is the engine's half of the contract)."""
        nt = enc.node_tab
        if nt.cpu_cap.dtype != np.int32:
            return enc
        n = nt.valid.shape[0]
        max_static = int(np.max(np.abs(nt.static_score))) \
            if nt.static_score.size else 0
        wsum = sum(abs(w) for w in self.weights) + abs(self._anti_weight)
        if (10 * wsum + max_static + 1) * max(n, 1) < (1 << 30):
            return enc
        from dataclasses import replace as _dc_replace
        i64 = np.int64
        g = enc.mem_scale
        st, pb = enc.init_state, enc.pod_batch
        return _dc_replace(
            enc,
            mem_scale=1,
            node_tab=_dc_replace(
                nt, cpu_cap=nt.cpu_cap.astype(i64),
                mem_cap=nt.mem_cap.astype(i64) * g,
                static_score=nt.static_score.astype(i64)),
            init_state=_dc_replace(
                st, cpu_used=st.cpu_used.astype(i64),
                mem_used=st.mem_used.astype(i64) * g,
                nz_cpu=st.nz_cpu.astype(i64),
                nz_mem=st.nz_mem.astype(i64) * g),
            pod_batch=_dc_replace(
                pb, req_cpu=pb.req_cpu.astype(i64),
                req_mem=pb.req_mem.astype(i64) * g,
                nz_cpu=pb.nz_cpu.astype(i64),
                nz_mem=pb.nz_mem.astype(i64) * g))

    def device_args(self, enc: EncodeResult):
        enc = self._ensure_safe_dtypes(enc)
        nt, st, pb = enc.node_tab, enc.init_state, enc.pod_batch
        node = NodeConst(
            valid=nt.valid, sched_ok=nt.sched_ok,
            cpu_cap=nt.cpu_cap, mem_cap=nt.mem_cap,
            pod_cap=nt.pod_cap, labels=nt.label_words, tie_rank=nt.tie_rank,
            exceed_cpu=nt.exceed_cpu, exceed_mem=nt.exceed_mem,
            offgrid_max=enc.offgrid_max, aff_dom=nt.aff_dom,
            zone_id=nt.zone_id, zone_scratch=nt.zone_scratch,
            static_mask=nt.static_mask, static_score=nt.static_score)
        state = State(cpu_used=st.cpu_used, mem_used=st.mem_used,
                      nz_cpu=st.nz_cpu, nz_mem=st.nz_mem,
                      pod_count=st.pod_count, port_bits=st.port_bits,
                      disk_any=st.disk_any, disk_rw=st.disk_rw,
                      spread=st.spread, aff_count=st.aff_count,
                      aff_total=st.aff_total, svc_count=st.svc_count,
                      svc_total=st.svc_total)
        pods = PodXs(valid=pb.valid, req_cpu=pb.req_cpu, req_mem=pb.req_mem,
                     zero_req=pb.zero_req, nz_cpu=pb.nz_cpu,
                     nz_mem=pb.nz_mem, sel=pb.sel_words, ports=pb.port_words,
                     qany=pb.disk_qany, qrw=pb.disk_qrw, sany=pb.disk_sany,
                     srw=pb.disk_srw, host_idx=pb.host_idx,
                     group_id=pb.group_id, member=pb.member,
                     aff_req=pb.aff_req, anti_req=pb.anti_req,
                     aff_member=pb.aff_member, svc_group=pb.svc_group,
                     svc_member=pb.svc_member)
        return node, state, pods

    def _table_sig(self, enc: EncodeResult):
        """Shape/dtype signature of every array feeding NodeConst + State.
        Any mismatch against the cached mirror (capacity growth, interner
        word-count widening, an i32/i64 narrowing flip, a mem_scale
        change) forces a full reseed — the dirty-row journal only covers
        value changes at a fixed layout."""
        nt, st = enc.node_tab, enc.init_state
        arrs = (nt.valid, nt.sched_ok, nt.cpu_cap, nt.mem_cap, nt.pod_cap,
                nt.label_words, nt.tie_rank, nt.exceed_cpu, nt.exceed_mem,
                enc.offgrid_max, nt.aff_dom, nt.zone_id, nt.zone_scratch,
                nt.static_mask, nt.static_score,
                st.cpu_used, st.mem_used, st.nz_cpu, st.nz_mem,
                st.pod_count, st.port_bits, st.disk_any, st.disk_rw,
                st.spread, st.aff_count, st.aff_total, st.svc_count,
                st.svc_total)
        return (enc.mem_scale,) + tuple(
            (np.asarray(a).shape, np.asarray(a).dtype.str) for a in arrs)

    def _delta_eligible(self, enc: EncodeResult,
                        flags: Tuple[bool, bool]) -> bool:
        """The dirty-row scatter only rewrites per-slot (axis-0) columns,
        so it applies exactly when every other table field is a canonical
        constant: an incremental encode (journal present) with no
        affinity terms, no spread groups, and no anti-affinity policy
        (zone scratch tables). Same family as the chain-eligibility test
        in sched/batch.py — the live pipeline's steady state."""
        return (self.delta_uploads and enc.delta is not None
                and flags == (False, False) and not enc.tile_groups
                and self._anti_weight == 0)

    def _scatter_table(self, dev_tab, fields, host_tab, rows):
        """Scatter the journaled dirty rows of one table into its device
        mirror. Row count pads to the next pow2 (one compiled scatter per
        bucket, not per tile); the pad duplicates rows[0], and duplicate
        .set writes of identical values are deterministic. Returns the
        updated table and the host->device bytes moved."""
        bucket = 1 << max(0, (int(rows.size) - 1).bit_length())
        idx = np.empty(bucket, np.int64)
        idx[:rows.size] = rows
        idx[rows.size:] = rows[0]
        sub = {f: getattr(dev_tab, f) for f in fields}
        host_rows = {f: np.ascontiguousarray(
            np.asarray(getattr(host_tab, f))[idx]) for f in fields}
        out = self._scatter(sub, idx, host_rows)
        moved = idx.nbytes + sum(r.nbytes for r in host_rows.values())
        return dev_tab._replace(**out), moved

    def _fetch_tables(self, enc: EncodeResult, node: NodeConst, state: State,
                      flags: Tuple[bool, bool], state_needed: bool):
        """Resolve the (NodeConst, State-init) run arguments through the
        device-resident mirror. Hit: scatter only the rows the encoder's
        journal marks dirty since the mirror's generation. Miss or
        ineligible: full host upload (and reseed the mirror when
        eligible). Single-process path only — multi-host placement goes
        through _place_global.

        A chained tile (state_needed=False) skips the State mirror: its
        state_gen lags and the next unchained tile catches up by
        scattering every row dirtied since."""
        self.upload_stats["table_bytes"] = \
            _tree_nbytes(node) + _tree_nbytes(state)
        if not self._delta_eligible(enc, flags):
            self._table_cache = None
            self.upload_stats["full_tiles"] += 1
            self.upload_stats["full_bytes"] += _tree_nbytes(node) + (
                _tree_nbytes(state) if state_needed else 0)
            return node, state
        sig = self._table_sig(enc)
        delta = enc.delta
        cache = self._table_cache
        if cache is not None and cache.sig == sig \
                and cache.src == delta.encoder_id \
                and cache.epochs == delta.shard_epochs \
                and delta.full_gen <= min(cache.node_gen, cache.state_gen):
            moved = 0
            node_rows = np.nonzero(
                delta.node_dirty_gen > cache.node_gen)[0]
            if node_rows.size:
                cache.node, nb = self._scatter_table(
                    cache.node, _NODE_ROW_FIELDS, node, node_rows)
                moved += nb
            cache.node_gen = delta.table_gen
            if state_needed:
                state_rows = np.nonzero(
                    delta.state_dirty_gen > cache.state_gen)[0]
                if state_rows.size:
                    cache.state, sb = self._scatter_table(
                        cache.state, _STATE_ROW_FIELDS, state, state_rows)
                    moved += sb
                cache.state_gen = delta.table_gen
            if moved:
                self.upload_stats["delta_tiles"] += 1
                self.upload_stats["delta_bytes"] += moved
            else:
                self.upload_stats["reuse_tiles"] += 1
            return cache.node, cache.state
        # miss: seed the mirror with one full (sharded) upload
        if self.mesh is not None:
            node_sh, state_sh, _ = _node_shardings(self.mesh, self.node_axis)
            node_dev = jax.device_put(node, node_sh)
            state_dev = jax.device_put(state, state_sh)
        else:
            node_dev = jax.device_put(node)
            state_dev = jax.device_put(state)
        self._table_cache = _TableCache(sig, delta.encoder_id,
                                        delta.shard_epochs,
                                        node_dev, state_dev,
                                        delta.table_gen, delta.table_gen)
        self.upload_stats["full_tiles"] += 1
        self.upload_stats["full_bytes"] += \
            _tree_nbytes(node) + _tree_nbytes(state)
        return node_dev, state_dev

    def probe(self, enc: EncodeResult) -> Tuple[np.ndarray, np.ndarray]:
        """-> (mask bool[P, N], total i64[P, N]) of predicate fit and
        priority score per pending pod against the pre-batch state. The
        extender sidecar's kernel; also the device half of mixed-mode
        (device predicates + HTTP extender filter on survivors)."""
        node, state, pods = self.device_args(enc)
        has_aff, _ = self._enc_flags(enc)
        # has_spread stays ON: compiling the spread tier out shifts every
        # total by a constant — fine for the scan's argmax, wrong for the
        # absolute HostPriority scores the extender protocol returns
        key = ("probe", has_aff)
        fn = self._runs.get(key)
        if fn is None:
            fn = jax.jit(_make_probe(self.weights, self._anti_weight,
                                     has_aff, has_spread=True))
            self._runs[key] = fn
        mask, total = fn(node, state, pods)
        return np.asarray(mask), np.asarray(total)

    def filter_masks(self, enc: EncodeResult) -> np.ndarray:
        """-> bool[n_pods, N] predicate-fit masks against the pre-batch
        state (the extender Filter verb / mixed mode's probe rung). The
        all-integer predicate tier runs as a hand-written Pallas TPU
        kernel when the encoding qualifies (i32-narrowed, no affinity
        terms, single device — see pallas_filter.supports); anything
        else takes the XLA probe. Both are bit-exact with the oracle."""
        if self.mesh is None and self.policy is None \
                and not BatchEngine._pallas_broken:
            from . import pallas_filter
            if pallas_filter.supports(enc):
                try:
                    return pallas_filter.filter_masks(enc)
                except Exception:
                    # a Mosaic/compile rejection on some TPU generation
                    # must degrade, not take the extender down; the XLA
                    # probe is the same math
                    import logging
                    logging.getLogger(__name__).exception(
                        "pallas filter kernel failed; falling back to "
                        "the XLA probe for this process")
                    BatchEngine._pallas_broken = True
        mask, _ = self.probe(enc)
        return np.asarray(mask[:enc.n_pods]).astype(bool)

    @property
    def spans_processes(self) -> bool:
        """True when the mesh crosses OS processes (multi-host: each
        process owns a slice of the global device set — the DCN
        deployment shape; jax.distributed must be initialized)."""
        return self.mesh is not None and jax.process_count() > 1

    def _place_global(self, args):
        """Host pytrees -> global jax.Arrays for a multi-process mesh.

        Single-process jit accepts host numpy and shards it; across
        processes the committed arrays span non-addressable devices,
        so each process must contribute its addressable shards
        explicitly. Every process runs the SAME encode (the scheduler
        replicates host state, exactly like multi-host data loading
        where each host materializes its slice), so the callback just
        serves the local index windows of the shared host array."""
        shardings = _node_shardings(self.mesh, self.node_axis)
        return self._put_tree(args, shardings)

    @staticmethod
    def _put_tree(tree, sharding_tree):
        def put(host, sh):
            host = np.asarray(host)
            return jax.make_array_from_callback(
                host.shape, sh, lambda idx, _h=host: _h[idx])

        return jax.tree_util.tree_map(put, tree, sharding_tree)

    def run(self, enc: EncodeResult) -> Tuple[np.ndarray, State]:
        """-> (assigned node indices i32[P] (-1 = no fit), final state)."""
        node, state, pods = self.device_args(enc)
        if self.spans_processes:
            node, state, pods = self._place_global((node, state, pods))
        run = self._get_run(*self._enc_flags(enc))
        final_state, assigned = run(node, state, pods)
        return np.asarray(assigned), final_state

    def run_chunked(self, enc: EncodeResult, chunk: int = 1024,
                    state_override: Optional[State] = None,
                    block: bool = True) -> Tuple[np.ndarray, State]:
        """Like run(), but the pod axis executes as fixed-size scan chunks
        with the carry threaded between calls on device. One XLA program
        (the [chunk] shape) serves every tile size — the pow2-ladder of
        per-tile-shape compiles collapses to a single compilation, and a
        30k-pod batch is ~30 dispatches of the same executable. Padded
        pods are invalid and never touch state, so chunked execution is
        bit-identical to one long scan.

        state_override: start from this on-device State instead of the
        encoded init (the pipelined scheduler chains tile k+1 off tile
        k's final carry without a host round-trip). block=False skips
        the final host transfer — dispatches are queued asynchronously
        and the returned assignment array materializes on first
        np.asarray."""
        enc = self._ensure_safe_dtypes(enc)
        node, state, pods = self.device_args(enc)
        flags = self._enc_flags(enc)
        multiproc = self.spans_processes
        if multiproc:
            # multi-host: chunks slice HOST pytrees, then each piece
            # (and the node/state constants once) is placed globally;
            # the carry stays an on-device global array between chunks
            node_sh, state_sh, pods_sh = _node_shardings(self.mesh,
                                                         self.node_axis)
            node = self._put_tree(node, node_sh)
            if state_override is None:
                state = self._put_tree(state, state_sh)
        else:
            node, state = self._fetch_tables(
                enc, node, state, flags,
                state_needed=state_override is None)
        if state_override is not None:
            state = state_override
        run = self._get_run(*flags)
        p = pods.valid.shape[0]
        self.upload_stats["pod_bytes"] += _tree_nbytes(pods)
        outs = []
        for lo in range(0, p, chunk):
            piece = jax.tree_util.tree_map(lambda a: a[lo:lo + chunk], pods)
            n = piece.valid.shape[0]
            if n < chunk:  # pad the tail chunk to the compiled shape
                piece = jax.tree_util.tree_map(
                    lambda a: np.concatenate(
                        [np.asarray(a),
                         np.zeros((chunk - n,) + a.shape[1:], a.dtype)]),
                    piece)
            if multiproc:
                piece = self._put_tree(piece, pods_sh)
            state, assigned = run(node, state, piece)
            outs.append(assigned)
        if multiproc:
            # replicated outputs are addressable per process; host concat
            # (after the dispatch loop — one sync, not one per chunk)
            # avoids an out-of-jit op over global arrays
            flat = (np.concatenate([np.asarray(a) for a in outs])[:p]
                    if outs else np.zeros(0, np.int32))
            return flat, state
        flat = jnp.concatenate(outs)[:p] if outs else jnp.zeros(0, jnp.int32)
        if block:
            return np.asarray(flat), state
        return flat, state

    def schedule(self, snap: ClusterSnapshot, pod_pad_to: Optional[int] = None,
                 chunk: Optional[int] = None
                 ) -> Tuple[List[Optional[str]], EncodeResult]:
        """Encode + run + decode: one host name (or None) per pending pod."""
        enc = encode_snapshot(snap, node_pad_to=self.n_shards,
                              pod_pad_to=pod_pad_to, policy=self.policy)
        if chunk:
            assigned, _ = self.run_chunked(enc, chunk)
        else:
            assigned, _ = self.run(enc)
        out: List[Optional[str]] = []
        for j in range(enc.n_pods):
            idx = int(assigned[j])
            out.append(enc.node_names[idx] if idx >= 0 else None)
        return out, enc


def schedule_batch(snap: ClusterSnapshot,
                   weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
                   mesh: Optional[Mesh] = None,
                   policy=None) -> List[Optional[str]]:
    """One-shot helper (tests, extender sidecar)."""
    return BatchEngine(weights, mesh, policy=policy).schedule(snap)[0]
