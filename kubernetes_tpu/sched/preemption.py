"""Priority preemption: the serial oracle and the eviction-pass state.

When a pending high-priority pod is infeasible on every node, the
scheduler may evict strictly-lower-priority pods to make room (the
reference models this as PriorityClass + nominatedNodeName,
scheduler/algorithm/preemption — DIVERGENCES #35). The selection rule,
shared verbatim by this serial oracle and the device kernel
(engine._make_preempt), is:

  * candidate nodes: live, schedulable, selector/host-matching, and NOT
    carrying resource-exceeding pods (on a non-exceed node every counted
    pod contributes its full request, so releasing a victim releases
    exactly its recorded request — no misfit bookkeeping on the search
    path);
  * victims on a node: counted pods with priority strictly below the
    preemptor's, ordered (priority asc, insertion asc) — the eviction
    set is always a PREFIX of that order, so per-node search reduces to
    prefix sums of released cpu/mem;
  * per node, k* = the minimal prefix length whose release makes the
    preemptor feasible under the engine's exact predicate forms
    (fits_count = pod_count - k < pod_cap, zero-cap cpu/mem = unlimited,
    zero-request pods check only the count);
  * across nodes: fewest evictions first, then lowest senior victim
    priority (the largest priority in the evicted prefix), final tie by
    the engine's tie_rank — encoded as one injective int64 composite so
    host argmax (oracle) and device argmax agree bit-for-bit.

k* == 0 at the pick means a feasible non-preempting node exists: the
caller must NOT evict (wrongful-eviction rule 2) and simply requeues.

Preemptors are restricted to the flag-free subset (no host ports, no
volumes, no affinity): those are the predicates the victim search does
not model, so restricting the preemptor keeps the oracle exact instead
of approximately-right.

Everything here is deterministic: the eviction-pass backoff draws from
one seeded stream (f"{seed}:preemption") and reads time from an
injectable Clock — the sched/ determinism lint polices both.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core import types as api
from ..utils.clock import Clock, REAL

# priority bound (|p| <= PMAX, enforced by registry validation): keeps
# the composite victim score exact in int64 at every supported shape
PMAX = 1_000_000_000
# senior-victim sentinel for k*=0 (no evictions): beats every real
# priority, so "evict nobody" always outranks "evict somebody" at equal
# eviction counts
SENIOR_NONE = -PMAX - 1
# per-eviction-count stride of the composite score: wider than the
# (PMAX - senior) term's full range [0, 2*PMAX+1]
SCORE_STRIDE = 2 * PMAX + 2


def composite_score(n: int, v: int, kstar: int, senior: int,
                    tie_rank: int) -> int:
    """The injective node-choice score (python ints — exact): fewest
    evictions, then lowest senior victim priority, then tie_rank."""
    return ((v - kstar) * SCORE_STRIDE + (PMAX - senior)) * n + tie_rank


def preemptor_eligible(pod: api.Pod) -> bool:
    """Flag-free preemptors only: the victim search models counts and
    cpu/mem plus the static node masks — a preemptor relying on host
    ports, volumes (disk conflicts) or affinity would need predicates
    the search doesn't evaluate, so it skips preemption entirely."""
    sp = pod.spec
    if sp.affinity is not None:
        return False
    if sp.volumes:
        return False
    for c in sp.containers:
        for p in c.ports:
            if p.host_port:
                return False
    return True


@dataclass
class VictimTable:
    """Host snapshot of the preemption search inputs for ONE preemptor:
    per-node State columns plus the per-node victim prefix arrays
    ((priority asc, insertion asc) order, padded to v_pad). Built under
    the encoder lock (IncrementalEncoder.victim_table) so the columns,
    the victim identities and the fencing epochs are one consistent
    cut; both the oracle and the device kernel read only this."""
    pod_key: Tuple[str, str]              # (namespace, name)
    pod_uid: str
    prio: int
    req_cpu: int
    req_mem: int
    zero_req: bool
    cand: np.ndarray                      # bool [N] candidate-node mask
    cpu_cap: np.ndarray                   # i64 [N] (0 = unlimited)
    mem_cap: np.ndarray                   # i64 [N] (0 = unlimited)
    pod_cap: np.ndarray                   # i64 [N]
    cpu_used: np.ndarray                  # i64 [N]
    mem_used: np.ndarray                  # i64 [N]
    pod_count: np.ndarray                 # i64 [N]
    tie_rank: np.ndarray                  # i64 [N] (injective)
    v_prio: np.ndarray                    # i64 [N, V] (pad: PMAX+1)
    v_cpu: np.ndarray                     # i64 [N, V] (pad: 0)
    v_mem: np.ndarray                     # i64 [N, V] (pad: 0)
    v_valid: np.ndarray                   # bool [N, V]
    victims: List[List[Tuple[str, str, str]]]  # per node [(ns, name, uid)]
    node_names: List[str]
    # fencing metadata: a reshard or encoder swap after this snapshot
    # invalidates the victim set (batch.py re-checks before evicting)
    state_epoch: int = 0
    shard_epochs: Optional[Tuple[int, ...]] = None
    encoder_id: int = 0

    @property
    def n(self) -> int:
        return int(self.cand.shape[0])

    @property
    def v(self) -> int:
        return int(self.v_prio.shape[1])


@dataclass
class OracleResult:
    pick: int                 # chosen node slot (np.argmax convention)
    kstar: int                # evictions at the pick (0 = none needed)
    feasible: bool            # False: no victim set makes the pod fit
    node_kstar: np.ndarray    # i64 [N] per-node minimal eviction count
    node_score: np.ndarray    # i64 [N] composite (-1 = infeasible)

    def victim_keys(self, t: VictimTable) -> List[Tuple[str, str, str]]:
        if not self.feasible or self.kstar <= 0:
            return []
        return list(t.victims[self.pick][: self.kstar])


def oracle_find_victims(t: VictimTable) -> OracleResult:
    """The correctness truth: plain-python exact-int replay of the
    selection rule. The device kernel must be bit-equal to this at
    every shape (tests/test_device_parity.py)."""
    n, v = t.n, t.v
    node_kstar = np.zeros(n, dtype=np.int64)
    node_score = np.full(n, -1, dtype=np.int64)
    for j in range(n):
        if not bool(t.cand[j]):
            continue
        vm = t.v_valid[j] & (t.v_prio[j] < t.prio)
        nv = int(vm.sum())
        pc = int(t.pod_count[j])
        pcap = int(t.pod_cap[j])
        cc, mc = int(t.cpu_cap[j]), int(t.mem_cap[j])
        cu, mu = int(t.cpu_used[j]), int(t.mem_used[j])
        released_c = released_m = 0
        found = -1
        for k in range(nv + 1):
            if k > 0:
                released_c += int(t.v_cpu[j][k - 1])
                released_m += int(t.v_mem[j][k - 1])
            fits_count = (pc - k) < pcap
            if t.zero_req:
                ok = fits_count
            else:
                free_cpu = cc == 0 or cc - (cu - released_c) >= t.req_cpu
                free_mem = mc == 0 or mc - (mu - released_m) >= t.req_mem
                ok = fits_count and free_cpu and free_mem
            if ok:
                found = k
                break
        if found < 0:
            continue
        node_kstar[j] = found
        senior = int(t.v_prio[j][found - 1]) if found > 0 else SENIOR_NONE
        node_score[j] = composite_score(n, v, found, senior,
                                        int(t.tie_rank[j]))
    pick = int(np.argmax(node_score))  # first-max, like jnp.argmax
    return OracleResult(pick=pick, kstar=int(node_kstar[pick]),
                        feasible=bool(node_score[pick] >= 0),
                        node_kstar=node_kstar, node_score=node_score)


@dataclass
class PreemptionDecision:
    """One live eviction decision, recorded with the exact table it was
    computed from — the post-hoc audit replays the oracle over it."""
    pod_key: Tuple[str, str]
    pod_uid: str
    prio: int
    node: str
    pick: int
    kstar: int
    score: int
    victims: List[Tuple[str, str, str]]   # (ns, name, uid) chosen prefix
    table: VictimTable
    state_epoch: int
    shard_epochs: Optional[Tuple[int, ...]]
    # how many of `victims` were actually deleted (a Conflict/NotFound
    # strike stops the round early; the deleted ones are by construction
    # a prefix of the chosen — and audited — set)
    evicted: int = 0
    t: float = 0.0                        # pass clock, monotonic


def audit_decision(d: PreemptionDecision) -> List[str]:
    """Post-hoc wrongful-eviction audit: replay the serial oracle over
    the decision's recorded table. Returns violation strings (empty =
    clean). Checks, in order: device/oracle agreement, the never-evict-
    >=-priority invariant, and the never-evict-when-a-non-preempting-
    node-existed invariant."""
    out: List[str] = []
    o = oracle_find_victims(d.table)
    if not o.feasible:
        out.append(f"{d.pod_key}: oracle found NO feasible victim set "
                   f"but node {d.node} was preempted")
        return out
    if (o.pick, o.kstar) != (d.pick, d.kstar):
        out.append(f"{d.pod_key}: device picked node {d.pick} k={d.kstar}"
                   f", oracle node {o.pick} k={o.kstar}")
    if o.kstar == 0 and d.victims:
        out.append(f"{d.pod_key}: feasible non-preempting node "
                   f"{d.table.node_names[o.pick]} existed, yet "
                   f"{len(d.victims)} pods were evicted")
    want = o.victim_keys(d.table)
    if list(d.victims) != want:
        out.append(f"{d.pod_key}: victim set {d.victims} != oracle "
                   f"prefix {want}")
    vp = d.table.v_prio[d.pick]
    for i in range(min(d.kstar, d.table.v)):
        if int(vp[i]) >= d.prio:
            out.append(f"{d.pod_key}: victim {d.victims[i] if i < len(d.victims) else i} "
                       f"priority {int(vp[i])} >= preemptor {d.prio}")
    return out


class PreemptionPass:
    """Per-scheduler eviction-pass state: the seeded cooldown/backoff
    that prevents eviction storms, and the decision log the soak audits.

    A preemptor whose victim delete hits Conflict/NotFound (the PR-5
    contract: a same-name replacement won the name, or someone else
    already deleted the victim) is requeued FIFO and must NOT re-select
    the SAME victim set until a cooldown expires — capped jittered
    exponential backoff off one seeded stream, time from the injected
    Clock. A successful eviction round registers the same hold (flat,
    no escalation) so retries while the victims drain don't re-delete
    them; once the victims actually terminate the recomputed set
    differs and the hold no longer applies.

    A successful round also NOMINATES its node for a short TTL: while
    the victims drain (their resources still counted in the encoder),
    a second preemptor's victim search would see the identical table,
    pick the identical node, and the flash crowd would serialize one
    grace period per pod. Masking nominated nodes out of later
    searches spreads concurrent preemptors across distinct nodes — the
    reference's nominatedNodeName, reduced to one nomination per node
    (see DIVERGENCES #35). Normal (non-preempting) scheduling is
    unaffected; the mask only narrows victim searches.
    """

    def __init__(self, seed: int = 0, clock: Optional[Clock] = None,
                 cooldown_base: float = 0.25, cooldown_cap: float = 8.0,
                 grace_period_seconds: int = 1,
                 nominate_ttl: Optional[float] = None):
        self._rng = random.Random(f"{seed}:preemption")
        self._clock = clock or REAL
        self.cooldown_base = cooldown_base
        self.cooldown_cap = cooldown_cap
        self.grace_period_seconds = grace_period_seconds
        # long enough for the victims' graceful deletes to journal
        # their release, short enough that a stuck drain frees the
        # node for a fresh search
        self.nominate_ttl = (grace_period_seconds + 2.0
                             if nominate_ttl is None else nominate_ttl)
        # preemptor uid -> (hold-until monotonic, strikes, victim-set key)
        self._cool: Dict[str, Tuple[float, int, Any]] = {}
        # node name -> (nomination expiry monotonic, nominator uid)
        self._nominated: Dict[str, Tuple[float, str]] = {}
        self.decisions: List[PreemptionDecision] = []

    @staticmethod
    def vset_key(node: str, victims: Sequence[Tuple[str, str, str]]) -> Any:
        return (node, tuple(uid for _, _, uid in victims))

    def now(self) -> float:
        return self._clock.monotonic()

    def blocked(self, pod: api.Pod, vset_key: Any) -> bool:
        """Is this (preemptor, victim set) inside its cooldown window?
        A DIFFERENT victim set is never blocked — the cluster moved."""
        ent = self._cool.get(pod.metadata.uid)
        if ent is None or ent[2] != vset_key:
            return False
        return self.now() < ent[0]

    def hold(self, pod: api.Pod, vset_key: Any, escalate: bool) -> float:
        """Register a cooldown for this victim set; escalate=True (a
        Conflict/NotFound strike) doubles the window up to the cap,
        escalate=False (successful eviction round) keeps it flat."""
        prev = self._cool.get(pod.metadata.uid)
        strikes = 0
        if escalate:
            strikes = (prev[1] + 1) if prev is not None else 1
        window = min(self.cooldown_cap,
                     self.cooldown_base * (2.0 ** strikes))
        window *= 0.5 + 0.5 * self._rng.random()  # jitter, seeded
        self._cool[pod.metadata.uid] = (self.now() + window, strikes,
                                        vset_key)
        return window

    def nominate(self, node: str, uid: str = "",
                 ttl: Optional[float] = None) -> None:
        """Claim a node's draining capacity for one preemptor (uid)."""
        self._nominated[node] = (self.now() + (
            self.nominate_ttl if ttl is None else ttl), uid)

    def nominated_nodes(self, exclude_uid: Optional[str] = None
                        ) -> Set[str]:
        """Live nominations by OTHER preemptors (expired ones pruned) —
        the victim search masks these out of its candidate set. A pod's
        OWN nominated node stays visible to it: while its victims drain
        the recomputed set is identical, so the cooldown hold (not a
        fresh eviction) is what fires — masking it instead would push
        the pod onto a second node and cascade wasted evictions."""
        now = self.now()
        self._nominated = {n: e for n, e in self._nominated.items()
                           if e[0] > now}
        return {n for n, (_, uid) in self._nominated.items()
                if exclude_uid is None or uid != exclude_uid}

    def record(self, d: PreemptionDecision) -> None:
        self.decisions.append(d)

    def audit(self) -> List[str]:
        """Replay every recorded decision through the serial oracle."""
        out: List[str] = []
        for d in self.decisions:
            out.extend(audit_decision(d))
        return out
