"""System modeler: assumed-pod accounting between bind and watch confirm.

Reference: plugin/pkg/scheduler/modeler.go:87-197 SimpleModeler — a 30s-TTL
store of pods we've bound but whose binding the watch hasn't confirmed yet,
merged into the PodLister the algorithm sees so in-flight bindings count
against node capacity. LockedAction serializes bind vs forget (:47-56).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..core import labels as labelspkg
from ..core import types as api
from ..api.cache import meta_namespace_key

ASSUMED_POD_TTL = 30.0  # ref: modeler.go:108


class _TTLStore:
    """TTL-expiring keyed store (ref: cache.NewTTLStore)."""

    def __init__(self, ttl: float, clock=time):
        self.ttl = ttl
        self._clock = clock
        self._items: Dict[str, Tuple[api.Pod, float]] = {}

    def add(self, pod: api.Pod) -> None:
        self._items[meta_namespace_key(pod)] = (pod, self._clock.time())

    def delete_key(self, key: str) -> None:
        self._items.pop(key, None)

    def list(self) -> List[api.Pod]:
        now = self._clock.time()
        dead = [k for k, (_, ts) in self._items.items()
                if now - ts > self.ttl]
        for k in dead:
            del self._items[k]
        return [p for p, _ in self._items.values()]


class SimpleModeler:
    """(ref: modeler.go:87 SimpleModeler)

    queued_pods / scheduled_pods: listers with list(selector) + exists(pod).
    The merged pod lister = scheduled pods + still-assumed pods; a pod that
    has shown up in either underlying lister stops being assumed.
    """

    def __init__(self, queued_pods, scheduled_pods,
                 ttl: float = ASSUMED_POD_TTL, clock=time):
        self.queued_pods = queued_pods
        self.scheduled_pods = scheduled_pods
        self._assumed = _TTLStore(ttl, clock)
        self._lock = threading.RLock()
        self._clock = clock
        # forget tombstones, keyed (ns/name, uid): a confirm-reflector
        # forget that races AHEAD of the committer's assume must win, or
        # a pod deleted right after confirmation would sit assumed (and
        # consume phantom capacity) until the TTL. uid-scoped so a
        # recreated same-name pod assumes normally. Expiry rides an
        # insertion-ordered deque so GC is O(expired) per forget — a
        # full-dict rebuild was O(n^2) across a 30k-pod confirm storm.
        self._forgotten: Dict[Tuple[str, str], float] = {}
        self._forgotten_order: deque = deque()

    def locked_action(self, fn):
        """(ref: modeler.go:47 actionLocker.LockedAction)"""
        with self._lock:
            return fn()

    def _gc_tombstones(self, now: float) -> None:
        ttl = self._assumed.ttl
        order = self._forgotten_order
        while order and now - order[0][0] > ttl:
            ts, key = order.popleft()
            if self._forgotten.get(key) == ts:
                del self._forgotten[key]

    def _tombstoned(self, pod: api.Pod, now: float) -> bool:
        ts = self._forgotten.get(
            (meta_namespace_key(pod), pod.metadata.uid))
        return ts is not None and now - ts <= self._assumed.ttl

    def assume_pod(self, pod: api.Pod) -> None:
        with self._lock:
            if not self._tombstoned(pod, self._clock.time()):
                self._assumed.add(pod)

    def assume_pods(self, pods: List[api.Pod]) -> None:
        """One lock acquisition for a whole committed tile (the per-pod
        variant made the binder hold/drop the lock 8192 times per tile
        while the confirm reflector's forgets queued behind it)."""
        with self._lock:
            now = self._clock.time()
            for pod in pods:
                if not self._tombstoned(pod, now):
                    self._assumed.add(pod)

    def forget_pod(self, pod: api.Pod) -> None:
        with self._lock:
            now = self._clock.time()
            key = (meta_namespace_key(pod), pod.metadata.uid)
            self._forgotten[key] = now
            self._forgotten_order.append((now, key))
            self._gc_tombstones(now)
            self._assumed.delete_key(meta_namespace_key(pod))

    def forget_pod_by_key(self, key: str) -> None:
        with self._lock:
            self._assumed.delete_key(key)

    # -- the merged lister the algorithm sees (ref: modeler.go listPods) --

    def list(self, selector: Optional[labelspkg.Selector] = None
             ) -> List[api.Pod]:
        with self._lock:
            for pod in self._assumed.list():
                if self.queued_pods.exists(pod) or \
                        self.scheduled_pods.exists(pod):
                    self._assumed.delete_key(meta_namespace_key(pod))
            scheduled = self.scheduled_pods.list(selector)
            assumed = self._assumed.list()
            if selector is not None and not selector.empty():
                assumed = [p for p in assumed
                           if selector.matches(p.metadata.labels)]
            seen = {meta_namespace_key(p) for p in scheduled}
            merged = scheduled + [p for p in assumed
                                  if meta_namespace_key(p) not in seen]
            return merged

    def exists(self, pod: api.Pod) -> bool:
        key = meta_namespace_key(pod)
        return any(meta_namespace_key(p) == key for p in self.list())

    def pod_lister(self) -> "SimpleModeler":
        return self
