"""Fit predicates — bit-exact re-statement of the reference's semantics.

Reference: plugin/pkg/scheduler/algorithm/predicates/predicates.go. Every
function documents its source symbol. Signature convention: a predicate is
`fn(pod, existing_pods, node) -> (fit: bool, reason: Optional[str])`; reason
is a failure tag like the reference's FailedResourceType global
(predicates.go:148) — returning it beats mutating a global. `node` is the
api.Node object (the reference passes a node name + NodeInfo getter; our
listers hand the object over directly).

Parity-critical details preserved:
  - getResourceRequest sums requests as integer milliCPU / bytes
    (predicates.go:150).
  - CheckPodsExceedingFreeResources processes pods in list order and SKIPS
    non-fitting pods from the running sum (predicates.go:160-185) — so one
    over-capacity existing pod can fail the predicate for the new pod.
  - Zero-request pods are only checked against the pod-count capacity
    (predicates.go:198-199).
  - Capacity of 0 for cpu/memory means "unlimited" in the fit check
    (CheckPodsExceedingFreeResources: totalMilliCPU == 0 -> fitsCPU).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..core import labels as labelspkg
from ..core import types as api

PredicateResult = Tuple[bool, Optional[str]]

# failure tags (ref: predicates.go FailedResourceType values)
POD_EXCEEDS_FREE_CPU = "PodExceedsFreeCPU"
POD_EXCEEDS_FREE_MEMORY = "PodExceedsFreeMemory"
POD_EXCEEDS_MAX_POD_NUMBER = "PodExceedsMaxPodNumber"
NODE_NOT_SCHEDULABLE = "NodeNotSchedulable"


def node_schedulable(node: api.Node) -> bool:
    """Is the node a live binding target? (ref: factory.go:241
    getNodeConditionPredicate + createNodeLW's spec.unschedulable field
    selector, :281-285.)

    False when spec.unschedulable is set, when the Ready condition is
    not True (False OR Unknown — a stale-heartbeat node the
    NodeController marked Unknown is dead to the scheduler), or when
    OutOfDisk is reported anything but False. The single source of
    node-schedulability truth: the serial oracle's predicate, the
    factory's candidate filter and the device encoders' mask column all
    call this."""
    if node.spec.unschedulable:
        return False
    for cond in node.status.conditions:
        if cond.type == api.NODE_READY and cond.status != api.CONDITION_TRUE:
            return False
        if cond.type == api.NODE_OUT_OF_DISK and \
                cond.status != api.CONDITION_FALSE:
            return False
    return True


def pod_fits_node_schedulable(pod: api.Pod, existing_pods: Sequence[api.Pod],
                              node: api.Node) -> PredicateResult:
    """Node-schedulability as a fit predicate, so a node list that was
    NOT pre-filtered (static listers, mid-tile condition flips) still
    never produces a bind to a NotReady/Unknown/cordoned node."""
    if node_schedulable(node):
        return True, None
    return False, NODE_NOT_SCHEDULABLE


def get_resource_request(pod: api.Pod) -> Tuple[int, int]:
    """(milliCPU, memory bytes) summed over containers
    (ref: predicates.go:150 getResourceRequest)."""
    milli_cpu = 0
    memory = 0
    for c in pod.spec.containers:
        req = c.resources.requests
        if "cpu" in req:
            milli_cpu += req["cpu"].milli
        if "memory" in req:
            memory += req["memory"].value
    return milli_cpu, memory


def _capacity(node: api.Node, resource: str) -> int:
    q = node.status.capacity.get(resource)
    if q is None:
        return 0
    return q.milli if resource == "cpu" else q.value


def check_pods_exceeding_free_resources(
        pods: Sequence[api.Pod], node: api.Node
) -> Tuple[List[api.Pod], List[api.Pod], List[api.Pod]]:
    """(fitting, not_fitting_cpu, not_fitting_memory); order-dependent with
    skip-on-misfit accounting (ref: predicates.go:160
    CheckPodsExceedingFreeResources)."""
    total_milli_cpu = _capacity(node, "cpu")
    total_memory = _capacity(node, "memory")
    cpu_requested = 0
    mem_requested = 0
    fitting: List[api.Pod] = []
    not_cpu: List[api.Pod] = []
    not_mem: List[api.Pod] = []
    for pod in pods:
        req_cpu, req_mem = get_resource_request(pod)
        fits_cpu = total_milli_cpu == 0 or (total_milli_cpu - cpu_requested) >= req_cpu
        fits_mem = total_memory == 0 or (total_memory - mem_requested) >= req_mem
        if not fits_cpu:
            not_cpu.append(pod)
            continue
        if not fits_mem:
            not_mem.append(pod)
            continue
        cpu_requested += req_cpu
        mem_requested += req_mem
        fitting.append(pod)
    return fitting, not_cpu, not_mem


def pod_fits_resources(pod: api.Pod, existing_pods: Sequence[api.Pod],
                       node: api.Node) -> PredicateResult:
    """(ref: predicates.go:192 ResourceFit.PodFitsResources)"""
    req_cpu, req_mem = get_resource_request(pod)
    pod_cap = node.status.capacity.get("pods")
    pod_cap_value = pod_cap.value if pod_cap is not None else 0
    if req_cpu == 0 and req_mem == 0:
        # zero-request pods are only limited by the pod-count capacity;
        # the reference leaves FailedResourceType unset on this path
        # (predicates.go:198-199), so the failure map records the
        # predicate name — reason None mirrors that
        return len(existing_pods) < pod_cap_value, None
    pods = list(existing_pods) + [pod]
    _, exceeding_cpu, exceeding_mem = check_pods_exceeding_free_resources(pods, node)
    if len(pods) > pod_cap_value:
        return False, POD_EXCEEDS_MAX_POD_NUMBER
    if exceeding_cpu:
        return False, POD_EXCEEDS_FREE_CPU
    if exceeding_mem:
        return False, POD_EXCEEDS_FREE_MEMORY
    return True, None


def pod_fits_host_ports(pod: api.Pod, existing_pods: Sequence[api.Pod],
                        node: api.Node) -> PredicateResult:
    """hostPort collision (ref: predicates.go:403 PodFitsHostPorts;
    getUsedPorts :417 — port 0 means unbound and never collides)."""
    existing_ports = get_used_ports(existing_pods)
    want_ports = get_used_ports([pod])
    for port in want_ports:
        if port == 0:
            continue
        if port in existing_ports:
            return False, None
    return True, None


def get_used_ports(pods: Sequence[api.Pod]) -> Dict[int, bool]:
    ports: Dict[int, bool] = {}
    for pod in pods:
        for c in pod.spec.containers:
            for p in c.ports:
                ports[p.host_port] = True
    return ports


def pod_fits_host(pod: api.Pod, existing_pods: Sequence[api.Pod],
                  node: api.Node) -> PredicateResult:
    """spec.nodeName pinning (ref: predicates.go:258 PodFitsHost)."""
    if not pod.spec.node_name:
        return True, None
    return pod.spec.node_name == node.metadata.name, None


def pod_matches_node_labels(pod: api.Pod, node: api.Node) -> bool:
    """(ref: predicates.go:238 PodMatchesNodeLabels)"""
    if not pod.spec.node_selector:
        return True
    sel = labelspkg.selector_from_set(pod.spec.node_selector)
    return sel.matches(node.metadata.labels)


def pod_selector_matches(pod: api.Pod, existing_pods: Sequence[api.Pod],
                         node: api.Node) -> PredicateResult:
    """(ref: predicates.go:250 NodeSelector.PodSelectorMatches)"""
    return pod_matches_node_labels(pod, node), None


# ------------------------------------------------------------ disk conflict

def _have_same(a: Sequence[str], b: Sequence[str]) -> bool:
    return any(x in b for x in a)


def is_volume_conflict(volume: api.Volume, pod: api.Pod) -> bool:
    """(ref: predicates.go:75 isVolumeConflict)
    - GCE PD: same pdName conflicts unless both mounts are read-only
    - AWS EBS: same volumeID always conflicts
    - Ceph RBD: shared monitor + same pool + same image conflicts
    """
    if volume.gce_persistent_disk is not None:
        disk = volume.gce_persistent_disk
        for ev in pod.spec.volumes:
            if (ev.gce_persistent_disk is not None
                    and ev.gce_persistent_disk.pd_name == disk.pd_name
                    and not (ev.gce_persistent_disk.read_only and disk.read_only)):
                return True
    if volume.aws_elastic_block_store is not None:
        vol_id = volume.aws_elastic_block_store.volume_id
        for ev in pod.spec.volumes:
            if (ev.aws_elastic_block_store is not None
                    and ev.aws_elastic_block_store.volume_id == vol_id):
                return True
    if volume.rbd is not None:
        mon, pool, image = (volume.rbd.ceph_monitors, volume.rbd.rbd_pool,
                            volume.rbd.rbd_image)
        for ev in pod.spec.volumes:
            if ev.rbd is not None:
                if (_have_same(mon, ev.rbd.ceph_monitors)
                        and ev.rbd.rbd_pool == pool
                        and ev.rbd.rbd_image == image):
                    return True
    return False


def no_disk_conflict(pod: api.Pod, existing_pods: Sequence[api.Pod],
                     node: api.Node) -> PredicateResult:
    """(ref: predicates.go:127 NoDiskConflict)"""
    for volume in pod.spec.volumes:
        for existing in existing_pods:
            if is_volume_conflict(volume, existing):
                return False, None
    return True, None


# ------------------------------------------------------ configurable preds

def new_node_label_predicate(wanted: Sequence[str], presence: bool):
    """(ref: predicates.go:292 CheckNodeLabelPresence)"""
    def check_node_label_presence(pod, existing_pods, node) -> PredicateResult:
        node_labels = node.metadata.labels
        for label in wanted:
            exists = label in node_labels
            if (exists and not presence) or (not exists and presence):
                return False, None
        return True, None
    return check_node_label_presence


def new_service_affinity_predicate(pod_lister, service_lister,
                                   affinity_labels: Sequence[str],
                                   node_by_name=None):
    """Implicit node-label affinity inherited from peer service pods
    (ref: predicates.go:334 ServiceAffinity.CheckServiceAffinity). The
    reference resolves the peer pod's node via NodeInfo wired at
    construction; `node_by_name(name) -> Optional[Node]` plays that role."""
    def check_service_affinity(pod, existing_pods, node) -> PredicateResult:
        affinity: Dict[str, str] = {}
        labels_exist = True
        for l in affinity_labels:
            if l in pod.spec.node_selector:
                affinity[l] = pod.spec.node_selector[l]
            else:
                labels_exist = False
        if not labels_exist:
            services = service_lister.get_pod_services(pod)
            if services:
                sel = labelspkg.selector_from_set(services[0].spec.selector)
                service_pods = [p for p in pod_lister.list(sel)
                                if p.metadata.namespace == pod.metadata.namespace]
                if service_pods:
                    getter = node_by_name or (lambda n: None)
                    other = getter(service_pods[0].spec.node_name)
                    if other is not None:
                        for l in affinity_labels:
                            if l in affinity:
                                continue
                            if l in other.metadata.labels:
                                affinity[l] = other.metadata.labels[l]
        if not affinity:
            return True, None
        sel = labelspkg.selector_from_set(affinity)
        return sel.matches(node.metadata.labels), None
    return check_service_affinity


# ------------------------------------------------ inter-pod affinity tier

def term_namespaces(pod: api.Pod, term: api.PodAffinityTerm) -> List[str]:
    """Resolved namespace scope: empty list means the pod's own namespace."""
    return list(term.namespaces) if term.namespaces else [pod.metadata.namespace]


def pod_matches_term(candidate: api.Pod, pod: api.Pod,
                     term: api.PodAffinityTerm) -> bool:
    """Does `candidate` fall inside `term`'s selector+namespace scope
    (scope resolved relative to `pod`, the term's owner)?"""
    if candidate.metadata.namespace not in term_namespaces(pod, term):
        return False
    sel = labelspkg.selector_from_set(term.label_selector)
    return sel.matches(candidate.metadata.labels)


def new_inter_pod_affinity_predicate(pod_lister, node_by_name):
    """Required inter-pod affinity/anti-affinity — the quadratic pod x pod
    term (BASELINE config 4; no v1.1 reference symbol — see
    core/types.py PodAffinityTerm).

    Semantics (the parity contract the device engine reproduces):
      - affinity term: the candidate node must carry `topology_key`, and
        some running, assigned pod matching the term must live on a node
        with the same value for that key. Bootstrap rule: if NO pod
        anywhere matches the term but the incoming pod matches its own
        term, the term is satisfied (first pod of a self-affine group).
      - anti-affinity term: no running, assigned pod matching the term may
        share the candidate node's topology domain; a node lacking the key
        belongs to no domain and always passes.
      - pods on unknown nodes (node_by_name -> None) or nodes lacking the
        key occupy no domain; Succeeded/Failed pods are ignored, matching
        MapPodsToMachines' phase filter (predicates.go:429).
    """
    def inter_pod_affinity(pod: api.Pod, existing_pods, node) -> PredicateResult:
        affinity = pod.spec.affinity
        if affinity is None:
            return True, None
        aff_terms = (affinity.pod_affinity.required_during_scheduling
                     if affinity.pod_affinity else [])
        anti_terms = (affinity.pod_anti_affinity.required_during_scheduling
                      if affinity.pod_anti_affinity else [])
        if not aff_terms and not anti_terms:
            return True, None
        all_pods = filter_non_running_pods(
            pod_lister.list(labelspkg.everything()))

        def domain_value(p: api.Pod, key: str) -> Optional[str]:
            if not p.spec.node_name:
                return None
            host = node_by_name(p.spec.node_name)
            if host is None:
                return None
            return host.metadata.labels.get(key)

        for term in aff_terms:
            node_value = node.metadata.labels.get(term.topology_key)
            if node_value is None:
                # an affinity term always needs the key, even under the
                # bootstrap rule — else the first pod of a group could land
                # on a domain-less node and strand the rest
                return False, None
            matches = [p for p in all_pods if pod_matches_term(p, pod, term)]
            if not matches and pod_matches_term(pod, pod, term):
                continue  # bootstrap: first pod of a self-affine group
            if not any(domain_value(p, term.topology_key) == node_value
                       for p in matches):
                return False, None
        for term in anti_terms:
            node_value = node.metadata.labels.get(term.topology_key)
            if node_value is None:
                continue
            for p in all_pods:
                if pod_matches_term(p, pod, term) and \
                        domain_value(p, term.topology_key) == node_value:
                    return False, None
        return True, None
    return inter_pod_affinity


def filter_non_running_pods(pods: Sequence[api.Pod]) -> List[api.Pod]:
    """Drop Succeeded/Failed pods (ref: predicates.go:429
    filterNonRunningPods)."""
    return [p for p in pods
            if p.status.phase not in (api.POD_SUCCEEDED, api.POD_FAILED)]


def map_pods_to_machines(pod_lister) -> Dict[str, List[api.Pod]]:
    """Pivot all pods into hostname -> pods (ref: predicates.go:445
    MapPodsToMachines; unassigned pods land under "")."""
    machine_to_pods: Dict[str, List[api.Pod]] = {}
    pods = filter_non_running_pods(pod_lister.list(labelspkg.everything()))
    for pod in pods:
        machine_to_pods.setdefault(pod.spec.node_name, []).append(pod)
    return machine_to_pods
