"""Scheduler config factory: watch wiring + algorithm assembly.

Reference: plugin/pkg/scheduler/factory/factory.go:47-452 —
  - unassigned pods (spec.nodeName= field selector, :260-262) -> FIFO queue
  - assigned pods (spec.nodeName!=) -> ScheduledPodLister; informer handlers
    forget modeler assumptions (:92-115)
  - nodes with spec.unschedulable=false (:281-285) further filtered by the
    readiness condition predicate (Ready==True && OutOfDisk==False,
    :241-256)
  - services + RCs for the spreading priorities
  - binder POSTs Bindings (:353-364)
  - default error func: 1s->60s exponential pod backoff + requeue
    (:376-452)
"""

from __future__ import annotations

import time
import threading
from typing import Callable, List, Optional

from ..api.cache import (FIFO, Informer, ObjectCache, Reflector,
                         StoreToPodLister, StoreToReplicationControllerLister,
                         StoreToServiceLister, meta_namespace_key)
from ..core import types as api
from ..utils.backoff import Backoff
from ..utils.ratelimit import TokenBucketRateLimiter
from . import plugins
from .api import Policy
from .extender import HTTPExtender
from .generic import GenericScheduler
from .modeler import SimpleModeler
from .predicates import node_schedulable
from .scheduler import Scheduler, SchedulerConfig

DEFAULT_BIND_PODS_QPS = 50.0   # ref: plugin/cmd/kube-scheduler/app/server.go:69
DEFAULT_BIND_PODS_BURST = 100  # ref: server.go:70


def node_condition_predicate(node: api.Node) -> bool:
    """(ref: factory.go:241 getNodeConditionPredicate; the
    spec.unschedulable check stands in for createNodeLW's server-side
    field selector — the informer is deliberately UNfiltered here, see
    ConfigFactory). Delegates to predicates.node_schedulable so the
    candidate filter, the serial NodeSchedulable predicate and the
    device encoders' sched_ok mask cannot drift."""
    return node_schedulable(node)


class ReadyNodeLister:
    """Node lister filtered to schedulable+ready nodes; get() looks up any
    cached node by name (the NodeInfo role for ServiceAffinity)."""

    def __init__(self, cache: ObjectCache):
        self.cache = cache

    def list(self) -> List[api.Node]:
        return [n for n in self.cache.list() if node_condition_predicate(n)]

    def get(self, name: str) -> Optional[api.Node]:
        return self.cache.get_by_key(name)


class Binder:
    """(ref: factory.go:353 binder — POST bindings)"""

    def __init__(self, client):
        self.client = client

    def bind(self, binding: api.Binding):
        return self.client.bind(binding)


class PodQueueLister:
    """Lister view over the pending FIFO (modeler's queuedPods)."""

    def __init__(self, fifo: FIFO):
        self.fifo = fifo

    def list(self, selector=None) -> List[api.Pod]:
        pods = self.fifo.list()
        if selector is not None and not selector.empty():
            pods = [p for p in pods if selector.matches(p.metadata.labels)]
        return pods

    def exists(self, pod: api.Pod) -> bool:
        return self.fifo.contains(meta_namespace_key(pod))


# engine core predicates: always enforced by the device scan (1.0 alias
# PodFitsPorts accepted); a policy must name all of them to be eligible
_ENGINE_CORE_PREDICATES = {"PodFitsResources", "NoDiskConflict",
                           "MatchNodeSelector", "HostName"}


def _translate_policy(policy):
    """Policy -> (weights, DevicePolicy) for the device engine, or None if
    the policy needs the serial path. See ConfigFactory.create_batch."""
    from .device import DevicePolicy
    if policy is None:
        return (1, 1, 1), None
    if policy.extenders:
        return None
    dev = DevicePolicy()
    if policy.predicates:
        named = set()
        for p in policy.predicates:
            if p.service_affinity is not None:
                return None  # peer-inherited node affinity: serial only
            if p.labels_presence is not None:
                dev.label_presence.append(
                    (tuple(p.labels_presence.labels),
                     p.labels_presence.presence))
                continue
            named.add("PodFitsHostPorts" if p.name == "PodFitsPorts"
                      else p.name)
        # InterPodAffinity is required too: the engine enforces the
        # affinity mask unconditionally, so a policy omitting it would get
        # a stricter engine than its serial counterpart
        required = _ENGINE_CORE_PREDICATES | {"PodFitsHostPorts",
                                              "InterPodAffinity"}
        # NodeSchedulable is enforced unconditionally by the engine's
        # sched_ok mask (and by the serial path's candidate filter), so
        # a policy may name it but never has to
        if not required <= named or named - (required | {"NodeSchedulable"}):
            return None  # dropped core predicate / unknown name
    weights = [1, 1, 1]
    if policy.priorities:
        weights = [0, 0, 0]
        slot = {"LeastRequestedPriority": 0,
                "BalancedResourceAllocation": 1,
                "SelectorSpreadPriority": 2}
        for p in policy.priorities:
            if p.service_anti_affinity is not None:
                if dev.needs_anti_affinity:
                    return None  # engine encodes one zone label
                dev.anti_affinity_label = p.service_anti_affinity.label
                dev.anti_affinity_weight = p.weight
                continue
            if p.label_preference is not None:
                dev.label_priorities.append(
                    (p.label_preference.label, p.label_preference.presence,
                     p.weight))
                continue
            if p.name in slot:
                weights[slot[p.name]] += p.weight
            elif p.name == "EqualPriority":
                pass  # constant shift across nodes: argmax-invariant
            else:
                return None  # e.g. ServiceSpreadingPriority (services-only)
    dev_needed = (dev.needs_anti_affinity or dev.label_presence
                  or dev.label_priorities)
    return tuple(weights), (dev if dev_needed else None)


class ConfigFactory:
    """(ref: factory.go:72 NewConfigFactory)"""

    def __init__(self, client, bind_qps: float = DEFAULT_BIND_PODS_QPS,
                 bind_burst: int = DEFAULT_BIND_PODS_BURST,
                 rate_limit: bool = True, recorder=None):
        self.client = client
        self.pod_queue = FIFO()
        self.recorder = recorder

        # unassigned pods -> FIFO (ref: createUnassignedPodLW :260)
        self.unassigned_reflector = Reflector(
            client, "pods", field_selector="spec.nodeName=",
            store=self.pod_queue)

        # assigned pods -> ScheduledPodLister; forget modeler assumptions on
        # add/delete (ref: factory.go:92-115 scheduledPodPopulator).
        # scheduled_observers: external hooks (kubemark benchmark / SLO
        # probes) ride THIS informer instead of opening their own watch —
        # the reference benchmark likewise watches completion through the
        # scheduler's ScheduledPodLister (scheduler_test.go:278), and a
        # duplicate pods watch costs a per-event fan-out at 30k scale
        self.scheduled_observers: List[Callable] = []
        self.scheduled_cache = ObjectCache()
        self.scheduled_reflector = Reflector(
            client, "pods", field_selector="spec.nodeName!=",
            store=self.scheduled_cache,
            on_add=self._scheduled_added, on_delete=self._forget)
        self.scheduled_pod_lister = StoreToPodLister(self.scheduled_cache)

        # nodes: UNfiltered, unlike createNodeLW's
        # spec.unschedulable=false selector (:281) — the reference pairs
        # that filtered watch with a NodeInfo that hits the live nodes
        # API (factory.go CreateFromKeys: f.Client.Nodes()), so
        # ServiceAffinity/anti-affinity still resolve CORDONED nodes'
        # labels. One unfiltered cache lands the same observable
        # semantics: candidate lists apply node_condition_predicate
        # (which now covers unschedulable), while get() — the NodeInfo
        # role — resolves any cached node, so pods on cordoned nodes
        # keep occupying their topology domains instead of silently
        # vanishing from affinity math
        self.node_informer = Informer(client, "nodes")
        self.node_lister = ReadyNodeLister(self.node_informer.cache)

        # services + RCs (ref: createServiceLW/createControllerLW :288-295)
        self.service_informer = Informer(client, "services")
        self.service_lister = StoreToServiceLister(self.service_informer.cache)
        self.controller_informer = Informer(client, "replicationcontrollers")
        self.controller_lister = StoreToReplicationControllerLister(
            self.controller_informer.cache)

        self.modeler = SimpleModeler(PodQueueLister(self.pod_queue),
                                     self.scheduled_pod_lister)
        self.pod_lister = self.modeler  # the merged view the algorithm sees
        self.backoff = Backoff(1.0, 60.0)  # ref: factory.go podBackoff
        # shared delayed-requeue machinery (see _requeue_worker)
        self._requeue_heap: list = []
        self._requeue_cond = threading.Condition()
        self._requeue_thread: Optional[threading.Thread] = None
        self._requeue_seq = 0
        self.rate_limiter = TokenBucketRateLimiter(bind_qps, bind_burst) \
            if rate_limit else None
        self._started = False
        self._error_func = None

    def _forget(self, pod: api.Pod) -> None:
        self.modeler.locked_action(lambda: self.modeler.forget_pod(pod))

    def _scheduled_added(self, pod: api.Pod) -> None:
        self._forget(pod)
        for cb in self.scheduled_observers:
            cb(pod)

    # ------------------------------------------------------------- wiring

    def start(self) -> "ConfigFactory":
        if not self._started:
            self.unassigned_reflector.start()
            self.scheduled_reflector.start()
            self.node_informer.start()
            self.service_informer.start()
            self.controller_informer.start()
            self._started = True
        return self

    def stop(self) -> None:
        self.pod_queue.close()
        self.unassigned_reflector.stop()
        self.scheduled_reflector.stop()
        self.node_informer.stop()
        self.service_informer.stop()
        self.controller_informer.stop()

    def plugin_args(self) -> plugins.PluginFactoryArgs:
        return plugins.PluginFactoryArgs(
            pod_lister=self.pod_lister,
            service_lister=self.service_lister,
            controller_lister=self.controller_lister,
            node_lister=self.node_lister)

    # ----------------------------------------------------------- assembly

    def create(self) -> SchedulerConfig:
        """Default algorithm provider (ref: factory.go Create)."""
        return self.create_from_provider(plugins.DEFAULT_PROVIDER)

    def create_from_provider(self, provider_name: str) -> SchedulerConfig:
        predicate_keys, priority_keys = plugins.get_algorithm_provider(
            provider_name)
        args = self.plugin_args()
        return self._create(
            plugins.get_fit_predicates(predicate_keys, args),
            plugins.get_priority_configs(priority_keys, args),
            extenders=[])

    def create_from_config(self, policy: Policy) -> SchedulerConfig:
        """(ref: factory.go:137 CreateFromConfig — empty lists fall back to
        the provider defaults)."""
        args = self.plugin_args()
        if policy.predicates:
            # key collisions (e.g. two unnamed labelsPresence entries) must
            # not drop predicates — the device engine enforces all of them
            predicates = {}
            for p in policy.predicates:
                key = p.name
                while key in predicates:
                    key += "#"
                predicates[key] = plugins.predicate_from_policy(p, args)
        else:
            keys, _ = plugins.get_algorithm_provider(plugins.DEFAULT_PROVIDER)
            predicates = plugins.get_fit_predicates(keys, args)
        if policy.priorities:
            priorities = [plugins.priority_from_policy(p, args)
                          for p in policy.priorities]
        else:
            _, keys = plugins.get_algorithm_provider(plugins.DEFAULT_PROVIDER)
            priorities = plugins.get_priority_configs(keys, args)
        extenders = [HTTPExtender(cfg) for cfg in policy.extenders]
        return self._create(predicates, priorities, extenders)

    def _create(self, predicates, priorities, extenders,
                algorithm=None, on_assume=None) -> SchedulerConfig:
        if algorithm is None:
            algorithm = GenericScheduler(predicates, priorities,
                                         self.pod_lister, extenders)
        return SchedulerConfig(
            algorithm=algorithm,
            next_pod=self._next_pod,
            binder=Binder(self.client),
            node_lister=self.node_lister,
            modeler=self.modeler,
            error=self.make_default_error_func(),
            recorder=self.recorder,
            bind_pods_rate_limiter=self.rate_limiter,
            on_assume=on_assume)

    def _next_pod(self) -> Optional[api.Pod]:
        """(ref: factory.go:230 NextPod — blocking FIFO pop)"""
        return self.pod_queue.pop(timeout=0.5)

    @property
    def error_func(self) -> Callable:
        """Shared backoff+requeue error handler (batch path)."""
        if self._error_func is None:
            self._error_func = self.make_default_error_func()
        return self._error_func

    def create_batch(self, policy: Optional[Policy] = None, **kw):
        """TPU fast-path config, or None if the policy needs the serial
        path. The engine covers the default provider's predicate/priority
        set plus the policy-file customs it can encode statically
        (CheckNodeLabelPresence, CalculateNodeLabelPriority,
        ServiceAntiAffinity — device.DevicePolicy). Anything else
        (ServiceAffinity predicates, HTTP extenders, a policy that drops
        one of the engine's core predicates) must use
        create()/create_from_config() — the provable serial fallback the
        BASELINE requires."""
        from .batch import BatchSchedulerConfig
        from .device import BatchEngine
        translated = _translate_policy(policy)
        if translated is None:
            return None
        weights, device_policy = translated
        if device_policy is not None or weights != (1, 1, 1):
            if "engine" in kw:
                raise ValueError(
                    "create_batch: cannot combine an explicit engine with "
                    "a policy that needs engine configuration")
            kw["engine"] = BatchEngine(weights, policy=device_policy)
        return BatchSchedulerConfig(self, **kw)

    def create_mixed(self, policy: Optional[Policy]):
        """Mixed-mode config (device probe + HTTP extenders), or None if
        the policy doesn't qualify: it must carry extenders (otherwise
        create_batch is strictly better) and its predicate/priority set
        must map onto the engine without DevicePolicy tiers (the
        incremental encoder's domain). The middle rung of the ladder
        batch > mixed > serial."""
        if policy is None or not policy.extenders:
            return None
        stripped = Policy(predicates=policy.predicates,
                          priorities=policy.priorities, extenders=[])
        translated = _translate_policy(stripped)
        if translated is None:
            return None
        weights, device_policy = translated
        if device_policy is not None:
            return None
        from .device import BatchEngine
        from .device_assist import DeviceAssistedAlgorithm
        serial = self.create_from_config(policy)
        algorithm = DeviceAssistedAlgorithm(
            self, BatchEngine(weights),
            extenders=serial.algorithm.extenders,
            serial_fallback=serial.algorithm)
        return self._create({}, [], [], algorithm=algorithm,
                            on_assume=algorithm.assume)

    def _requeue_worker(self) -> None:
        """ONE thread drains the time-ordered requeue heap — a
        goroutine-per-pod translation of makeDefaultErrorFunc would
        spawn an OS thread per failed pod and, on a cluster-full 30k-pod
        tile, exhaust the process thread limit (after which the silent
        Thread.start() failures strand pods Pending forever)."""
        import heapq
        while True:
            with self._requeue_cond:
                while not self._requeue_heap:
                    self._requeue_cond.wait()
                due, _seq, pod = self._requeue_heap[0]
                delay = due - time.monotonic()
                if delay > 0:
                    self._requeue_cond.wait(delay)
                    continue
                heapq.heappop(self._requeue_heap)
            self.backoff.gc()
            try:
                fresh = self.client.get("pods", pod.metadata.name,
                                        pod.metadata.namespace)
            except Exception:
                continue
            if not fresh.spec.node_name:
                self.pod_queue.add(fresh)

    def make_default_error_func(self) -> Callable:
        """(ref: factory.go:297 makeDefaultErrorFunc — backoff + requeue)"""
        import heapq

        def error_func(pod: api.Pod, err: Exception) -> None:
            # ref requeues with backoff for ALL errors — including
            # ErrNoNodesAvailable, which it only logs differently; the pod
            # was consumed from the FIFO, so skipping the requeue would
            # strand it Pending forever
            key = meta_namespace_key(pod)
            due = time.monotonic() + self.backoff.get(key)
            with self._requeue_cond:
                if self._requeue_thread is None:
                    self._requeue_thread = threading.Thread(
                        target=self._requeue_worker, daemon=True,
                        name="sched-requeue")
                    self._requeue_thread.start()
                self._requeue_seq += 1
                heapq.heappush(self._requeue_heap,
                               (due, self._requeue_seq, pod))
                self._requeue_cond.notify()
        return error_func
