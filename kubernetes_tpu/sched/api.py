"""Scheduler policy API — config-as-API-object.

Reference: plugin/pkg/scheduler/api/{types,v1,validation} — the versioned
Policy kind decoded from a JSON --policy-config-file, listing predicate /
priority names (with per-plugin arguments) and HTTP extenders
(examples/scheduler-policy-config.json,
 examples/scheduler-policy-config-with-extender.json).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..core.errors import Invalid


@dataclass(frozen=True)
class HostPriority:
    """(ref: plugin/pkg/scheduler/api/types.go:150 HostPriority)"""
    host: str
    score: int


@dataclass
class ServiceAffinityArgs:
    labels: List[str] = field(default_factory=list)


@dataclass
class LabelsPresenceArgs:
    labels: List[str] = field(default_factory=list)
    presence: bool = False


@dataclass
class PredicatePolicy:
    name: str = ""
    # argument variants (ref: api/types.go PredicateArgument)
    service_affinity: Optional[ServiceAffinityArgs] = None
    labels_presence: Optional[LabelsPresenceArgs] = None


@dataclass
class ServiceAntiAffinityArgs:
    label: str = ""


@dataclass
class LabelPreferenceArgs:
    label: str = ""
    presence: bool = False


@dataclass
class PriorityPolicy:
    name: str = ""
    weight: int = 1
    service_anti_affinity: Optional[ServiceAntiAffinityArgs] = None
    label_preference: Optional[LabelPreferenceArgs] = None


@dataclass
class ExtenderConfig:
    """(ref: api/types.go:114 ExtenderConfig)"""
    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    weight: int = 1
    api_version: str = "v1"
    http_timeout: float = 5.0  # ref: extender.go:33 DefaultExtenderTimeout
    enable_https: bool = False


@dataclass
class Policy:
    predicates: List[PredicatePolicy] = field(default_factory=list)
    priorities: List[PriorityPolicy] = field(default_factory=list)
    extenders: List[ExtenderConfig] = field(default_factory=list)


def policy_from_json(raw: str) -> Policy:
    """Decode + validate a policy config file
    (ref: api/validation/validation.go:43 — extender weight must be
    positive)."""
    try:
        data = json.loads(raw)
    except json.JSONDecodeError as e:
        raise Invalid(f"invalid policy JSON: {e}")
    pol = Policy()
    for p in data.get("predicates", []):
        pp = PredicatePolicy(name=p.get("name", ""))
        arg = p.get("argument") or {}
        if "serviceAffinity" in arg:
            pp.service_affinity = ServiceAffinityArgs(
                labels=arg["serviceAffinity"].get("labels", []))
        if "labelsPresence" in arg:
            pp.labels_presence = LabelsPresenceArgs(
                labels=arg["labelsPresence"].get("labels", []),
                presence=arg["labelsPresence"].get("presence", False))
        pol.predicates.append(pp)
    for p in data.get("priorities", []):
        pr = PriorityPolicy(name=p.get("name", ""),
                            weight=p.get("weight", 1))
        # ref: validation.go ValidatePolicy — priorities need positive weight
        if pr.weight <= 0:
            raise Invalid(
                f"Priority {pr.name} should have a positive weight applied to it")
        arg = p.get("argument") or {}
        if "serviceAntiAffinity" in arg:
            pr.service_anti_affinity = ServiceAntiAffinityArgs(
                label=arg["serviceAntiAffinity"].get("label", ""))
        if "labelPreference" in arg:
            pr.label_preference = LabelPreferenceArgs(
                label=arg["labelPreference"].get("label", ""),
                presence=arg["labelPreference"].get("presence", False))
        pol.priorities.append(pr)
    for e in data.get("extenders", []):
        weight = e.get("weight", 1)
        # ref: validation.go — extender weight must be non-negative
        if weight < 0:
            raise Invalid(
                f"Priority for extender {e.get('urlPrefix', '')} should have "
                f"a non negative weight applied to it")
        pol.extenders.append(ExtenderConfig(
            url_prefix=e.get("urlPrefix", ""),
            filter_verb=e.get("filterVerb", ""),
            prioritize_verb=e.get("prioritizeVerb", ""),
            weight=weight,
            api_version=e.get("apiVersion", "v1"),
            http_timeout=e.get("httpTimeout", 5.0),
            enable_https=e.get("enableHttps", False)))
        if not pol.extenders[-1].url_prefix:
            raise Invalid("extender urlPrefix is required")
    return pol
