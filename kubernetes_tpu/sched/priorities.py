"""Priority functions — bit-exact re-statement of the reference's scoring.

Reference: plugin/pkg/scheduler/algorithm/priorities/priorities.go and
selector_spreading.go. All scores are ints 0..10; callers weight and sum.

Parity-critical details preserved:
  - calculateScore (priorities.go:33): integer division truncation;
    capacity 0 -> 0; requested > capacity -> 0.
  - Nonzero defaults for request-less containers: 100 milliCPU, 200MiB
    (priorities.go:53-54, getNonzeroRequests:58) — applied per-container,
    and an explicit request of 0 stays 0.
  - LeastRequested final score int((cpu_score + mem_score) / 2)
    (priorities.go:112).
  - BalancedResourceAllocation: float fractions, >= 1 on either axis -> 0,
    else int(10 - abs(diff) * 10) (priorities.go:181-242).
  - SelectorSpread counts matching pods per node INCLUDING unassigned pods
    (their count lands under node "" and participates in maxCount,
    selector_spreading.go:80-97); score = int(10 * (max-count)/max).
  - ServiceAntiAffinity: unlabeled nodes always score 0
    (selector_spreading.go:188-191).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..core import labels as labelspkg
from ..core import types as api
from .api import HostPriority
from .predicates import _capacity as _cap_resource
from .predicates import map_pods_to_machines

DEFAULT_MILLI_CPU_REQUEST = 100                 # ref: priorities.go:53
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024      # ref: priorities.go:54


def calculate_score(requested: int, capacity: int) -> int:
    """(ref: priorities.go:33 calculateScore — integer division!)"""
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return ((capacity - requested) * 10) // capacity


def get_nonzero_requests(requests: Dict[str, api.Quantity]) -> Tuple[int, int]:
    """(ref: priorities.go:58 getNonzeroRequests — absent key defaults,
    explicit zero stays zero)"""
    cpu = requests["cpu"].milli if "cpu" in requests else DEFAULT_MILLI_CPU_REQUEST
    mem = requests["memory"].value if "memory" in requests else DEFAULT_MEMORY_REQUEST
    return cpu, mem


def _nonzero_totals(pod: api.Pod, pods: Sequence[api.Pod]) -> Tuple[int, int]:
    total_cpu = 0
    total_mem = 0
    for existing in pods:
        for c in existing.spec.containers:
            cpu, mem = get_nonzero_requests(c.resources.requests)
            total_cpu += cpu
            total_mem += mem
    for c in pod.spec.containers:
        cpu, mem = get_nonzero_requests(c.resources.requests)
        total_cpu += cpu
        total_mem += mem
    return total_cpu, total_mem


def _cap(node: api.Node, resource: str) -> int:
    return _cap_resource(node, resource)


def calculate_resource_occupancy(pod: api.Pod, node: api.Node,
                                 pods: Sequence[api.Pod]) -> HostPriority:
    """(ref: priorities.go:77 calculateResourceOccupancy)"""
    total_cpu, total_mem = _nonzero_totals(pod, pods)
    cpu_score = calculate_score(total_cpu, _cap(node, "cpu"))
    mem_score = calculate_score(total_mem, _cap(node, "memory"))
    return HostPriority(node.metadata.name, (cpu_score + mem_score) // 2)


def least_requested_priority(pod: api.Pod, pod_lister,
                             node_lister) -> List[HostPriority]:
    """(ref: priorities.go:118 LeastRequestedPriority)"""
    nodes = node_lister.list()
    pods_by_machine = map_pods_to_machines(pod_lister)
    return [calculate_resource_occupancy(
                pod, n, pods_by_machine.get(n.metadata.name, []))
            for n in nodes]


def calculate_balanced_resource_allocation(pod: api.Pod, node: api.Node,
                                           pods: Sequence[api.Pod]
                                           ) -> HostPriority:
    """(ref: priorities.go:198 calculateBalancedResourceAllocation)"""
    total_cpu, total_mem = _nonzero_totals(pod, pods)
    cpu_fraction = _fraction(total_cpu, _cap(node, "cpu"))
    mem_fraction = _fraction(total_mem, _cap(node, "memory"))
    if cpu_fraction >= 1 or mem_fraction >= 1:
        score = 0
    else:
        diff = abs(cpu_fraction - mem_fraction)
        score = int(10 - diff * 10)
    return HostPriority(node.metadata.name, score)


def _fraction(requested: int, capacity: int) -> float:
    if capacity == 0:
        return 1.0
    return requested / capacity


def balanced_resource_allocation(pod: api.Pod, pod_lister,
                                 node_lister) -> List[HostPriority]:
    """(ref: priorities.go:181 BalancedResourceAllocation)"""
    nodes = node_lister.list()
    pods_by_machine = map_pods_to_machines(pod_lister)
    return [calculate_balanced_resource_allocation(
                pod, n, pods_by_machine.get(n.metadata.name, []))
            for n in nodes]


def new_node_label_priority(label: str, presence: bool):
    """(ref: priorities.go:148 CalculateNodeLabelPriority — 0 or 10)"""
    def calculate_node_label_priority(pod, pod_lister, node_lister):
        out = []
        for node in node_lister.list():
            exists = label in node.metadata.labels
            success = (exists and presence) or (not exists and not presence)
            out.append(HostPriority(node.metadata.name, 10 if success else 0))
        return out
    return calculate_node_label_priority


def equal_priority(pod: api.Pod, pod_lister, node_lister) -> List[HostPriority]:
    """(ref: generic_scheduler.go:227 EqualPriority — everyone scores 1)"""
    return [HostPriority(n.metadata.name, 1) for n in node_lister.list()]


# ----------------------------------------------------------- spreading

class SelectorSpread:
    """(ref: selector_spreading.go:28-114 SelectorSpread)"""

    def __init__(self, service_lister, controller_lister=None):
        self.service_lister = service_lister
        self.controller_lister = controller_lister

    def calculate_spread_priority(self, pod: api.Pod, pod_lister,
                                  node_lister) -> List[HostPriority]:
        selectors: List[labelspkg.Selector] = []
        if self.service_lister is not None:
            for svc in self.service_lister.get_pod_services(pod):
                selectors.append(labelspkg.selector_from_set(svc.spec.selector))
        if self.controller_lister is not None:
            for rc in self.controller_lister.get_pod_controllers(pod):
                selectors.append(labelspkg.selector_from_set(rc.spec.selector))

        ns_pods: List[api.Pod] = []
        if selectors:
            ns_pods = [p for p in pod_lister.list(labelspkg.everything())
                       if p.metadata.namespace == pod.metadata.namespace]

        counts: Dict[str, int] = {}
        max_count = 0
        for p in ns_pods:
            if any(sel.matches(p.metadata.labels) for sel in selectors):
                host = p.spec.node_name  # unassigned pods count under ""
                counts[host] = counts.get(host, 0) + 1
                max_count = max(max_count, counts[host])

        out = []
        for node in node_lister.list():
            score = 10.0
            if max_count > 0:
                score = 10 * (max_count - counts.get(node.metadata.name, 0)) / max_count
            out.append(HostPriority(node.metadata.name, int(score)))
        return out


class ServiceAntiAffinity:
    """Spread a service's pods across values of a node label — zones
    (ref: selector_spreading.go:117-196 ServiceAntiAffinity)."""

    def __init__(self, service_lister, label: str):
        self.service_lister = service_lister
        self.label = label

    def calculate_anti_affinity_priority(self, pod: api.Pod, pod_lister,
                                         node_lister) -> List[HostPriority]:
        ns_service_pods: List[api.Pod] = []
        services = self.service_lister.get_pod_services(pod)
        if services:
            sel = labelspkg.selector_from_set(services[0].spec.selector)
            ns_service_pods = [p for p in pod_lister.list(sel)
                               if p.metadata.namespace == pod.metadata.namespace]

        labeled: Dict[str, str] = {}
        other: List[str] = []
        for node in node_lister.list():
            if self.label in node.metadata.labels:
                labeled[node.metadata.name] = node.metadata.labels[self.label]
            else:
                other.append(node.metadata.name)

        pod_counts: Dict[str, int] = {}
        for p in ns_service_pods:
            value = labeled.get(p.spec.node_name)
            if value is None:
                continue
            pod_counts[value] = pod_counts.get(value, 0) + 1

        num_service_pods = len(ns_service_pods)
        out = []
        for node_name, value in labeled.items():
            score = 10.0
            if num_service_pods > 0:
                score = 10 * (num_service_pods - pod_counts.get(value, 0)) / num_service_pods
            out.append(HostPriority(node_name, int(score)))
        for node_name in other:
            out.append(HostPriority(node_name, 0))
        return out
