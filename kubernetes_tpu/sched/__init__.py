from .api import HostPriority, Policy
from .generic import FitError, GenericScheduler, NoNodesAvailable
