"""The scheduler control loop.

Reference: plugin/pkg/scheduler/scheduler.go:110-165 — `scheduleOne` is
strictly serial: NextPod (blocking FIFO pop) -> rate limit -> Schedule ->
Binding POST under the modeler lock -> AssumePod; errors go to the Error
func (backoff + requeue). Metrics names match metrics/metrics.go:30-80.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

from ..core import types as api
from ..utils.metrics import MetricsRegistry, global_metrics


class SchedulerConfig:
    def __init__(self, algorithm, next_pod: Callable[[], Optional[api.Pod]],
                 binder, node_lister, modeler,
                 error: Callable[[api.Pod, Exception], None],
                 recorder=None, bind_pods_rate_limiter=None,
                 metrics: Optional[MetricsRegistry] = None,
                 on_assume: Optional[Callable[[api.Pod], None]] = None):
        self.algorithm = algorithm
        self.next_pod = next_pod
        self.binder = binder
        self.node_lister = node_lister
        self.modeler = modeler
        self.error = error
        self.recorder = recorder
        self.bind_pods_rate_limiter = bind_pods_rate_limiter
        self.metrics = metrics or global_metrics
        # extra assume observer (the mixed-mode device state joins the
        # modeler at the AssumePod moment)
        self.on_assume = on_assume


class Scheduler:
    """(ref: scheduler.go:80 Scheduler + Run/scheduleOne)"""

    def __init__(self, config: SchedulerConfig):
        self.config = config
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run(self) -> "Scheduler":
        self._thread = threading.Thread(target=self._loop, name="scheduler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                busy = self.schedule_one()
            except Exception:
                # pod-level failures are routed inside schedule_one;
                # anything escaping would otherwise kill the daemon
                # thread and stall scheduling cluster-wide. Treat the
                # round as idle so a persistent failure backs off
                # instead of busy-spinning the log.
                logger.exception("schedule_one failed")
                busy = False
            if not busy:
                # no pod this round (timeout or closed queue): back off a
                # touch so a closed factory doesn't turn this into a busy-spin
                self._stop.wait(0.01)

    def schedule_one(self) -> bool:
        """(ref: scheduler.go:120 scheduleOne). Returns True if a pod was
        processed."""
        c = self.config
        pod = c.next_pod()
        if pod is None:  # queue closed / timed out — loop re-checks stop
            return False
        if c.bind_pods_rate_limiter is not None:
            c.bind_pods_rate_limiter.accept()
        start = time.monotonic()
        try:
            dest = c.algorithm.schedule(pod, c.node_lister)
        except Exception as e:
            c.metrics.observe("scheduling_algorithm_latency_microseconds",
                              (time.monotonic() - start) * 1e6)
            # ref: E2eSchedulingLatency is deferred, so it observes failed
            # attempts too
            c.metrics.observe("scheduler_e2e_scheduling_latency_microseconds",
                              (time.monotonic() - start) * 1e6)
            try:
                if c.recorder is not None:
                    c.recorder.eventf(pod, "Warning", "FailedScheduling",
                                      str(e))
            finally:
                # the requeue must not be lost to a recorder failure —
                # the pod is already consumed from the FIFO
                c.error(pod, e)
            return True
        c.metrics.observe("scheduling_algorithm_latency_microseconds",
                          (time.monotonic() - start) * 1e6)

        binding = api.Binding(
            metadata=api.ObjectMeta(namespace=pod.metadata.namespace,
                                    name=pod.metadata.name),
            target=api.ObjectReference(kind="Node", name=dest))

        def bind_and_assume():
            bind_start = time.monotonic()
            try:
                c.binder.bind(binding)
            except Exception as e:
                c.metrics.observe("binding_latency_microseconds",
                                  (time.monotonic() - bind_start) * 1e6)
                if c.recorder is not None:
                    c.recorder.eventf(pod, "Normal", "FailedScheduling",
                                      f"Binding rejected: {e}")
                c.error(pod, e)
                return
            c.metrics.observe("binding_latency_microseconds",
                              (time.monotonic() - bind_start) * 1e6)
            if c.recorder is not None:
                c.recorder.eventf(
                    pod, "Normal", "Scheduled",
                    f"Successfully assigned {pod.metadata.name} to {dest}")
            from dataclasses import replace
            assumed = replace(pod, spec=replace(pod.spec, node_name=dest))
            # the bind already landed: a failure in the assume tail must
            # not escape and kill the scheduler thread — the watch echo
            # re-syncs whatever the caches missed
            try:
                c.modeler.assume_pod(assumed)
                if c.on_assume is not None:
                    c.on_assume(assumed)
            except Exception:
                logger.exception("assume after bind failed for %s",
                                 pod.metadata.name)

        c.modeler.locked_action(bind_and_assume)
        c.metrics.observe("scheduler_e2e_scheduling_latency_microseconds",
                          (time.monotonic() - start) * 1e6)
        return True
