"""Mixed-mode scheduling: device-probed predicates/priorities + HTTP
extenders on the survivors.

The middle rung of the fast-path ladder (full batch > mixed > serial):
a policy with extenders can't run the all-device batch loop — the
extender RPC sits between filter and select (extender.go:95) — but the
O(nodes x predicates) inner math still belongs on device. Each pod gets
one probe (BatchEngine.probe over the incremental state), the extender
chain filters/scores the surviving nodes over HTTP, and selection uses
the reference's ordering with the engine's deterministic tie-break.

Pods the incremental encoder can't express (inter-pod affinity terms)
take a per-pod serial fallback — the provable-fallback contract at pod
granularity instead of condemning the whole policy to the serial loop.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..core import types as api
from .api import HostPriority
from .device import BatchEngine
from .device.incremental import IncrementalEncoder, NeedsFullEncode
from .generic import FitError, GenericScheduler, sort_host_priorities


class DeviceAssistedAlgorithm:
    """Drop-in for the serial control loop's `algorithm` seam
    (scheduler_interface.go ScheduleAlgorithm), device-backed."""

    def __init__(self, factory, engine: BatchEngine,
                 extenders: Sequence,
                 serial_fallback: Optional[GenericScheduler] = None):
        self.factory = factory
        self.engine = engine
        self.extenders = list(extenders)
        self.serial_fallback = serial_fallback
        self.inc = IncrementalEncoder().attach(factory)

    def assume(self, pod: api.Pod) -> None:
        """Wired to SchedulerConfig.on_assume: the bound pod joins the
        persistent device state at the modeler-assume moment."""
        self.inc.assume(pod)

    def schedule(self, pod: api.Pod, node_lister) -> str:
        try:
            enc = self.inc.encode_tile(
                [pod], self.factory.service_lister.list(),
                self.factory.controller_lister.list())
        except NeedsFullEncode:
            if self.serial_fallback is None:
                raise
            return self.serial_fallback.schedule(pod, node_lister)
        mask, total = self.engine.probe(enc)
        mask, total = mask[0], total[0]
        # one pass over the candidate nodes (the Node objects are needed
        # for the extender wire format anyway); slots come from the
        # encoder's live table — stable for a node's life — instead of
        # rebuilding O(n_cap) dicts per pod
        slot = self.inc.node_slot
        n_lanes = len(mask)
        survivors: List[api.Node] = []
        for n in node_lister.list():
            i = slot.get(n.metadata.name)
            # bounds guard: a node added after encode_tile may hold a
            # slot past this probe's arrays (table growth); it wasn't in
            # the snapshot, so it simply isn't a candidate this pod
            if i is not None and i < n_lanes and mask[i]:
                survivors.append(n)
        if survivors:
            for extender in self.extenders:
                survivors = extender.filter(pod, survivors)
                if not survivors:
                    break
        if not survivors:
            raise FitError(pod, {})

        # a non-conformant extender may return hosts it was never sent
        # (the serial path tolerates them, extender.py decodes verbatim);
        # score unknowns at device 0 rather than KeyError-looping the pod
        combined = {}
        for n in survivors:
            i = slot.get(n.metadata.name)
            combined[n.metadata.name] = (
                int(total[i]) if i is not None and i < n_lanes else 0)
        for extender in self.extenders:
            try:
                scores, weight = extender.prioritize(pod, survivors)
            except Exception:
                continue  # prioritize errors are ignored
                # (generic_scheduler.go:197-199)
            for entry in scores:
                if entry.host in combined:
                    combined[entry.host] += entry.score * weight
        ordered = sort_host_priorities(
            [HostPriority(host, score) for host, score in combined.items()])
        # deterministic tie-break: first in reference order (the engine's
        # documented divergence from rand.Int()%len)
        return ordered[0].host
