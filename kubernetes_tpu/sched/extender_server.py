"""Scheduler extender — the HTTP sidecar SERVER (the TPU seam, serving).

The whole point of the north star (SURVEY.md §7 step 5): serve the
reference's extender wire protocol so the TPU scoring backend bolts onto
a *stock* kube-scheduler unchanged — the stock scheduler POSTs
ExtenderArgs and our device engine answers Filter / Prioritize.

Reference: plugin/pkg/scheduler/extender.go:38-172 (the client that will
call us), api/types.go:114-158 (wire types), and the server shape in
test/integration/extender_test.go:66-103 (Extender.serveHTTP) +
docs/design/scheduler_extender.md. Routes:

    POST {prefix}/{apiVersion}/{filterVerb}
        body: ExtenderArgs{"pod": <Pod>, "nodes": <NodeList>}
        resp: ExtenderFilterResult{"nodes": <NodeList>, "error": str}
    POST {prefix}/{apiVersion}/{prioritizeVerb}
        body: ExtenderArgs
        resp: HostPriorityList [{"host": str, "score": int}]

Filter errors are reported in-band (the caller fails the pod); prioritize
errors yield an empty list (the caller ignores prioritize failures,
generic_scheduler.go:197-199 / extender_test.go:92-95).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Sequence, Tuple

from ..core import types as api
from ..core.scheme import Scheme, default_scheme
from .api import HostPriority

# fn(pod, node) -> bool            (extender_test.go:53 fitPredicate)
FitPredicate = Callable[[api.Pod, api.Node], bool]
# fn(pod, nodes) -> [HostPriority] (extender_test.go:54 priorityFunc)
PriorityFunc = Callable[[api.Pod, Sequence[api.Node]], List[HostPriority]]


class CallableBackend:
    """Arbitrary predicates/prioritizers behind the wire protocol — the
    reference integration test's Extender struct (extender_test.go:60-147).
    """

    def __init__(self, predicates: Sequence[FitPredicate] = (),
                 prioritizers: Sequence[Tuple[PriorityFunc, int]] = ()):
        self.predicates = list(predicates)
        self.prioritizers = list(prioritizers)

    def filter(self, pod: api.Pod,
               nodes: Sequence[api.Node]) -> List[api.Node]:
        """(ref: extender_test.go:104 Extender.Filter)"""
        filtered = []
        for node in nodes:
            if all(pred(pod, node) for pred in self.predicates):
                filtered.append(node)
        return filtered

    def prioritize(self, pod: api.Pod,
                   nodes: Sequence[api.Node]) -> List[HostPriority]:
        """(ref: extender_test.go:126 Extender.Prioritize)"""
        combined = {}
        for func, weight in self.prioritizers:
            if weight == 0:
                continue
            for entry in func(pod, nodes):
                combined[entry.host] = combined.get(entry.host, 0) \
                    + entry.score * weight
        return [HostPriority(h, s) for h, s in combined.items()]


class DeviceBackend:
    """The TPU backend behind the extender seam: predicates answered as a
    device mask, priorities as device score totals (BatchEngine.probe).

    `state_provider()` supplies the cluster context the wire format does
    not carry (existing pods / services / RCs — a deployed sidecar feeds
    this from its own reflectors against the apiserver); candidate nodes
    always come from the request, per the protocol."""

    def __init__(self, weights=None, policy=None,
                 state_provider: Optional[Callable] = None):
        from .device import BatchEngine
        from .device.engine import DEFAULT_WEIGHTS
        self.engine = BatchEngine(weights or DEFAULT_WEIGHTS, policy=policy)
        self.state_provider = state_provider or (lambda: ([], [], []))

    def _encode(self, pod: api.Pod, nodes: Sequence[api.Node]):
        from .device import ClusterSnapshot, encode_snapshot
        existing, services, controllers = self.state_provider()
        snap = ClusterSnapshot(
            nodes=list(nodes), existing_pods=list(existing),
            services=list(services), controllers=list(controllers),
            pending_pods=[pod])
        return encode_snapshot(snap, policy=self.engine.policy)

    def filter(self, pod: api.Pod,
               nodes: Sequence[api.Node]) -> List[api.Node]:
        # mask-only: rides the Pallas predicate kernel when the
        # encoding qualifies (engine.filter_masks)
        enc = self._encode(pod, nodes)
        mask = self.engine.filter_masks(enc)[0]
        by_name = {n.metadata.name: n for n in nodes}
        return [by_name[enc.node_names[i]]
                for i in range(len(enc.node_names))
                if mask[i] and enc.node_names[i] in by_name]

    def prioritize(self, pod: api.Pod,
                   nodes: Sequence[api.Node]) -> List[HostPriority]:
        enc = self._encode(pod, nodes)
        _mask, total = self.engine.probe(enc)
        total = total[0]
        wanted = {n.metadata.name for n in nodes}
        return [HostPriority(enc.node_names[i], int(total[i]))
                for i in range(len(enc.node_names))
                if enc.node_names[i] in wanted]


class ExtenderServer:
    """HTTP sidecar serving one backend over the extender wire protocol."""

    def __init__(self, backend, filter_verb: str = "filter",
                 prioritize_verb: str = "prioritize",
                 api_version: str = "v1", host: str = "127.0.0.1",
                 port: int = 0, scheme: Scheme = default_scheme):
        self.backend = backend
        self.filter_verb = filter_verb
        self.prioritize_verb = prioritize_verb
        self.api_version = api_version
        self.scheme = scheme
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                server.handle(self)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        """Drops into ExtenderConfig.url_prefix."""
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ExtenderServer":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # ----------------------------------------------------------- dispatch

    def _decode_args(self, h) -> Tuple[api.Pod, List[api.Node]]:
        length = int(h.headers.get("Content-Length") or 0)
        args = json.loads(h.rfile.read(length))
        pod = self.scheme.decode_dict({**args["pod"], "kind": "Pod"})
        items = (args.get("nodes") or {}).get("items") or []
        nodes = [self.scheme.decode_dict({**n, "kind": "Node"})
                 for n in items]
        return pod, nodes

    def handle(self, h: BaseHTTPRequestHandler) -> None:
        # verb dispatch by path suffix, as the reference test server does
        # (extender_test.go:80 strings.Contains(req.URL.Path, filter))
        leaf = h.path.rstrip("/").rsplit("/", 1)[-1]
        try:
            if leaf == self.filter_verb:
                payload = self._handle_filter(h)
            elif leaf == self.prioritize_verb:
                payload = self._handle_prioritize(h)
            else:
                return self._send(h, 404, {"error": f"unknown verb {leaf!r}"})
            self._send(h, 200, payload)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _handle_filter(self, h) -> dict:
        try:
            pod, nodes = self._decode_args(h)
            filtered = self.backend.filter(pod, nodes)
            return {"nodes": self.scheme.encode_list("Node", filtered),
                    "error": ""}
        except Exception as e:  # in-band error fails the pod (extender.go:95)
            return {"nodes": {"kind": "NodeList", "items": []},
                    "error": str(e) or repr(e)}

    def _handle_prioritize(self, h) -> list:
        try:
            pod, nodes = self._decode_args(h)
            return [{"host": p.host, "score": p.score}
                    for p in self.backend.prioritize(pod, nodes)]
        except Exception:  # prioritize errors are ignored by the caller
            return []

    def _send(self, h, code: int, payload) -> None:
        raw = json.dumps(payload).encode()
        h.send_response(code)
        h.send_header("Content-Type", "application/json")
        h.send_header("Content-Length", str(len(raw)))
        h.end_headers()
        h.wfile.write(raw)
