"""The lint baseline: explicit, counted allowances for accepted sites.

`baseline.toml` is an array of `[[allow]]` tables; each names one
(file, rule, site, symbol) violation identity, how many occurrences are
accepted there, and WHY. The runner reconciles the tree against it both
ways:

  - more occurrences than allowed  -> new violations, hard error
  - fewer occurrences than allowed -> baseline drift, also an error:
    a fixed violation must take its allowance with it, or the
    allowlist silently becomes a grant for future regressions.

The parser is a deliberate TOML subset (this interpreter predates
tomllib, and the lint suite takes no dependencies): `[[allow]]`
headers, `key = "string" | integer` pairs, comments, blank lines.
Anything else is a parse error — the baseline is machine-written
prose, not a config language.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

Key = Tuple[str, str, str, str]  # (path, rule, site, symbol)


class BaselineError(Exception):
    pass


@dataclass
class Baseline:
    #: violation identity -> accepted occurrence count
    allow: Dict[Key, int] = field(default_factory=dict)
    #: identity -> reason string (kept for reporting)
    reasons: Dict[Key, str] = field(default_factory=dict)

    def reconcile(self, violations) -> Tuple[list, List[str]]:
        """-> (new violations beyond allowance, stale entry labels)."""
        counts: Dict[Key, int] = {}
        by_key: Dict[Key, list] = {}
        for v in violations:
            counts[v.key()] = counts.get(v.key(), 0) + 1
            by_key.setdefault(v.key(), []).append(v)
        new = []
        for key, vs in sorted(by_key.items()):
            allowed = self.allow.get(key, 0)
            if len(vs) > allowed:
                # the tail occurrences are the unallowed ones (sorted
                # by line already) — deterministic either way, and the
                # message names the full count
                new.extend(vs[allowed:])
        stale = []
        for key, allowed in sorted(self.allow.items()):
            actual = counts.get(key, 0)
            if actual < allowed:
                path, rule, site, symbol = key
                stale.append(
                    f"{path}: [{rule}] {site}: {symbol} — baseline "
                    f"allows {allowed}, tree has {actual}; remove the "
                    f"fixed allowance from lint/baseline.toml")
        return new, stale


_REQUIRED = ("file", "rule", "site", "symbol")


def parse_baseline(text: str, origin: str = "<baseline>") -> Baseline:
    bl = Baseline()
    entry: Dict[str, object] = {}
    entry_line = 0

    def commit() -> None:
        if not entry:
            return
        missing = [k for k in _REQUIRED if k not in entry]
        if missing:
            raise BaselineError(
                f"{origin}:{entry_line}: [[allow]] entry missing "
                f"{missing}")
        key: Key = (str(entry["file"]), str(entry["rule"]),
                    str(entry["site"]), str(entry["symbol"]))
        if key in bl.allow:
            raise BaselineError(
                f"{origin}:{entry_line}: duplicate allowance for {key}")
        count = entry.get("count", 1)
        if not isinstance(count, int) or count < 1:
            raise BaselineError(
                f"{origin}:{entry_line}: count must be a positive "
                f"integer, got {count!r}")
        bl.allow[key] = count
        bl.reasons[key] = str(entry.get("reason", ""))

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[allow]]":
            commit()
            entry = {}
            entry_line = lineno
            continue
        if "=" in line and entry_line:
            key, _, value = line.partition("=")
            key = key.strip()
            value = value.strip()
            if value.startswith('"'):
                end = value.find('"', 1)
                if end < 0:
                    raise BaselineError(
                        f"{origin}:{lineno}: unterminated string")
                entry[key] = value[1:end]
            else:
                value = value.split("#", 1)[0].strip()
                try:
                    entry[key] = int(value)
                except ValueError:
                    raise BaselineError(
                        f"{origin}:{lineno}: unsupported value "
                        f"{value!r} (strings and integers only)")
            continue
        raise BaselineError(
            f"{origin}:{lineno}: unsupported syntax {line!r} (this "
            f"baseline is a TOML subset: [[allow]] tables of "
            f"string/int pairs)")
    commit()
    return bl


def load_baseline(path: str) -> Baseline:
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except FileNotFoundError:
        return Baseline()
    return parse_baseline(text, origin=path)
