"""CLI: `python -m kubernetes_tpu.lint [--json] [--root DIR]
[--baseline FILE]`.

Exit status: 0 when the tree is clean against the baseline, 1 when
there are new violations or stale baseline entries — the same verdict
the tier-1 gate (tests/test_lint.py) enforces. `--json` prints one
machine-readable report line (bench.py records the wall time from it).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import DEFAULT_BASELINE, run_lint


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.lint",
        description="orchlint: AST invariant lint (determinism, "
                    "lock-discipline, jax-hygiene, api-idempotency)")
    ap.add_argument("--json", action="store_true",
                    help="one JSON report line instead of text")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected from the "
                         "installed package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: lint/baseline.toml)")
    args = ap.parse_args(argv)

    report = run_lint(root=args.root, baseline_path=args.baseline)
    if args.json:
        print(json.dumps(report.to_dict(), sort_keys=True))
    else:
        for v in report.new:
            print(v.render())
        for s in report.stale:
            print(f"stale baseline: {s}")
        print(f"orchlint: {report.files_scanned} files, "
              f"{len(report.violations)} known site(s), "
              f"{len(report.new)} new violation(s), "
              f"{len(report.stale)} stale baseline entr(ies) "
              f"in {report.seconds:.2f}s -> "
              f"{'OK' if report.ok else 'FAIL'}")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
